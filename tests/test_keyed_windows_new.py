"""Keyed (inside-partition) externalTime, timeLength, and delay windows —
per-key instances of ExternalTimeWindowProcessor / TimeLengthWindowProcessor
/ DelayWindowProcessor (partitions give every key its own window)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


STREAM = "@app:playback define stream S (sym string, v int);\n"


def test_keyed_external_time_sliding_sum():
    # per-key clock: A's rows only expire when A gets new events
    m, rt, c = build("""@app:playback define stream S (sym string, ets long, v int);
        partition with (sym of S) begin
        from S#window.externalTime(ets, 1 sec)
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 1000, 10])
    h.send(1200, ["B", 1200, 100])
    h.send(1500, ["A", 1500, 20])     # A window: 10+20
    h.send(2300, ["A", 2300, 30])     # 1000+1000<=2300: row 10 expires -> 20+30
    h.send(5000, ["B", 5000, 1])      # B: row 100 expired -> 1
    m.shutdown()
    got = {}
    for e in c.events:
        got[e.data[0]] = e.data[1]
    by_seq = [tuple(e.data) for e in c.events]
    assert ("A", 30) in by_seq       # after first A
    assert by_seq[-2:] == [("A", 50), ("B", 1)] or got == {"A": 50, "B": 1}


def test_keyed_external_time_expired_keep_timestamps():
    m, rt, c = build("""@app:playback define stream S (sym string, ets long, v int);
        partition with (sym of S) begin
        from S#window.externalTime(ets, 1 sec)
        select sym, v insert all events into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 1000, 1])
    h.send(2500, ["A", 2500, 2])     # expires row 1
    m.shutdown()
    # arrival, expiry (original timestamp — ExternalTimeWindowProcessor
    # keeps event time), then the new current
    got = [(e.timestamp, tuple(e.data)) for e in c.events]
    assert got == [(1000, ("A", 1)), (1000, ("A", 1)), (2500, ("A", 2))]


def test_keyed_external_time_nonmonotone_clock_degrades_gracefully():
    # a backwards external timestamp must not corrupt expiry: the per-key
    # running max (segmented cummax) treats the stalled clock as "no
    # advance", mirroring the unkeyed stage and the reference's behavior
    # of never expiring on a clock that goes backwards
    m, rt, c = build("""@app:playback define stream S (sym string, ets long, v int);
        partition with (sym of S) begin
        from S#window.externalTime(ets, 1 sec)
        select sym, sum(v) as total insert into OutStream; end;
    """)
    from siddhi_tpu.core.event import Event
    h = rt.get_input_handler("S")
    # one batch, A's clock goes 2000 -> 1500 (backwards) -> 3500
    h.send([Event(timestamp=2000, data=["A", 2000, 1]),
            Event(timestamp=2100, data=["A", 1500, 2]),
            Event(timestamp=2200, data=["B", 9000, 100]),
            Event(timestamp=2300, data=["A", 3500, 4])])
    m.shutdown()
    a_totals = [e.data[1] for e in c.events if e.data[0] == "A"]
    # rows 1 and 2 expire exactly once each (at clock 3500: 2000+1000 and
    # 1500+1000 are both covered); no arbitrary expiry from the backwards
    # tick — final A total is 4, never negative or duplicated
    assert a_totals[-1] == 4
    assert all(t >= 0 for t in a_totals)
    b_totals = [e.data[1] for e in c.events if e.data[0] == "B"]
    assert b_totals == [100]


def test_keyed_timelength_evicts_by_count_and_time():
    m, rt, c = build(STREAM + """
        partition with (sym of S) begin
        from S#window.timeLength(10 sec, 2)
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 1])
    h.send(1100, ["A", 2])      # A live: 1,2
    h.send(1200, ["A", 4])      # count cap 2: evict 1 -> total 6
    h.send(1300, ["B", 100])    # B independent
    h.send(1400, ["A", 8])      # evict 2 -> total 12
    m.shutdown()
    last = {}
    for e in c.events:
        last[e.data[0]] = e.data[1]
    assert last == {"A": 12, "B": 100}


def test_keyed_timelength_time_expiry_still_works():
    m, rt, c = build(STREAM + """
        partition with (sym of S) begin
        from S#window.timeLength(1 sec, 10)
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 5])
    h.send(2500, ["A", 7])      # row 5 expired by time
    m.shutdown()
    assert [tuple(e.data) for e in c.events][-1] == ("A", 7)


def test_keyed_batch_window_per_key_chunks():
    from siddhi_tpu.core.event import Event

    m, rt, c = build(STREAM + """
        partition with (sym of S) begin
        from S#window.batch()
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    # chunk 1: A{1,2}, B{10}
    h.send([Event(timestamp=1000, data=["A", 1]),
            Event(timestamp=1000, data=["A", 2]),
            Event(timestamp=1000, data=["B", 10])])
    # chunk 2: A{5} — replaces A's batch; B untouched
    h.send(1100, ["A", 5])
    m.shutdown()
    rows = [tuple(e.data) for e in c.events]
    # batch-mode sums per flush: chunk1 A->3, B->10; chunk2 A->5
    assert rows[-1] == ("A", 5)
    assert ("A", 3) in rows and ("B", 10) in rows


def test_keyed_lengthbatch_multi_key_chunk_emits_every_key():
    # regression: a single chunk flushing several keys' batches must emit
    # one row per key, not just the chunk's last row
    from siddhi_tpu.core.event import Event

    m, rt, c = build(STREAM + """
        partition with (sym of S) begin
        from S#window.lengthBatch(2)
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=1000, data=["A", 1]),
            Event(timestamp=1000, data=["A", 2]),
            Event(timestamp=1000, data=["B", 10]),
            Event(timestamp=1000, data=["B", 20])])
    m.shutdown()
    rows = sorted(tuple(e.data) for e in c.events)
    assert rows == [("A", 3), ("B", 30)]


def test_keyed_batch_window_join_side_probes_latest_chunk():
    m, rt, c = build("""
        define stream S (sym string, v int);
        define stream R (sym string, w int);
        partition with (sym of S, sym of R) begin
        from S#window.batch() join R#window.length(4)
             on S.sym == R.sym
        select S.sym as sym, S.v as v, R.w as w insert into OutStream; end;
    """)
    rt.get_input_handler("S").send(["A", 1])
    rt.get_input_handler("R").send(["A", 7])   # probes A's latest batch {1}
    m.shutdown()
    assert ("A", 1, 7) in [tuple(e.data) for e in c.events]


def test_keyed_hopping_window_per_key_phase():
    m, rt, c = build(STREAM + """
        partition with (sym of S) begin
        from S#window.hopping(3 sec, 1 sec)
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 1])        # A arms: first hop at 2000
    h.send(1500, ["B", 10])       # B arms: first hop at 2500
    h.send(2100, ["A", 2])        # A's hop at 2000 fired via timer/arrival
    h.send(2600, ["B", 20])       # B's hop fired
    h.send(3100, ["A", 4])        # A's 2nd hop (3000): trailing {1,2}
    m.shutdown()
    rows = [tuple(e.data) for e in c.events]
    assert ("A", 1) in rows       # A's first hop: {1}
    assert ("B", 10) in rows      # B's first hop: {10}
    assert ("A", 3) in rows       # A's second hop: {1,2}


def test_keyed_delay_releases_after_time():
    m, rt, c = build(STREAM + """
        partition with (sym of S) begin
        from S#window.delay(1 sec)
        select sym, v insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 1])
    h.send(1100, ["B", 2])
    assert c.events == []        # still held
    h.send(2200, ["A", 3])       # clock passes 2000: A1 and B2 release
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    # A1 and B2 released once the clock passed their +1s deadlines; A3's
    # deadline (3200) never arrives before shutdown, so it stays held
    assert got == [("A", 1), ("B", 2)]


def test_keyed_session_with_latency_per_key_host_instances():
    m, rt, c = build(STREAM + """
        partition with (sym of S) begin
        from S#window.session(2 sec, sym, 1 sec)
        select sym, v insert all events into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    h.send(3500, ["u2", 9])     # u1's session parked (latency hold)
    h.send(3700, ["u1", 2])     # late event revives u1
    h.send(9000, ["u2", 0])     # everything expires
    m.shutdown()
    u1 = [tuple(e.data) for e in c.events if e.data[0] == "u1"]
    # both rows appear twice (CURRENT + one joint EXPIRED emission)
    assert u1.count(("u1", 1)) == 2 and u1.count(("u1", 2)) == 2
