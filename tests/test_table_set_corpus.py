"""Reference update-`set` corpus — scenarios from
``query/table/set/SetUpdate{,OrInsert}InMemoryTableTestCase.java``. The
reference smokes assert nothing; final table contents are pinned here via
on-demand queries."""

from siddhi_tpu import SiddhiManager


def build(query):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define stream UpdateStockStream (symbol string, price float, volume long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
    """ + query)
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])
    return m, rt


def rows(rt):
    return sorted((e.data[0], round(e.data[1], 2), e.data[2])
                  for e in rt.query("from StockTable select *"))


def test_set_all_columns():
    """SetUpdate test1 (:50-82): set every column from the trigger."""
    m, rt = build("""
        @info(name = 'query2')
        from UpdateStockStream
        update StockTable
        set StockTable.price = price, StockTable.symbol = symbol,
            StockTable.volume = volume
        on StockTable.symbol == symbol;
    """)
    rt.get_input_handler("UpdateStockStream").send(["IBM", 100.0, 200])
    assert ("IBM", 100.0, 200) in rows(rt)
    m.shutdown()


def test_set_subset_of_columns():
    """SetUpdate test2 (:84-115): a subset `set` leaves other columns."""
    m, rt = build("""
        @info(name = 'query2')
        from UpdateStockStream
        update StockTable
        set StockTable.price = price
        on StockTable.symbol == symbol;
    """)
    rt.get_input_handler("UpdateStockStream").send(["IBM", 100.0, 999])
    assert ("IBM", 100.0, 100) in rows(rt)     # volume untouched
    m.shutdown()


def test_set_constant_value():
    """SetUpdate test3 (:117-148): a constant assignment expression."""
    m, rt = build("""
        @info(name = 'query2')
        from UpdateStockStream
        update StockTable
        set StockTable.price = 10
        on StockTable.symbol == symbol;
    """)
    rt.get_input_handler("UpdateStockStream").send(["IBM", 100.0, 100])
    assert ("IBM", 10.0, 100) in rows(rt)
    m.shutdown()


def test_set_renamed_output_attribute():
    """SetUpdate test4 (:150-183): the assignment reads a projected
    (renamed) attribute."""
    m, rt = build("""
        @info(name = 'query2')
        from UpdateStockStream
        select symbol, price as newPrice
        update StockTable
        set StockTable.price = newPrice
        on StockTable.symbol == symbol;
    """)
    rt.get_input_handler("UpdateStockStream").send(["IBM", 100.0, 100])
    assert ("IBM", 100.0, 100) in rows(rt)
    m.shutdown()


def test_set_arithmetic_expression():
    """SetUpdate test5 (:185-...): arithmetic over a projected attribute."""
    m, rt = build("""
        @info(name = 'query2')
        from UpdateStockStream
        select symbol, price as newPrice
        update StockTable
        set StockTable.price = newPrice + 100
        on StockTable.symbol == symbol;
    """)
    rt.get_input_handler("UpdateStockStream").send(["IBM", 100.0, 100])
    assert ("IBM", 200.0, 100) in rows(rt)
    m.shutdown()


def test_set_update_or_insert_miss_inserts():
    """SetUpdateOrInsert shape: a non-matching trigger inserts the full
    row; a matching one applies only the set clause."""
    m, rt = build("""
        @info(name = 'query2')
        from UpdateStockStream
        update or insert into StockTable
        set StockTable.price = price
        on StockTable.symbol == symbol;
    """)
    u = rt.get_input_handler("UpdateStockStream")
    u.send(["FB", 33.0, 300])          # miss: full insert
    u.send(["IBM", 200.0, 999])        # hit: only price changes
    got = rows(rt)
    assert ("FB", 33.0, 300) in got
    assert ("IBM", 200.0, 100) in got
    m.shutdown()
