"""Multi-host: two REAL ``jax.distributed`` CPU processes form one cluster
and run ACTUAL query runtimes — the flagship group-by aggregation and a
partitioned NFA pattern — with their keyed state sharded over the global
mesh (``shard_query_step``), through the real host pump
(``InputHandler.send`` -> junction -> jitted step -> ``StreamCallback``).
Both processes must produce the single-process runtime's exact outputs.
This is the DCN-facing half of the comm backend (reference NCCL/MPI
transports -> jax.distributed + XLA collectives, SURVEY.md §2.13/§5.8).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

# Runs a SPMD worker: every process feeds IDENTICAL event sequences (the
# multi-controller contract — replicated jit inputs must agree), state is
# key-sharded across BOTH processes, outputs are pulled host-side (the
# sharded step replicates its OUT batch across processes; see
# parallel/mesh._out_shardings).
_WORKER = textwrap.dedent("""
    import json
    import os
    import sys

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/root/repo/.jax_cache")
    sys.path.insert(0, "/root/repo")

    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    # config-level platform reset: plugin platforms (the axon TPU tunnel)
    # override JAX_PLATFORMS at interpreter start, and jax.distributed
    # over the tunnel would hang (see parallel/mesh.force_host_devices)
    from siddhi_tpu.parallel.mesh import force_host_devices

    force_host_devices(2)
    print("worker: platform ready", file=sys.stderr, flush=True)
    from siddhi_tpu.parallel.distributed import (
        global_mesh,
        initialize_cluster,
        process_info,
    )

    initialize_cluster(coordinator_address=coord, num_processes=nproc,
                       process_id=pid)
    print("worker: cluster up", file=sys.stderr, flush=True)
    info = process_info()
    assert info["process_count"] == nproc, info
    assert info["global_devices"] == 2 * nproc, info

    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.parallel.mesh import shard_query_step

    class C(StreamCallback):
        def __init__(self):
            self.rows = []

        def receive(self, events):
            self.rows.extend([e.timestamp] + list(e.data) for e in events)

    results = {}

    # ---- flagship: group-by window aggregation, selector state [_, K]
    # sharded across the 2-process global mesh
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime('''
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(8)
        select symbol, avg(price) as ap, sum(volume) as tv
        group by symbol
        insert into Out;
    ''')
    c = C()
    rt.add_callback("Out", c)
    shard_query_step(rt.query_runtimes["q"], global_mesh())
    h = rt.get_input_handler("S")
    for i in range(96):
        h.send(1000 + i, [f"K{i % 24}", float(i % 13) + 0.5, int(i)])
    m.shutdown()
    results["flagship"] = c.rows

    # ---- partitioned NFA pattern over the same global mesh
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime('''
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
          select e1.v as v1, e2.v as v2
          insert into Out;
        end;
    ''')
    c2 = C()
    rt2.add_callback("Out", c2)
    shard_query_step(rt2.query_runtimes["q"], global_mesh())
    ha = rt2.get_input_handler("A")
    hb = rt2.get_input_handler("B")
    t = 1000
    for i in range(48):
        k = f"P{(i * 7) % 16}"
        va = float((i * 3) % 11)
        ha.send(t, [k, va])
        hb.send(t + 1, [k, va + (1.0 if i % 3 else -1.0)])
        t += 50
    m2.shutdown()
    results["nfa"] = c2.rows

    print(json.dumps(results), flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _single_process_expected():
    """The same two feeds against plain single-process runtimes."""
    from siddhi_tpu import SiddhiManager, StreamCallback

    class C(StreamCallback):
        def __init__(self):
            self.rows = []

        def receive(self, events):
            self.rows.extend([e.timestamp] + list(e.data) for e in events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(8)
        select symbol, avg(price) as ap, sum(volume) as tv
        group by symbol
        insert into Out;
    """)
    c = C()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    for i in range(96):
        h.send(1000 + i, [f"K{i % 24}", float(i % 13) + 0.5, int(i)])
    m.shutdown()

    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime("""
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
          select e1.v as v1, e2.v as v2
          insert into Out;
        end;
    """)
    c2 = C()
    rt2.add_callback("Out", c2)
    ha = rt2.get_input_handler("A")
    hb = rt2.get_input_handler("B")
    t = 1000
    for i in range(48):
        k = f"P{(i * 7) % 16}"
        va = float((i * 3) % 11)
        ha.send(t, [k, va])
        hb.send(t + 1, [k, va + (1.0 if i % 3 else -1.0)])
        t += 50
    m2.shutdown()
    return {"flagship": c.rows, "nfa": c2.rows}


def test_two_process_cluster_runs_real_queries():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=400)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    expected = _single_process_expected()
    assert len(expected["flagship"]) == 96
    assert len(expected["nfa"]) > 0
    for o in outs:
        payload = json.loads(o.strip().splitlines()[-1])
        assert payload["flagship"] == expected["flagship"]
        assert payload["nfa"] == expected["nfa"]
