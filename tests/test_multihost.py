"""Multi-host smoke: two REAL `jax.distributed` CPU processes form one
cluster (`initialize_cluster` + `global_mesh`) and run a sharded query
step whose output must equal the single-process run — the DCN-facing
half of the comm backend (reference NCCL/MPI transports ->
jax.distributed + XLA collectives)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import json
    import os
    import sys

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/root/repo/.jax_cache")
    sys.path.insert(0, "/root/repo")

    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    # config-level platform reset: plugin platforms (the axon TPU tunnel)
    # override JAX_PLATFORMS at interpreter start, and jax.distributed
    # over the tunnel would hang (see parallel/mesh.force_host_devices)
    from siddhi_tpu.parallel.mesh import force_host_devices

    force_host_devices(2)
    print("worker: platform ready", file=sys.stderr, flush=True)
    from siddhi_tpu.parallel.distributed import (
        global_mesh,
        initialize_cluster,
        process_info,
    )

    initialize_cluster(coordinator_address=coord, num_processes=nproc,
                       process_id=pid)
    print("worker: cluster up", file=sys.stderr, flush=True)
    info = process_info()
    assert info["process_count"] == nproc, info
    assert info["global_devices"] == 2 * nproc, info

    # one sharded step over the global mesh: a per-key segment sum of
    # [K, W] rows sharded on the key axis across BOTH hosts
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()

    K, W = 8, 4
    vals_h = (np.arange(K * W, dtype=np.float64).reshape(K, W) + 1.0)

    @jax.jit
    def step(vals):
        return jnp.sum(vals, axis=1) * 2.0

    sharding = NamedSharding(mesh, P("keys", None))
    with mesh:
        vals = jax.make_array_from_callback(
            (K, W), sharding, lambda idx: vals_h[idx])
        out = jax.jit(step, out_shardings=NamedSharding(mesh, P("keys")))(vals)
        # cross-host collective: a global sum over the sharded axis
        total = jax.jit(lambda v: jnp.sum(v))(vals)
    # each process can read only ITS addressable shards of the global
    # array; the parent reassembles both halves
    local = [((s.index[0].start or 0), np.asarray(s.data).ravel().tolist())
             for s in out.addressable_shards]
    tot = float(np.asarray(total.addressable_shards[0].data))
    print(json.dumps({"local": local, "total": tot}), flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_cluster_matches_single_process():
    import numpy as np

    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=200)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    # single-process reference result
    K, W = 8, 4
    vals = np.arange(K * W, dtype=np.float64).reshape(K, W) + 1.0
    expect = (vals.sum(axis=1) * 2.0).tolist()
    merged = [None] * K
    for o in outs:
        payload = json.loads(o.strip().splitlines()[-1])
        assert payload["total"] == float(vals.sum())   # global collective
        for start, chunk in payload["local"]:
            merged[start:start + len(chunk)] = chunk
    assert merged == expect
