"""Multi-host: two REAL ``jax.distributed`` CPU processes form one cluster
and run ACTUAL query runtimes — the flagship group-by aggregation and a
partitioned NFA pattern — with their keyed state sharded over the global
mesh (``shard_query_step``), through the real host pump
(``InputHandler.send`` -> junction -> jitted step -> ``StreamCallback``).
Both processes must produce the single-process runtime's exact outputs.
This is the DCN-facing half of the comm backend (reference NCCL/MPI
transports -> jax.distributed + XLA collectives, SURVEY.md §2.13/§5.8).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

# Runs a SPMD worker: every process feeds IDENTICAL event sequences (the
# multi-controller contract — replicated jit inputs must agree), state is
# key-sharded across BOTH processes, outputs are pulled host-side (the
# sharded step replicates its OUT batch across processes; see
# parallel/mesh._out_shardings).
_WORKER = textwrap.dedent("""
    import gc
    gc.disable()      # GC during jax tracing segfaults this build
    import json
    import os
    import sys

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "")  # see conftest: the
    # on-disk jit cache poisons itself on this sandbox
    sys.path.insert(0, "/root/repo")

    coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    # config-level platform reset: plugin platforms (the axon TPU tunnel)
    # override JAX_PLATFORMS at interpreter start, and jax.distributed
    # over the tunnel would hang (see parallel/mesh.force_host_devices)
    from siddhi_tpu.parallel.mesh import force_host_devices

    force_host_devices(2)
    print("worker: platform ready", file=sys.stderr, flush=True)
    from siddhi_tpu.parallel.distributed import (
        global_mesh,
        initialize_cluster,
        process_info,
    )

    initialize_cluster(coordinator_address=coord, num_processes=nproc,
                       process_id=pid)
    print("worker: cluster up", file=sys.stderr, flush=True)
    info = process_info()
    assert info["process_count"] == nproc, info
    assert info["global_devices"] == 2 * nproc, info

    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.parallel.mesh import shard_query_step

    class C(StreamCallback):
        def __init__(self):
            self.rows = []

        def receive(self, events):
            self.rows.extend([e.timestamp] + list(e.data) for e in events)

    results = {}

    # ---- flagship: group-by window aggregation, selector state [_, K]
    # sharded across the 2-process global mesh
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime('''
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(8)
        select symbol, avg(price) as ap, sum(volume) as tv
        group by symbol
        insert into Out;
    ''')
    c = C()
    rt.add_callback("Out", c)
    shard_query_step(rt.query_runtimes["q"], global_mesh())
    h = rt.get_input_handler("S")
    for i in range(96):
        h.send(1000 + i, [f"K{i % 24}", float(i % 13) + 0.5, int(i)])
    m.shutdown()
    results["flagship"] = c.rows

    # ---- partitioned NFA pattern over the same global mesh
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime('''
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
          select e1.v as v1, e2.v as v2
          insert into Out;
        end;
    ''')
    c2 = C()
    rt2.add_callback("Out", c2)
    shard_query_step(rt2.query_runtimes["q"], global_mesh())
    ha = rt2.get_input_handler("A")
    hb = rt2.get_input_handler("B")
    t = 1000
    for i in range(48):
        k = f"P{(i * 7) % 16}"
        va = float((i * 3) % 11)
        ha.send(t, [k, va])
        hb.send(t + 1, [k, va + (1.0 if i % 3 else -1.0)])
        t += 50
    m2.shutdown()
    results["nfa"] = c2.rows

    print(json.dumps(results), flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _single_process_expected():
    """The same two feeds against plain single-process runtimes."""
    from siddhi_tpu import SiddhiManager, StreamCallback

    class C(StreamCallback):
        def __init__(self):
            self.rows = []

        def receive(self, events):
            self.rows.extend([e.timestamp] + list(e.data) for e in events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(8)
        select symbol, avg(price) as ap, sum(volume) as tv
        group by symbol
        insert into Out;
    """)
    c = C()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    for i in range(96):
        h.send(1000 + i, [f"K{i % 24}", float(i % 13) + 0.5, int(i)])
    m.shutdown()

    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime("""
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
          select e1.v as v1, e2.v as v2
          insert into Out;
        end;
    """)
    c2 = C()
    rt2.add_callback("Out", c2)
    ha = rt2.get_input_handler("A")
    hb = rt2.get_input_handler("B")
    t = 1000
    for i in range(48):
        k = f"P{(i * 7) % 16}"
        va = float((i * 3) % 11)
        ha.send(t, [k, va])
        hb.send(t + 1, [k, va + (1.0 if i % 3 else -1.0)])
        t += 50
    m2.shutdown()
    return {"flagship": c.rows, "nfa": c2.rows}


_MULTIPROCESS_UNSUPPORTED = "Multiprocess computations aren't implemented"


def _skip_if_backend_cannot(err: str) -> None:
    """Cross-process computations need a collectives-capable backend
    (TPU, or CPU with gloo linked in); this jaxlib's plain-CPU XLA
    refuses them at compile time. That is an environment limit, not a
    code regression — skip with the backend's own message."""
    if _MULTIPROCESS_UNSUPPORTED in err:
        pytest.skip("backend cannot compile cross-process computations "
                    "(single-process recovery paths are covered by "
                    "tests/test_resilience_cluster.py)")


def test_two_process_cluster_runs_real_queries():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=400)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        _skip_if_backend_cannot(err)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    expected = _single_process_expected()
    assert len(expected["flagship"]) == 96
    assert len(expected["nfa"]) > 0
    for o in outs:
        payload = json.loads(o.strip().splitlines()[-1])
        assert payload["flagship"] == expected["flagship"]
        assert payload["nfa"] == expected["nfa"]


# ------------------------------------------------ peer-death failure bound

_DEATH_WORKER = textwrap.dedent("""
    import gc
    gc.disable()      # GC during jax tracing segfaults this build
    import json
    import os
    import sys
    import time

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "")  # see conftest: the
    # on-disk jit cache poisons itself on this sandbox
    sys.path.insert(0, "/root/repo")

    coord, nproc, pid, flag = (sys.argv[1], int(sys.argv[2]),
                               int(sys.argv[3]), sys.argv[4])
    from siddhi_tpu.parallel.mesh import force_host_devices

    force_host_devices(2)
    from siddhi_tpu.parallel.distributed import (
        global_mesh, initialize_cluster)

    initialize_cluster(coordinator_address=coord, num_processes=nproc,
                       process_id=pid)
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.util.config import InMemoryConfigManager
    from siddhi_tpu.parallel.mesh import shard_query_step

    # the partitioned NFA step carries 2 all-reduces per step on this
    # mesh (checked via lowered HLO), so the survivor's next step REALLY
    # blocks on the dead peer — the flagship group-by happens to compile
    # collective-free at this shape and cannot exercise the bound
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.cluster_step_timeout": "4"}))
    rt = m.create_siddhi_app_runtime('''
        @app:playback
        @OnError(action='stream')
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
          select e1.v as v1, e2.v as v2
          insert into Out;
        end;
    ''')
    faults = []

    class F(StreamCallback):
        def receive(self, events):
            faults.extend(str(e.data[-1]) for e in events)

    rt.add_callback("!A", F())
    shard_query_step(rt.query_runtimes["q"], global_mesh())
    ha = rt.get_input_handler("A")
    hb = rt.get_input_handler("B")
    for i in range(4):
        ha.send(1000 + i * 10, [f"P{i % 4}", float(i)])
        hb.send(1001 + i * 10, [f"P{i % 4}", float(i) + 1.0])
    if pid == 1:
        open(flag, "w").write("dead")
        os._exit(17)      # abrupt peer death, no cleanup
    while not os.path.exists(flag):
        time.sleep(0.05)
    time.sleep(1.0)
    # the survivor's next sharded step blocks on the dead peer's
    # all-reduce: the guarded pull must surface a LABELED error within
    # the configured bound through the @OnError fault stream
    t0 = time.time()
    for i in range(4, 8):
        ha.send(1000 + i * 10, [f"P{i % 4}", float(i)])
        if faults:
            break
    elapsed = time.time() - t0
    print(json.dumps({"faults": faults[:1], "elapsed": elapsed}), flush=True)
    os._exit(0)           # skip shutdown: the dead cluster cannot barrier
""")


def test_peer_death_is_bounded_and_labeled():
    """VERDICT r04 next #6: killing one of two processes mid-stream must
    produce a bounded, labeled failure on the survivor — surfaced through
    the @OnError fault-stream machinery (reference failure-surface analog:
    Source.java:155-185 retry/error hooks) — not a hang."""
    import tempfile

    port = _free_port()
    coord = f"127.0.0.1:{port}"
    flag = tempfile.mktemp(prefix="siddhi-peer-death-")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _DEATH_WORKER, coord, "2", str(pid), flag],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in (0, 1)
    ]
    try:
        out1, err1 = procs[1].communicate(timeout=300)
        _skip_if_backend_cannot(err1)
        assert procs[1].returncode == 17
        try:
            out0, err0 = procs[0].communicate(timeout=240)
        except subprocess.TimeoutExpired:
            raise AssertionError("survivor hung after peer death")
        _skip_if_backend_cannot(err0)
        assert procs[0].returncode == 0, f"survivor failed:\n{err0[-3000:]}"
    finally:
        for q in procs:          # an early failure must not leak a spinner
            if q.poll() is None:
                q.kill()
    payload = json.loads(out0.strip().splitlines()[-1])
    assert payload["faults"], "no fault-stream event on the survivor"
    # two bounded outcomes, both labeled with the peer failure: gloo's
    # transport notices the closed connection immediately ("Connection
    # closed by peer"), or — when the transport keeps waiting — the
    # guarded pull times out with ClusterPeerError ("cluster peer
    # process is presumed dead")
    assert "peer" in payload["faults"][0], payload
    assert payload["elapsed"] < 60, payload


def test_guarded_pull_times_out_with_labeled_error():
    """Unit semantics of the bounded wait (the integration test above may
    take gloo's fast connection-closed path instead): a pull whose
    materialization stalls longer than the bound raises ClusterPeerError
    with the recovery hint."""
    import time

    import numpy as np

    from siddhi_tpu.parallel.distributed import ClusterPeerError, guarded_pull

    class Stall:
        def __array__(self, dtype=None, copy=None):
            time.sleep(8.0)          # a peer-blocked device pull
            return np.zeros(3)

    t0 = time.time()
    with pytest.raises(ClusterPeerError, match="peer.*snapshot"):
        guarded_pull(Stall(), 1.0, what="unit step")
    assert time.time() - t0 < 5.0    # bounded, not the full stall
    # the fast path returns the value when the wait completes in time
    v = guarded_pull(np.arange(3), 5.0)
    assert list(v) == [0, 1, 2]
