"""Tests for timeLength, delay, externalTimeBatch, sort, frequent,
lossyFrequent, session windows — expectations mirror the reference
``query/window/*TestCase.java`` corpus."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


def test_time_length_window_length_bound():
    # length bound dominates when events are rapid
    m, rt, c = build("""
        @app:playback
        define stream S (sym string, v int);
        from S#window.timeLength(10 sec, 2)
        select sym, sum(v) as total
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["a", 1])
    h.send(1001, ["a", 2])
    h.send(1002, ["a", 4])   # length 2: the 1 falls out
    m.shutdown()
    assert [e.data[1] for e in c.events] == [1, 3, 6]


def test_time_length_window_time_bound():
    m, rt, c = build("""
        @app:playback
        define stream S (sym string, v int);
        from S#window.timeLength(100 milliseconds, 10)
        select sym, sum(v) as total
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["a", 1])
    h.send(1300, ["a", 2])   # the 1 is time-expired before processing
    m.shutdown()
    assert [e.data[1] for e in c.events] == [1, 2]


def test_delay_window():
    m, rt, c = build("""
        @app:playback
        define stream S (sym string, v int);
        from S#window.delay(100 milliseconds)
        select sym, v
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["a", 1])        # held
    assert c.events == []
    h.send(1150, ["b", 2])        # releases the 1; holds the 2
    got = [e.data[1] for e in c.events]
    assert got == [1]
    h.send(1300, ["c", 3])        # releases the 2
    got = [e.data[1] for e in c.events]
    assert got == [1, 2]
    m.shutdown()


def test_external_time_batch():
    # reference ExternalTimeBatchWindowTestCase shape: batches by event time
    m, rt, c = build("""
        define stream S (ts long, v int);
        from S#window.externalTimeBatch(ts, 1 sec)
        select sum(v) as total
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send([1000, 1])
    h.send([1500, 2])
    h.send([2100, 4])     # crosses 1000+1000: flush batch {1,2} -> total 3
    h.send([2500, 8])
    h.send([3200, 16])    # flush {4,8} -> 12
    m.shutdown()
    totals = [e.data[0] for e in c.events if not e.is_expired]
    assert totals == [3, 12]


def test_sort_window():
    # keeps 2 smallest volumes; overflow evicts the largest as expired
    m, rt, c = build("""
        define stream S (sym string, vol int);
        from S#window.sort(2, vol)
        select sym, sum(vol) as total
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["a", 50])
    h.send(["b", 20])
    h.send(["c", 40])   # evicts 50 -> window {20, 40}
    m.shutdown()
    assert [e.data[1] for e in c.events] == [50, 70, 60]


def test_frequent_window():
    # only the single most-frequent symbol is tracked
    m, rt, c = build("""
        define stream S (sym string, v int);
        from S#window.frequent(1, sym)
        select sym, v
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["a", 1])     # tracked, current
    h.send(["a", 2])     # tracked, current
    h.send(["b", 3])     # full: decrement a (2->1); no room -> b dropped
    h.send(["a", 4])     # still tracked
    m.shutdown()
    got = [(e.data[0], e.data[1]) for e in c.events if not e.is_expired]
    assert got == [("a", 1), ("a", 2), ("a", 4)]


def test_session_window():
    m, rt, c = build("""
        @app:playback
        define stream S (user string, v int);
        from S#window.session(100 milliseconds, user)
        select user, sum(v) as total
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    h.send(1050, ["u1", 2])     # same session
    h.send(1500, ["u1", 4])     # previous session expired (gap 450 > 100)
    m.shutdown()
    # sums: 1, 3, then session expiry removes 1+2, then +4 -> 4
    totals = [e.data[1] for e in c.events if not e.is_expired]
    assert totals == [1, 3, 4]


def test_lossy_frequent_window():
    m, rt, c = build("""
        define stream S (sym string);
        from S#window.lossyFrequent(0.5, 0.1, sym)
        select sym
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for s in ["a", "a", "a", "b", "a"]:
        h.send([s])
    m.shutdown()
    # 'a' dominates (support 0.5): emitted each time; single 'b' (1/4 < 0.4) not
    got = [e.data[0] for e in c.events if not e.is_expired]
    assert got == ["a", "a", "a", "a"]


def test_sort_window_string_attr():
    # string sort compares decoded values, not dictionary ids
    from siddhi_tpu import QueryCallback

    class QC(QueryCallback):
        def __init__(self):
            self.removed = []

        def receive(self, timestamp, in_events, remove_events):
            if remove_events:
                self.removed.extend(remove_events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        @info(name = 'q')
        from S#window.sort(2, sym)
        select sym, v
        insert all events into OutStream;
    """)
    qc = QC()
    rt.add_callback("q", qc)
    h = rt.get_input_handler("S")
    h.send(["z", 1])
    h.send(["a", 2])
    h.send(["m", 3])   # evicts 'z' (lexicographically greatest)
    m.shutdown()
    assert [e.data[0] for e in qc.removed] == ["z"]
