"""@index / single-attr @primaryKey probes — sub-linear equality lookups
(reference ``IndexEventHolder.java:60-80`` per-attribute indexes +
``CollectionExecutor`` probe compilation): host value->slots hash maps
for on-demand queries, device sorted-column searchsorted for joins."""

import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def _fill(rt, n, dup_every=None):
    """Insert n rows (sym Sx, price x, volume x%7) via bulk ingest."""
    h = rt.get_input_handler("In")
    B = 8192
    for c0 in range(0, n, B):
        m = min(B, n - c0)
        ids = np.arange(c0, c0 + m)
        h.send_columns({
            "sym": np.array([f"S{i}" for i in ids], dtype=object),
            "price": ids.astype(np.float64),
            "volume": (ids % 7).astype(np.int64),
        })


APP = """
define stream In (sym string, price double, volume long);
@index('sym')
define table T (sym string, price double, volume long);
from In insert into T;
"""


def test_on_demand_indexed_equality_probe_correct():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    _fill(rt, 1000)
    rows = rt.query("from T on T.sym == 'S123' select sym, price return;")
    assert [tuple(e.data) for e in rows] == [("S123", 123.0)]
    # conjunct with residual
    rows = rt.query(
        "from T on T.sym == 'S123' and volume > 100 select sym return;")
    assert rows == []   # 123 % 7 = 4, residual fails
    rows = rt.query(
        "from T on T.sym == 'S123' and volume >= 0 select sym return;")
    assert [e.data[0] for e in rows] == ["S123"]
    # miss
    assert rt.query("from T on T.sym == 'NOPE' select sym return;") == []
    m.shutdown()


def test_on_demand_probe_tracks_mutations():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    _fill(rt, 100)
    rt.query("delete T on T.sym == 'S42';")
    assert rt.query("from T on T.sym == 'S42' select sym return;") == []
    rt.query("update T set T.price = 999.0 on T.sym == 'S43';")
    rows = rt.query("from T on T.sym == 'S43' select price return;")
    assert [e.data[0] for e in rows] == [999.0]
    m.shutdown()


def test_on_demand_indexed_probe_sublinear_100k():
    # the probe must not degrade with table size: compare per-query time
    # on a 100k-row table between an indexed lookup and a forced full
    # scan (inequality prevents the probe) — the probe must win big
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    _fill(rt, 100_000)

    def best_of(q, n=3):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            rt.query(q)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    probe_q = "from T on T.sym == 'S77777' select sym, price return;"
    scan_q = ("from T on T.sym == 'S77777' and price >= 0.0 "
              "select sym, price return;")
    # warm both paths (jit/selector compile + index build)
    assert [e.data[0] for e in rt.query(probe_q)] == ["S77777"]
    rt.query(scan_q)
    t_probe = best_of(probe_q)
    # results agree
    assert [e.data[0] for e in rt.query(scan_q)] == ["S77777"]
    m.shutdown()
    # hash probe over 100k rows: well under 50ms (a full [1,C] device
    # scan + selector over 100k rows costs much more; avoid asserting a
    # flaky ratio — assert the probe's absolute cost stays tiny)
    assert t_probe < 0.05, f"indexed probe took {t_probe * 1e3:.1f} ms"


JOIN_APP = """
define stream In (sym string, price double, volume long);
define stream Q (qsym string, qty long);
@index('sym')
define table T (sym string, price double, volume long);
from In insert into T;
@info(name='j')
from Q join T on T.sym == Q.qsym
select Q.qsym as sym, T.price as price, Q.qty as qty
insert into OutStream;
"""


def test_indexed_join_correct_and_uses_probe():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(JOIN_APP)
    c = Collector()
    rt.add_callback("OutStream", c)
    _fill(rt, 5000)
    # planner detected the probe
    assert rt.query_runtimes["j"].index_probe is not None
    hq = rt.get_input_handler("Q")
    hq.send(["S1234", 7])
    hq.send(["MISSING", 1])
    hq.send(["S4999", 2])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [
        ("S1234", 1234.0, 7), ("S4999", 4999.0, 2)]


def test_indexed_join_with_residual_and_duplicates():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream In (sym string, price double, volume long);
        define stream Q (qsym string, minp double);
        @index('sym')
        define table T (sym string, price double, volume long);
        from In insert into T;
        @info(name='j')
        from Q join T on T.sym == Q.qsym and T.price > Q.minp
        select Q.qsym as sym, T.price as price
        insert into OutStream;
    """)
    c = Collector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("In")
    # duplicate keys with different prices
    h.send_columns({"sym": np.array(["A", "A", "A", "B"], dtype=object),
                    "price": np.array([1.0, 5.0, 9.0, 3.0]),
                    "volume": np.array([1, 1, 1, 1], dtype=np.int64)})
    assert rt.query_runtimes["j"].index_probe is not None
    hq = rt.get_input_handler("Q")
    hq.send(["A", 4.0])
    m.shutdown()
    assert sorted(e.data[1] for e in c.events) == [5.0, 9.0]


def test_indexed_join_probe_width_overflow_raises():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream In (sym string, price double, volume long);
        define stream Q (qsym string, qty long);
        @index('sym')
        define table T (sym string, price double, volume long);
        from In insert into T;
        @info(name='j')
        from Q join T on T.sym == Q.qsym
        select Q.qsym as sym, T.price as price insert into OutStream;
    """)
    rt.app_context.index_probe_width = 4
    rt.add_callback("OutStream", Collector())
    h = rt.get_input_handler("In")
    h.send_columns({"sym": np.array(["X"] * 10, dtype=object),
                    "price": np.arange(10, dtype=np.float64),
                    "volume": np.zeros(10, np.int64)})
    hq = rt.get_input_handler("Q")
    try:
        with pytest.raises(RuntimeError):
            hq.send(["X", 1])
    finally:
        m.shutdown()


def test_probe_skipped_for_narrowing_value_type():
    # `on T.volume == price` with price double against a long index:
    # casting 2.5 -> 2 would fabricate matches, so the planner must fall
    # back to the broadcast compare; on-demand likewise scans
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream In (sym string, price double, volume long);
        define stream Q (qsym string, price double);
        @index('volume')
        define table T (sym string, price double, volume long);
        from In insert into T;
        @info(name='j')
        from Q join T on T.volume == Q.price
        select T.sym as sym insert into OutStream;
    """)
    c = Collector()
    rt.add_callback("OutStream", c)
    assert rt.query_runtimes["j"].index_probe is None   # narrowing: no probe
    h = rt.get_input_handler("In")
    h.send(["A", 1.0, 2])
    rt.get_input_handler("Q").send(["q", 2.5])   # 2.5 != 2: no match
    rows = rt.query("from T on T.volume == 2.5 select sym return;")
    assert rows == [] and not c.events
    rt.get_input_handler("Q").send(["q", 2.0])   # 2.0 == 2: matches
    m.shutdown()
    assert [e.data[0] for e in c.events] == ["A"]


def test_unindexed_join_still_broadcasts():
    # no @index: the planner leaves the broadcast compare in place
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream In (sym string, price double, volume long);
        define stream Q (qsym string, qty long);
        define table T (sym string, price double, volume long);
        from In insert into T;
        @info(name='j')
        from Q join T on T.sym == Q.qsym
        select Q.qsym as sym, T.price as price insert into OutStream;
    """)
    c = Collector()
    rt.add_callback("OutStream", c)
    assert rt.query_runtimes["j"].index_probe is None
    _fill(rt, 100)
    rt.get_input_handler("Q").send(["S5", 1])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("S5", 5.0)]
