"""Grammar-coverage battery: SiddhiQL surface shapes from the reference
grammar (SiddhiQL.g4) that the hand-written parser must accept — pure
parse/compile checks (no runtime assertions beyond successful build)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.compiler.compiler import SiddhiCompiler


def parses(app: str):
    return SiddhiCompiler().parse(app)


def builds(app: str):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    m.shutdown()
    return rt


BASE = "define stream S (sym string, price double, vol long);\n"


def test_comments_line_and_block():
    parses("""
        -- line comment
        /* block
           comment */
        define stream S (a int);  -- trailing
        from S select a insert into O;
    """)


def test_time_constant_chains():
    # time_value: descending unit chains compose additively
    app = parses(BASE + """
        from S#window.time(1 hour 20 min 30 sec) select sym insert into O;
    """)
    q = app.execution_elements[0]
    w = q.input_stream.handlers[0]
    assert w.parameters[0].value == (3600 + 20 * 60 + 30) * 1000


def test_time_constant_every_unit():
    for unit, ms in [("milliseconds", 1), ("seconds", 1000), ("minutes", 60000),
                     ("hours", 3600000), ("days", 86400000)]:
        app = parses(BASE + f"from S#window.time(2 {unit}) select sym insert into O;")
        w = app.execution_elements[0].input_stream.handlers[0]
        assert w.parameters[0].value == 2 * ms


def test_numeric_literal_suffixes():
    # 10L, 10l (long), 1.5f/F (float), 1.5d/D (double)
    builds(BASE + """
        from S[vol > 10L and price > 1.5f and price < 99.5d]
        select sym insert into O;
    """)


def test_scientific_literals():
    # (the reference INT_LITERAL is decimal-only: no hex in SiddhiQL)
    builds(BASE + "from S[price > 1.5e2] select sym insert into O;")


def test_string_literals_quotes_and_escapes():
    builds(BASE + """
        from S[sym == "dq" or sym == 'sq' or sym == "it''s"]
        select sym insert into O;
    """)


def test_triple_quoted_string():
    builds(BASE + '''
        from S[sym == """tri"ple"""] select sym insert into O;
    ''')


def test_annotation_nesting_and_elements():
    builds("""
        @app:name('Nested')
        @app:description("desc, with commas")
        define stream S (a int);
        @info(name = 'q1')
        from S select a insert into O;
    """)


def test_output_rate_forms():
    for clause in ["output every 3 events", "output last every 1 sec",
                   "output first every 2 events", "output all every 1 min"]:
        builds(BASE + f"from S select sym, price {clause} insert into O;")
    # snapshot rate limiting REQUIRES `insert all events`
    # (QueryParser.java:120-128)
    builds(BASE + "from S select sym, price output snapshot every 1 sec "
                  "insert all events into O;")
    import pytest
    from siddhi_tpu.compiler.errors import SiddhiAppValidationException
    with pytest.raises(SiddhiAppValidationException):
        builds(BASE + "from S select sym, price output snapshot every 1 sec "
                      "insert into O;")


def test_join_type_keywords():
    for jt in ["join", "inner join", "left outer join", "right outer join",
               "full outer join"]:
        builds(BASE + """define stream T (sym string, x double);
            from S#window.length(5) %s T#window.length(5)
            on S.sym == T.sym
            select S.sym as sym, T.x as x insert into O;""" % jt)


def test_unidirectional_join():
    builds(BASE + """define stream T (sym string, x double);
        from S#window.length(5) unidirectional join T#window.length(5)
        on S.sym == T.sym
        select S.sym as sym, T.x as x insert into O;""")


def test_define_forms():
    builds("""
        define stream S (a int, b string);
        define table T (a int, b string);
        define window W (a int, b string) length(5) output all events;
        define trigger Trg at every 5 sec;
        define trigger Start at 'start';
        from S select a, b insert into T;
    """)


def test_aggregation_define_and_range():
    builds("""
        define stream S (sym string, price double, ts long);
        define aggregation Agg
        from S select sym, avg(price) as ap
        group by sym
        aggregate by ts every sec ... year;
    """)


def test_on_demand_query_parse():
    c = SiddhiCompiler()
    q = c.parse_on_demand_query("from T on a > 5 select a, b")
    assert q is not None


def test_patterns_arrow_chains_and_groups():
    builds("""
        define stream A (v int); define stream B (v int); define stream C (v int);
        from every (e1=A -> e2=B) -> e3=C[v > e1.v]
        select e1.v as v1, e3.v as v3 insert into O;
    """)


def test_sequence_comma_chain():
    builds("""
        define stream A (v int); define stream B (v int);
        from e1=A, e2=B[v > e1.v]
        select e1.v as v1, e2.v as v2 insert into O;
    """)


def test_filter_math_and_functions_in_select():
    builds(BASE + """
        from S[not (price < 10.0) and (vol % 2 == 0 or sym != 'x')]
        select sym, price * 1.1 as up, ifThenElse(price > 50.0, 'hi', 'lo') as band
        insert into O;
    """)


def test_is_null_conditions():
    builds(BASE + "from S[sym is null] select price insert into O;")
    builds(BASE + "from S[not (sym is null)] select price insert into O;")


def test_delete_update_output_actions():
    builds("""
        define stream S (a int);
        define table T (a int);
        from S insert into T;
        from S delete T on T.a == a;
    """)
    builds("""
        define stream S (a int);
        define table T (a int);
        from S update T set T.a = a on T.a < a;
    """)
    builds("""
        define stream S (a int);
        define table T (a int);
        from S update or insert into T set T.a = a on T.a == a;
    """)


def test_current_expired_event_outputs():
    builds(BASE + "from S#window.length(2) select sym insert current events into O;")
    builds(BASE + "from S#window.length(2) select sym insert expired events into O;")
    builds(BASE + "from S#window.length(2) select sym insert all events into O;")


def test_group_by_having_order_limit_offset():
    builds(BASE + """
        from S#window.length(10)
        select sym, avg(price) as ap
        group by sym
        having ap > 10.0
        order by ap desc
        limit 5
        offset 1
        insert into O;
    """)


def test_multiline_app_with_partition_and_inner_stream():
    builds("""
        define stream S (sym string, v int);
        partition with (sym of S)
        begin
            from S select sym, v insert into #inner;
            from #inner#window.length(2) select sym, sum(v) as t
            insert into OutStream;
        end;
    """)


def test_annotation_on_aggregation_and_purge():
    builds("""
        define stream S (sym string, price double, ts long);
        @purge(enable='true', interval='10 sec',
               @retentionPeriod(sec='1 min', min='1 hour'))
        define aggregation A2
        from S select sym, sum(price) as total
        group by sym aggregate by ts every sec ... min;
    """)


def test_unidirectional_right_join_side():
    builds("""
        define stream L (sym string); define stream R (sym string);
        from L#window.length(2) join R#window.length(2) unidirectional
             on L.sym == R.sym
        select L.sym as sym insert into O;
    """)
