"""Stream functions (`#name(args)` handlers): #log, #pol2Cart, and
custom extension stream functions — reference
``query/processor/stream/LogStreamProcessor.java``,
``Pol2CartStreamFunctionProcessor.java``,
``StreamFunctionProcessor.java`` (and the core LogStreamProcessorTestCase /
Pol2CartStreamProcessorTestCase shapes)."""

import logging
import math

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.ops.expressions import CompileError
from siddhi_tpu.extension import StreamFunction
from siddhi_tpu.query_api.definitions import AttrType


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream", manager=None):
    manager = manager or SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


# --------------------------------------------------------------- pol2Cart


def test_pol2cart_appends_x_y():
    m, rt, c = build("""
        define stream PolarStream (theta double, rho double);
        from PolarStream#pol2Cart(theta, rho)
        select x, y
        insert into OutStream;
    """)
    rt.get_input_handler("PolarStream").send([0.7854, 5.0])
    m.shutdown()
    (x, y), = [tuple(e.data) for e in c.events]
    # reference example: theta in degrees
    assert x == pytest.approx(5.0 * math.cos(math.radians(0.7854)), rel=1e-9)
    assert y == pytest.approx(5.0 * math.sin(math.radians(0.7854)), rel=1e-9)


def test_pol2cart_with_z_and_select_star():
    m, rt, c = build("""
        define stream PolarStream (theta double, rho double);
        from PolarStream#pol2Cart(theta, rho, 3.4)
        select *
        insert into OutStream;
    """)
    rt.get_input_handler("PolarStream").send([90.0, 2.0])
    m.shutdown()
    row, = [tuple(e.data) for e in c.events]
    theta, rho, x, y, z = row
    assert (theta, rho) == (90.0, 2.0)
    assert x == pytest.approx(0.0, abs=1e-12)
    assert y == pytest.approx(2.0)
    assert z == pytest.approx(3.4)


def test_pol2cart_then_filter_and_window():
    # a post-function filter may reference the appended attributes, and the
    # window buffers them
    m, rt, c = build("""
        define stream PolarStream (theta double, rho double);
        from PolarStream#pol2Cart(theta, rho)[y > 0.0]#window.length(2)
        select sum(y) as total
        insert into OutStream;
    """)
    h = rt.get_input_handler("PolarStream")
    h.send([90.0, 1.0])    # y = 1
    h.send([270.0, 1.0])   # y = -1, filtered out
    h.send([90.0, 2.0])    # y = 2
    m.shutdown()
    totals = [e.data[0] for e in c.events]
    assert totals[-1] == pytest.approx(3.0)


def test_pol2cart_group_by_synthetic_attr():
    # group key computed from a stream-function output (host keyer path)
    m, rt, c = build("""
        define stream PolarStream (theta double, rho double);
        from PolarStream#pol2Cart(theta, rho)
        select x, count() as n
        group by x
        insert into OutStream;
    """)
    h = rt.get_input_handler("PolarStream")
    h.send([0.0, 2.0])   # x = 2
    h.send([0.0, 2.0])   # x = 2 again
    h.send([0.0, 3.0])   # x = 3
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got[-1][1] == 1 and got[1] == (2.0, 2)


def test_pol2cart_inside_partition():
    m, rt, c = build("""
        define stream PolarStream (symbol string, theta double, rho double);
        partition with (symbol of PolarStream)
        begin
            from PolarStream#pol2Cart(theta, rho)#window.length(10)
            select symbol, sum(y) as total
            insert into OutStream;
        end;
    """)
    h = rt.get_input_handler("PolarStream")
    h.send(["A", 90.0, 1.0])
    h.send(["B", 90.0, 5.0])
    h.send(["A", 90.0, 2.0])
    m.shutdown()
    last = {e.data[0]: e.data[1] for e in c.events}
    assert last["A"] == pytest.approx(3.0)
    assert last["B"] == pytest.approx(5.0)


def test_stream_function_name_collision_rejected():
    with pytest.raises(CompileError, match="collides"):
        build("""
            define stream PolarStream (x double, theta double, rho double);
            from PolarStream#pol2Cart(theta, rho)
            select x insert into OutStream;
        """)


def test_unknown_stream_function_rejected():
    with pytest.raises(CompileError, match="unknown stream function"):
        build("""
            define stream S (v int);
            from S#noSuchThing(v) select v insert into OutStream;
        """)


# -------------------------------------------------------------------- log


def test_log_passthrough_and_message(caplog):
    m, rt, c = build("""
        define stream S (symbol string, price double);
        from S#log('INFO', 'price event', true)[price > 10.0]
        select symbol insert into OutStream;
    """)
    with caplog.at_level(logging.INFO, logger="siddhi"):
        rt.get_input_handler("S").send(["WSO2", 55.5])
        rt.get_input_handler("S").send(["CHEAP", 5.0])
    m.shutdown()
    # pass-through: filter applies after, so only WSO2 reaches the output
    assert [e.data[0] for e in c.events] == ["WSO2"]
    msgs = [r.message for r in caplog.records]
    # log sits before the filter: both events are logged, with the message
    assert any("price event" in s and "WSO2" in s for s in msgs)
    assert any("CHEAP" in s for s in msgs)


def test_log_after_filter_only_logs_passing_rows(caplog):
    m, rt, c = build("""
        define stream S (symbol string, price double);
        from S[price > 10.0]#log('filtered')
        select symbol insert into OutStream;
    """)
    with caplog.at_level(logging.INFO, logger="siddhi"):
        rt.get_input_handler("S").send(["WSO2", 55.5])
        rt.get_input_handler("S").send(["CHEAP", 5.0])
    m.shutdown()
    msgs = [r.message for r in caplog.records]
    assert any("WSO2" in s for s in msgs)
    assert not any("CHEAP" in s for s in msgs)


def test_log_no_event(caplog):
    # #log('msg', false) logs the message without the event payload
    m, rt, c = build("""
        define stream S (v int);
        from S#log('tick', false) select v insert into OutStream;
    """)
    with caplog.at_level(logging.INFO, logger="siddhi"):
        rt.get_input_handler("S").send([7])
    m.shutdown()
    msgs = [r.message for r in caplog.records]
    assert any(s.endswith("tick") for s in msgs)
    assert not any("StreamEvent" in s for s in msgs)


def test_log_bad_priority_rejected():
    with pytest.raises(CompileError, match="priority"):
        build("""
            define stream S (v int);
            from S#log('LOUD', 'oops') select v insert into OutStream;
        """)


# --------------------------------------------------------- join sides


def test_pol2cart_on_join_side():
    m, rt, c = build("""
        define stream PolarStream (symbol string, theta double, rho double);
        define stream RefStream (symbol string, lim double);
        from PolarStream#pol2Cart(theta, rho)#window.length(5)
             join RefStream#window.length(5)
             on PolarStream.symbol == RefStream.symbol
        select PolarStream.symbol as symbol, PolarStream.y as y, RefStream.lim as lim
        insert into OutStream;
    """)
    rt.get_input_handler("RefStream").send(["A", 10.0])
    rt.get_input_handler("PolarStream").send(["A", 90.0, 4.0])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert ("A", pytest.approx(4.0), 10.0) in [
        (s, y, l) for s, y, l in got]


# ------------------------------------------------------ extension SPI


class Magnitude(StreamFunction):
    out_attrs = [("magnitude", AttrType.DOUBLE)]

    @staticmethod
    def apply(xp, a, b):
        return xp.sqrt(a * a + b * b)


def test_custom_stream_function_extension():
    manager = SiddhiManager()
    manager.set_extension("streamFunction:mag", Magnitude)
    m, rt, c = build("""
        define stream Vec (x1 double, x2 double);
        from Vec#mag(x1, x2)
        select magnitude
        insert into OutStream;
    """, manager=manager)
    rt.get_input_handler("Vec").send([3.0, 4.0])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [pytest.approx(5.0)]


def test_namespaced_stream_function_extension():
    # '#custom:mag(...)' resolves through the registry under its namespaced
    # name and must not shadow (or be shadowed by) root-namespace built-ins
    manager = SiddhiManager()
    manager.set_extension("streamFunction:custom:mag", Magnitude)
    m, rt, c = build("""
        define stream Vec (x1 double, x2 double);
        from Vec#custom:mag(x1, x2)
        select magnitude
        insert into OutStream;
    """, manager=manager)
    rt.get_input_handler("Vec").send([6.0, 8.0])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [pytest.approx(10.0)]

    with pytest.raises(CompileError, match="custom:log"):
        build("""
            define stream S (v int);
            from S#custom:log('x') select v insert into OutStream;
        """)


def test_post_window_stream_function():
    # #window.length(2)#pol2Cart(...): the transform applies to the
    # window's emitted rows (both CURRENT and EXPIRED)
    m, rt, c = build("""
        define stream PolarStream (theta double, rho double);
        from PolarStream#window.length(2)#pol2Cart(theta, rho)[y > 0.0]
        select y insert all events into OutStream;
    """)
    h = rt.get_input_handler("PolarStream")
    h.send([90.0, 1.0])    # y=1
    h.send([270.0, 1.0])   # y=-1 filtered
    h.send([90.0, 2.0])    # y=2; expired row y=1 passes
    m.shutdown()
    ys = [round(e.data[0], 9) for e in c.events]
    assert ys == [1.0, 2.0, 1.0] or sorted(ys) == [1.0, 1.0, 2.0]
