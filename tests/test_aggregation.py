"""M1 golden tests: group-by + running aggregators (no window).

Mirrors the style of reference ``query/aggregator/*TestCase.java`` — running
aggregates per event, per group, exactly as the sequential engine computes
them.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.stream.output.stream_callback import StreamCallback


class Collect(StreamCallback):
    def __init__(self):
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def run_app(app, stream, rows, out="Out"):
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(app)
    cb = Collect()
    rt.add_callback(out, cb)
    h = rt.get_input_handler(stream)
    for r in rows:
        h.send(r)
    manager.shutdown()
    return [e.data for e in cb.events]


def test_running_sum_count_avg_per_group():
    out = run_app(
        """
        define stream S (symbol string, price double);
        from S select symbol, sum(price) as total, count() as c, avg(price) as a
        group by symbol insert into Out;
        """,
        "S",
        [["IBM", 10.0], ["WSO2", 5.0], ["IBM", 30.0], ["IBM", 2.0], ["WSO2", 1.0]],
    )
    assert out == [
        ["IBM", 10.0, 1, 10.0],
        ["WSO2", 5.0, 1, 5.0],
        ["IBM", 40.0, 2, 20.0],
        ["IBM", 42.0, 3, 14.0],
        ["WSO2", 6.0, 2, 3.0],
    ]


def test_sum_int_returns_long_and_no_groupby():
    out = run_app(
        """
        define stream S (v int);
        from S select sum(v) as s, min(v) as mn, max(v) as mx insert into Out;
        """,
        "S",
        [[5], [3], [9]],
    )
    assert out == [[5, 5, 5], [8, 3, 5], [17, 3, 9]]


def test_batch_send_running_aggregates():
    # several events of the same group inside ONE device batch must still
    # produce sequential running values (segmented scan semantics)
    from siddhi_tpu.core.event import Event

    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (symbol string, v int);
        from S select symbol, sum(v) as s group by symbol insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send([
        Event(timestamp=1, data=["A", 1]),
        Event(timestamp=2, data=["B", 10]),
        Event(timestamp=3, data=["A", 2]),
        Event(timestamp=4, data=["A", 3]),
        Event(timestamp=5, data=["B", 20]),
    ])
    assert [e.data for e in cb.events] == [
        ["A", 1], ["B", 10], ["A", 3], ["A", 6], ["B", 30],
    ]
    manager.shutdown()


def test_having_on_aggregate():
    out = run_app(
        """
        define stream S (symbol string, price double);
        from S select symbol, avg(price) as ap group by symbol
        having ap > 10.0 insert into Out;
        """,
        "S",
        [["A", 5.0], ["A", 25.0], ["B", 50.0], ["A", 2.0]],
    )
    # running avg: A:5 (no), A:15 (yes), B:50 (yes), A:~10.67 (yes)
    assert out[0] == ["A", 15.0]
    assert out[1] == ["B", 50.0]
    assert out[2][0] == "A" and abs(out[2][1] - 32.0 / 3) < 1e-9


def test_stddev_and_bool_aggregators():
    out = run_app(
        """
        define stream S (v double, f bool);
        from S select stdDev(v) as sd, and(f) as allf, or(f) as anyf insert into Out;
        """,
        "S",
        [[2.0, True], [4.0, True], [6.0, False]],
    )
    assert out[0][0] == 0.0 and out[0][1] is True and out[0][2] is True
    assert out[1][0] == 1.0
    assert out[2][1] is False and out[2][2] is True
    # population stddev of (2,4,6) = sqrt(8/3)
    assert abs(out[2][0] - (8.0 / 3.0) ** 0.5) < 1e-9


def test_many_groups_capacity_growth():
    rows = [[f"sym{i % 50}", float(i)] for i in range(200)]
    out = run_app(
        """
        define stream S (symbol string, v double);
        from S select symbol, count() as c group by symbol insert into Out;
        """,
        "S",
        rows,
    )
    # each of the 50 symbols appears 4 times; counts go 1..4
    assert len(out) == 200
    assert out[-1] == ["sym49", 4]
    assert out[49] == ["sym49", 1]
    assert out[50] == ["sym0", 2]


def test_group_by_multiple_attributes():
    out = run_app(
        """
        define stream S (a string, b int, v int);
        from S select a, b, sum(v) as s group by a, b insert into Out;
        """,
        "S",
        [["x", 1, 10], ["x", 2, 20], ["x", 1, 5], ["y", 1, 7]],
    )
    assert out == [["x", 1, 10], ["x", 2, 20], ["x", 1, 15], ["y", 1, 7]]


def test_limit_and_offset_and_orderby():
    from siddhi_tpu.core.event import Event

    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (symbol string, v int);
        from S select symbol, v order by v desc limit 2 insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send([
        Event(timestamp=1, data=["a", 3]),
        Event(timestamp=1, data=["b", 9]),
        Event(timestamp=1, data=["c", 5]),
    ])
    assert [e.data for e in cb.events] == [["b", 9], ["c", 5]]
    manager.shutdown()


def test_min_max_extreme_values_not_null():
    # a datum equal to the fold identity must report, not read as null
    from siddhi_tpu import SiddhiManager, StreamCallback

    class C(StreamCallback):
        def __init__(self):
            super().__init__()
            self.rows = []

        def receive(self, events):
            self.rows.extend(tuple(e.data) for e in events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        from S select max(v) as mx, min(v) as mn insert into Out;
    """)
    c = C()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send([-2147483648])
    h.send([2147483647])
    m.shutdown()
    assert c.rows == [(-2147483648, -2147483648), (2147483647, -2147483648)]
