"""Reference primary-key table corpus — scenarios ported verbatim from
``query/table/PrimaryKeyTableTestCase.java``: @PrimaryKey uniqueness on
insert/update/upsert plus indexed and non-indexed join probes."""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def build_q(app, query="query2"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


PK_SYMBOL = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    @PrimaryKey('symbol')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""

PK_VOLUME = PK_SYMBOL.replace("@PrimaryKey('symbol')", "@PrimaryKey('volume')")


def test_pk_duplicate_insert_keeps_first():
    """primaryKeyTableTest1 (:57-120): a second insert with an existing
    primary key is rejected — the IBM probe still sees volume 100."""
    m, rt, q = build_q(PK_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    stock.send(["IBM", 56.6, 200])     # duplicate PK: dropped
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 100), ("WSO2", 100)]


def test_pk_inequality_join():
    """primaryKeyTableTest2 (:123-185): != probe over a PK table matches
    every other row."""
    m, rt, q = build_q(PK_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol != StockTable.symbol
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["GOOG", 100])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("GOOG", "IBM", 100), ("GOOG", "WSO2", 100)]


def test_pk_numeric_key_range_join():
    """primaryKeyTableTest6 (:409-...): numeric @PrimaryKey('volume') with
    a > probe."""
    m, rt, q = build_q(PK_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume > CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 50])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "WSO2", 200)]


def test_pk_upsert_on_key_then_range_join():
    """primaryKeyTableTest8 (:538-610): `update or insert on volume ==
    StockTable.volume` — the WSO2 row replaces FOO at volume 200."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1')
        from StockStream
        update or insert into StockTable on volume == StockTable.volume;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["FOO", 50.6, 200])
    stock.send(["WSO2", 55.6, 200])    # upsert replaces FOO
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "WSO2", 200)]


def test_pk_violating_update_is_rejected():
    """primaryKeyTableTest10 (:688-762): an update whose new symbol would
    collide with an existing primary key is dropped — the table is
    unchanged afterwards."""
    m, rt, q = build_q(PK_SYMBOL + """
        @info(name = 'query2')
        from UpdateStockStream update StockTable on StockTable.symbol != symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol != StockTable.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 77.6, 200])    # would rewrite WSO2's key to IBM
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("WSO2", 100), ("IBM", 100), ("IBM", 100)]


def test_pk_delete_on_key():
    """primaryKeyTableTest15 (:1076-1152): delete on the primary key, then
    an unconditional join sees only the surviving row."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream DeleteStockStream (symbol string, price float, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from DeleteStockStream delete StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    delete = rt.get_input_handler("DeleteStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["WSO2", 100])
    delete.send(["IBM", 77.6, 200])
    check.send(["FOO", 100])
    m.shutdown()
    got = [tuple(e.data) for e in q.events]
    assert sorted(got[:2]) == [("IBM", 100), ("WSO2", 100)]
    assert got[2:] == [("WSO2", 100)]


def test_pk_in_condition_probe():
    """primaryKeyTableTest21 (:1544-1605): `(symbol==StockTable.symbol) in
    StockTable` — only the WSO2 probe passes."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream[(symbol == StockTable.symbol) in StockTable]
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["BAR", 55.6, 150])
    stock.send(["IBM", 55.6, 100])
    check.send(["FOO", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("WSO2", 100)]


def test_pk_left_outer_join_upsert():
    """primaryKeyTableTest27 (:1930-...): a left-outer self-enrichment
    upsert — misses insert with price 0, hits keep the joined price; the
    three-column in-condition verifies both rows."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream left outer join StockTable
        on UpdateStockStream.comp == StockTable.symbol
        select comp as symbol, ifThenElse(price is null, 0f, price) as price, vol as volume
        update or insert into StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol == StockTable.symbol and volume == StockTable.volume
                               and price == StockTable.price) in StockTable]
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    check.send(["IBM", 100, 155.6])    # no match
    check.send(["WSO2", 100, 155.6])   # wrong price: no match
    update.send(["IBM", 200])          # miss -> insert (IBM, 0f, 200)
    update.send(["WSO2", 300])         # hit  -> update (WSO2, 55.6, 300)
    check.send(["IBM", 200, 0.0])
    check.send(["WSO2", 300, 55.6])
    m.shutdown()
    assert len(q.events) == 2
    assert [e.data[0] for e in q.events] == ["IBM", "WSO2"]
