"""Reference primary-key table corpus — scenarios ported verbatim from
``query/table/PrimaryKeyTableTestCase.java``: @PrimaryKey uniqueness on
insert/update/upsert plus indexed and non-indexed join probes."""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def build_q(app, query="query2"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


PK_SYMBOL = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    @PrimaryKey('symbol')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""

PK_VOLUME = PK_SYMBOL.replace("@PrimaryKey('symbol')", "@PrimaryKey('volume')")


def test_pk_duplicate_insert_keeps_first():
    """primaryKeyTableTest1 (:57-120): a second insert with an existing
    primary key is rejected — the IBM probe still sees volume 100."""
    m, rt, q = build_q(PK_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    stock.send(["IBM", 56.6, 200])     # duplicate PK: dropped
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 100), ("WSO2", 100)]


def test_pk_inequality_join():
    """primaryKeyTableTest2 (:123-185): != probe over a PK table matches
    every other row."""
    m, rt, q = build_q(PK_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol != StockTable.symbol
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["GOOG", 100])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("GOOG", "IBM", 100), ("GOOG", "WSO2", 100)]


def test_pk_numeric_key_range_join():
    """primaryKeyTableTest6 (:409-...): numeric @PrimaryKey('volume') with
    a > probe."""
    m, rt, q = build_q(PK_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume > CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 50])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "WSO2", 200)]


def test_pk_upsert_on_key_then_range_join():
    """primaryKeyTableTest8 (:538-610): `update or insert on volume ==
    StockTable.volume` — the WSO2 row replaces FOO at volume 200."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1')
        from StockStream
        update or insert into StockTable on volume == StockTable.volume;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["FOO", 50.6, 200])
    stock.send(["WSO2", 55.6, 200])    # upsert replaces FOO
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "WSO2", 200)]


def test_pk_violating_update_is_rejected():
    """primaryKeyTableTest10 (:688-762): an update whose new symbol would
    collide with an existing primary key is dropped — the table is
    unchanged afterwards."""
    m, rt, q = build_q(PK_SYMBOL + """
        @info(name = 'query2')
        from UpdateStockStream update StockTable on StockTable.symbol != symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol != StockTable.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 77.6, 200])    # would rewrite WSO2's key to IBM
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("WSO2", 100), ("IBM", 100), ("IBM", 100)]


def test_pk_delete_on_key():
    """primaryKeyTableTest15 (:1076-1152): delete on the primary key, then
    an unconditional join sees only the surviving row."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream DeleteStockStream (symbol string, price float, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from DeleteStockStream delete StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    delete = rt.get_input_handler("DeleteStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["WSO2", 100])
    delete.send(["IBM", 77.6, 200])
    check.send(["FOO", 100])
    m.shutdown()
    got = [tuple(e.data) for e in q.events]
    assert sorted(got[:2]) == [("IBM", 100), ("WSO2", 100)]
    assert got[2:] == [("WSO2", 100)]


def test_pk_in_condition_probe():
    """primaryKeyTableTest21 (:1544-1605): `(symbol==StockTable.symbol) in
    StockTable` — only the WSO2 probe passes."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream[(symbol == StockTable.symbol) in StockTable]
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["BAR", 55.6, 150])
    stock.send(["IBM", 55.6, 100])
    check.send(["FOO", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("WSO2", 100)]


def test_pk_left_outer_join_upsert():
    """primaryKeyTableTest27 (:1930-...): a left-outer self-enrichment
    upsert — misses insert with price 0, hits keep the joined price; the
    three-column in-condition verifies both rows."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream left outer join StockTable
        on UpdateStockStream.comp == StockTable.symbol
        select comp as symbol, ifThenElse(price is null, 0f, price) as price, vol as volume
        update or insert into StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol == StockTable.symbol and volume == StockTable.volume
                               and price == StockTable.price) in StockTable]
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    check.send(["IBM", 100, 155.6])    # no match
    check.send(["WSO2", 100, 155.6])   # wrong price: no match
    update.send(["IBM", 200])          # miss -> insert (IBM, 0f, 200)
    update.send(["WSO2", 300])         # hit  -> update (WSO2, 55.6, 300)
    check.send(["IBM", 200, 0.0])
    check.send(["WSO2", 300, 55.6])
    m.shutdown()
    assert len(q.events) == 2
    assert [e.data[0] for e in q.events] == ["IBM", "WSO2"]


# ---------------------------------------------------------------- round 5:
# the remainder of PrimaryKeyTableTestCase.java (29 scenarios; test35's
# indexing-speed timing race is covered deterministically by
# tests/test_index_probes.py instead)

PK_RANGE_FEED = [("WSO2", 55.6, 200), ("GOOG", 50.6, 50), ("ABC", 5.6, 70)]


def _range_join(op):
    return PK_VOLUME + f"""
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on {op}
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """


def _feed_range(rt):
    stock = rt.get_input_handler("StockStream")
    for row in PK_RANGE_FEED:
        stock.send(list(row))


def test_pk_stream_gt_table_join():
    """primaryKeyTableTest3 (:188-257): check.volume > table.volume probe,
    two probes with per-probe expected splits."""
    m, rt, q = build_q(_range_join("CheckStockStream.volume > StockTable.volume"))
    _feed_range(rt)
    check = rt.get_input_handler("CheckStockStream")
    check.send(["IBM", 100])
    check.send(["FOO", 60])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    assert sorted(rows[:2]) == [("IBM", "ABC", 70), ("IBM", "GOOG", 50)]
    assert rows[2:] == [("FOO", "GOOG", 50)]


def test_pk_table_lt_stream_join():
    """primaryKeyTableTest4 (:260-323): table.volume < check.volume."""
    m, rt, q = build_q(_range_join("StockTable.volume < CheckStockStream.volume"))
    _feed_range(rt)
    rt.get_input_handler("CheckStockStream").send(["IBM", 200])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "GOOG", 50)]


def test_pk_table_le_stream_join():
    """primaryKeyTableTest5 (:326-389): table.volume <= check.volume."""
    m, rt, q = build_q(_range_join("StockTable.volume <= CheckStockStream.volume"))
    _feed_range(rt)
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "GOOG", 50)]


def test_pk_table_ge_stream_join():
    """primaryKeyTableTest7 (:458-521): table.volume >= check.volume."""
    m, rt, q = build_q(_range_join("StockTable.volume >= CheckStockStream.volume"))
    _feed_range(rt)
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "WSO2", 200)]


PK_UPDATE3 = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    @PrimaryKey('{key}')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def test_pk_update_on_key_between_probes():
    """primaryKeyTableTest9 (:594-667): update on symbol key between two
    probe pairs — IBM's volume changes 100 -> 200, WSO2 untouched."""
    m, rt, q = build_q(PK_UPDATE3.format(key="symbol") + """
        @info(name = 'query2') from UpdateStockStream
        update StockTable on StockTable.symbol==symbol;
        @info(name = 'query3') from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    upd = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    upd.send(["IBM", 77.6, 200])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("IBM", 200), ("WSO2", 100)]


def _update_range(update_on, expect_ordered):
    """primaryKeyTableTest11-14 family: range-conditioned updates with a
    numeric PK; probe via check.volume-vs-table.volume joins."""
    app = PK_UPDATE3.format(key="volume") + f"""
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on {update_on};
        @info(name = 'query3') from CheckStockStream join StockTable
        on CheckStockStream.volume >= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;
    """
    m, rt, q = build_q(app, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 200])
    rt.get_input_handler("UpdateStockStream").send(["FOO", 77.6, 200])
    rt.get_input_handler("CheckStockStream").send(["BAR", 200])
    m.shutdown()
    rows = [(round(float(e.data[0]), 4), e.data[1]) for e in q.events]
    return rows


def test_pk_update_le_condition():
    """primaryKeyTableTest11 (:745-823): update on table.volume <= 200
    rewrites BOTH rows' (price, volume) to (77.6, 200) — but volume is the
    PK, so the second write collides and is rejected, leaving one 77.6 row
    and... the reference's expected2 is the ORIGINAL prices (update of PK
    columns that collide is dropped per row)."""
    rows = _update_range("StockTable.volume <= volume",
                         None)
    assert sorted(rows[:2]) == [(55.6, 100), (55.6, 200)]
    assert sorted(rows[2:]) == [(55.6, 100), (55.6, 200)]


def test_pk_update_lt_condition():
    """primaryKeyTableTest12 (:826-904): update on table.volume < 200 would
    move IBM(100) onto the occupied PK 200 — rejected; both rows keep
    their original values."""
    rows = _update_range("StockTable.volume < volume", None)
    assert sorted(rows[:2]) == [(55.6, 100), (55.6, 200)]
    assert sorted(rows[2:]) == [(55.6, 100), (55.6, 200)]


def test_pk_update_ge_condition():
    """primaryKeyTableTest13 (:907-979): update on table.volume >= 200 hits
    WSO2 only (200 -> 77.6/200, same PK: in-place update allowed); the
    probe join is `check.volume <= table.volume`."""
    app = PK_UPDATE3.format(key="volume") + """
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume >= volume;
        @info(name = 'query3') from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;
    """
    m, rt, q = build_q(app, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 200])
    rt.get_input_handler("UpdateStockStream").send(["FOO", 77.6, 200])
    rt.get_input_handler("CheckStockStream").send(["BAR", 200])
    m.shutdown()
    rows = [(round(float(e.data[0]), 4), e.data[1]) for e in q.events]
    assert rows == [(55.6, 200), (77.6, 200)]


def test_pk_update_gt_condition():
    """primaryKeyTableTest14 (:982-1055): update on table.volume > 150
    rewrites WSO2 to (77.6, 150): PK moves 200 -> 150 (unoccupied, allowed);
    the BAR probe at 150 sees (77.6, 150)."""
    app = PK_UPDATE3.format(key="volume") + """
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume > volume;
        @info(name = 'query3') from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;
    """
    m, rt, q = build_q(app, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 150])
    rt.get_input_handler("UpdateStockStream").send(["FOO", 77.6, 150])
    rt.get_input_handler("CheckStockStream").send(["BAR", 150])
    m.shutdown()
    rows = [(round(float(e.data[0]), 4), e.data[1]) for e in q.events]
    assert rows == [(55.6, 200), (77.6, 150)]


PK_DELETE = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream DeleteStockStream (symbol string, price float, volume long);
    @PrimaryKey('{key}')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def _delete_case(key, delete_on, feed, probes_expected):
    app = PK_DELETE.format(key=key) + f"""
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on {delete_on};
        @info(name = 'query3') from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """
    m, rt, q = build_q(app, query="query3")
    stock = rt.get_input_handler("StockStream")
    for row in feed:
        stock.send(list(row))
    check = rt.get_input_handler("CheckStockStream")
    dele = rt.get_input_handler("DeleteStockStream")
    check.send(["WSO2", 100])
    dele.send(["IBM", 77.6, probes_expected["del_vol"]])
    check.send(["FOO", 100])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    n1 = probes_expected["n_before"]
    assert sorted(rows[:n1]) == sorted(probes_expected["before"])
    assert rows[n1:] == probes_expected["after"]


def test_pk_delete_ne_condition():
    """primaryKeyTableTest16 (:1136-1211): delete on symbol != 'IBM'
    removes WSO2; IBM remains."""
    _delete_case(
        "symbol", "StockTable.symbol!=symbol",
        [("WSO2", 55.6, 100), ("IBM", 55.6, 100)],
        {"del_vol": 200, "n_before": 2,
         "before": [("IBM", 100), ("WSO2", 100)], "after": [("IBM", 100)]})


def test_pk_delete_gt_condition():
    """primaryKeyTableTest17 (:1214-1289): delete on table.volume > 150
    removes WSO2(200); IBM(100) remains."""
    _delete_case(
        "volume", "StockTable.volume>volume",
        [("WSO2", 55.6, 200), ("IBM", 55.6, 100)],
        {"del_vol": 150, "n_before": 2,
         "before": [("IBM", 100), ("WSO2", 200)], "after": [("IBM", 100)]})


def test_pk_delete_ge_condition():
    """primaryKeyTableTest18 (:1292-1368): delete on table.volume >= 200."""
    _delete_case(
        "volume", "StockTable.volume>=volume",
        [("WSO2", 55.6, 200), ("IBM", 55.6, 100)],
        {"del_vol": 200, "n_before": 2,
         "before": [("IBM", 100), ("WSO2", 200)], "after": [("IBM", 100)]})


def test_pk_delete_lt_condition():
    """primaryKeyTableTest19 (:1371-1446): delete on table.volume < 150
    removes IBM(100); WSO2(200) remains."""
    _delete_case(
        "volume", "StockTable.volume < volume",
        [("WSO2", 55.6, 200), ("IBM", 55.6, 100)],
        {"del_vol": 150, "n_before": 2,
         "before": [("IBM", 100), ("WSO2", 200)], "after": [("WSO2", 200)]})


def test_pk_delete_le_condition():
    """primaryKeyTableTest20 (:1449-1526): delete on table.volume <= 150
    removes IBM(100) and BAR(150); WSO2(200) remains."""
    _delete_case(
        "volume", "StockTable.volume <= volume",
        [("WSO2", 55.6, 200), ("BAR", 55.6, 150), ("IBM", 55.6, 100)],
        {"del_vol": 150, "n_before": 3,
         "before": [("IBM", 100), ("BAR", 150), ("WSO2", 200)],
         "after": [("WSO2", 200)]})


PK_IN = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    @PrimaryKey('{key}')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def _in_case(key, cond, probes, expected):
    m, rt, q = build_q(PK_IN.format(key=key) + f"""
        @info(name = 'query2')
        from CheckStockStream[{cond}]
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["BAR", 55.6, 150])
    stock.send(["IBM", 55.6, 100])
    check = rt.get_input_handler("CheckStockStream")
    for p in probes:
        check.send(list(p))
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == sorted(expected)


def test_pk_in_ne_condition():
    """primaryKeyTableTest22 (:1592-1654): (symbol != table.symbol) in
    StockTable passes when ANY row differs."""
    _in_case("symbol", "(symbol!=StockTable.symbol) in StockTable",
             [("FOO", 100), ("WSO2", 100)],
             [("FOO", 100), ("WSO2", 100)])


def test_pk_in_gt_condition():
    """primaryKeyTableTest23 (:1657-1719)."""
    _in_case("volume", "(volume > StockTable.volume) in StockTable",
             [("FOO", 170), ("FOO", 500)],
             [("FOO", 170), ("FOO", 500)])


def test_pk_in_lt_condition():
    """primaryKeyTableTest24 (:1722-1782): only 170 < some row (200)."""
    _in_case("volume", "(volume < StockTable.volume) in StockTable",
             [("FOO", 170), ("FOO", 500)],
             [("FOO", 170)])


def test_pk_in_le_condition():
    """primaryKeyTableTest25 (:1785-1846)."""
    _in_case("volume", "(volume <= StockTable.volume) in StockTable",
             [("FOO", 170), ("FOO", 200)],
             [("FOO", 170), ("FOO", 200)])


def test_pk_in_ge_condition():
    """primaryKeyTableTest26 (:1849-1910)."""
    _in_case("volume", "(volume >= StockTable.volume) in StockTable",
             [("FOO", 170), ("FOO", 100)],
             [("FOO", 170), ("FOO", 100)])


def test_pk_unknown_attribute_rejected():
    """primaryKeyTableTest28 (:1992-2014, AttributeNotExistException):
    @PrimaryKey names a non-existent attribute."""
    import pytest

    from tests.test_table_define_corpus import CREATION_ERRORS
    with pytest.raises(CREATION_ERRORS):
        m = SiddhiManager()
        m.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey('symbol1')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable;
        """)


def test_pk_empty_annotation_rejected():
    """primaryKeyTableTest29 (:2017-2040, SiddhiParserException)."""
    import pytest

    from tests.test_table_define_corpus import CREATION_ERRORS
    with pytest.raises(CREATION_ERRORS):
        m = SiddhiManager()
        m.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey()
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable;
        """)


def test_pk_duplicate_annotation_rejected():
    """primaryKeyTableTest31 (:2043-2066, DuplicateAnnotationException):
    two @PrimaryKey annotations on one table."""
    import pytest

    from tests.test_table_define_corpus import CREATION_ERRORS
    with pytest.raises(CREATION_ERRORS):
        m = SiddhiManager()
        m.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey('symbol') @PrimaryKey('price')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable;
        """)


def test_pk_malformed_annotation_rejected():
    """primaryKeyTableTest32 (:2069-2092, SiddhiParserException):
    @PrimaryKey'symbol' without parentheses."""
    import pytest

    from tests.test_table_define_corpus import CREATION_ERRORS
    with pytest.raises(CREATION_ERRORS):
        m = SiddhiManager()
        m.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey'symbol'
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable;
        """)


def test_pk_case_sensitive_attribute_rejected():
    """primaryKeyTableTest33 (:2095-2118, AttributeNotExistException):
    'Symbol' != 'symbol'."""
    import pytest

    from tests.test_table_define_corpus import CREATION_ERRORS
    with pytest.raises(CREATION_ERRORS):
        m = SiddhiManager()
        m.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey ('Symbol')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable;
        """)


COMPOSITE_PK = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    @PrimaryKey('symbol','volume')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def test_pk_composite_key_join():
    """primaryKeyTableTest36 (:2264-2327): ('symbol','volume') composite
    uniqueness — (IBM,100) and (IBM,200) coexist; probe on both keys."""
    m, rt, q = build_q(COMPOSITE_PK + """
        @info(name = 'query2') from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
           and CheckStockStream.volume==StockTable.volume
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    stock.send(["IBM", 56.6, 200])
    check = rt.get_input_handler("CheckStockStream")
    check.send(["IBM", 200])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 200), ("WSO2", 100)]


def test_pk_composite_partial_key_join():
    """primaryKeyTableTest37 (:2330-2394): probing only ONE half of the
    composite key returns every row of that symbol."""
    m, rt, q = build_q(COMPOSITE_PK + """
        @info(name = 'query2') from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    stock.send(["IBM", 56.6, 200])
    check = rt.get_input_handler("CheckStockStream")
    check.send(["IBM", 200])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("IBM", 200), ("WSO2", 100)]


def test_pk_composite_key_and_constant_filter_join():
    """primaryKeyTableTest38 (:2397-2463): composite-key probe AND a
    constant price filter."""
    m, rt, q = build_q(COMPOSITE_PK + """
        @info(name = 'query2') from CheckStockStream join StockTable
        on (CheckStockStream.symbol==StockTable.symbol
            and CheckStockStream.volume==StockTable.volume)
           and 55.6f == StockTable.price
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    for row in [["WSO2", 55.6, 100], ["IBM", 55.6, 100], ["IBM", 55.6, 101],
                ["IBM", 55.6, 102], ["IBM", 55.6, 200]]:
        stock.send(row)
    check = rt.get_input_handler("CheckStockStream")
    check.send(["IBM", 200])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 200), ("WSO2", 100)]


def test_pk_composite_key_and_attr_equal_join():
    """primaryKeyTableTest39 (:2466-2533): composite-key probe AND a
    stream-vs-table price equality."""
    app = COMPOSITE_PK.replace(
        "define stream CheckStockStream (symbol string, volume long);",
        "define stream CheckStockStream (symbol string, price float, volume long);")
    m, rt, q = build_q(app + """
        @info(name = 'query2') from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
           and CheckStockStream.volume==StockTable.volume
           and CheckStockStream.price == StockTable.price
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    for row in [["WSO2", 55.6, 100], ["IBM", 55.6, 100], ["IBM", 55.6, 101],
                ["IBM", 55.6, 102], ["IBM", 55.6, 200]]:
        stock.send(row)
    check = rt.get_input_handler("CheckStockStream")
    check.send(["IBM", 55.6, 200])
    check.send(["WSO2", 55.6, 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 200), ("WSO2", 100)]
