"""Reference time-rate-limit corpus — all 17 scenarios ported verbatim from
``query/ratelimit/TimeOutputRateLimitTestCase.java`` (feeds and expected
counts; Thread.sleep boundaries become playback timestamps, with the limiter
cycle anchored at the first event ts = 1000, so ticks land at 2000, 3000, …).

Semantics under test (reference ``query/output/ratelimit/time/*.java``):
- ``output [all] every T``: accumulate, flush everything on each tick.
- ``output first every T``: emit the window's 1st event immediately, reset
  on tick; group-by variant = first sighting of each group per window.
- ``output last every T``: flush the held last (or last-per-group) on tick.
- With lengthBatch + group-by + `insert all events`, the selector's batched
  group-by map is keyed by group ONLY, so a same-chunk CURRENT overwrites
  the EXPIRED of its group (QuerySelector.java:315-338) — this collapse is
  what produces the reference's remove-counts below.
"""

from siddhi_tpu import SiddhiManager, QueryCallback, StreamCallback


class Counter(QueryCallback):
    def __init__(self):
        self.in_count = 0
        self.remove_count = 0
        self.in_rows = []
        self.remove_rows = []
        self.arrived = False

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.in_count += len(in_events)
            self.in_rows.extend(tuple(e.data) for e in in_events)
        if remove_events:
            self.remove_count += len(remove_events)
            self.remove_rows.extend(tuple(e.data) for e in remove_events)
        self.arrived = True


def build(query_body):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(f"""@app:playback
        define stream LoginEvents (timestamp long, ip string);
        define stream Tick (x int);
        @info(name = 'query1')
        {query_body}
        from Tick select x insert into TickOut;
    """)
    c = Counter()
    rt.add_callback("query1", c)
    rt.start()
    return m, rt, c, rt.get_input_handler("LoginEvents"), rt.get_input_handler("Tick")


def feed(h, ts, ips):
    for ip in ips:
        h.send(ts, [ts, ip])


def test_time_rate_q1_all_every_sec():
    """testTimeOutputRateLimitQuery1 (:52-107): 2+2+1+1 events across four
    1 s windows, each flushed whole on its tick = 6."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip output every 1 sec insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.3"])
    feed(h, 2100, ["192.10.1.9", "192.10.1.4"])
    feed(h, 3200, ["192.10.1.30"])
    feed(h, 5200, ["192.10.1.40"])
    tick.send(6500, [0])
    assert c.arrived and c.remove_count == 0
    assert c.in_count == 6
    m.shutdown()


def test_time_rate_q2_all_keyword_every_sec():
    """testTimeOutputRateLimitQuery2 (:109-164): explicit `output all every
    1 sec`, same flush-per-window accounting = 6."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip output all every 1 sec insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.3"])
    feed(h, 2100, ["192.10.1.9", "192.10.1.4"])
    feed(h, 3200, ["192.10.1.30"])
    feed(h, 4700, ["192.10.1.40"])
    tick.send(6500, [0])
    assert c.arrived and c.remove_count == 0
    assert c.in_count == 6
    m.shutdown()


def test_time_rate_q3_all_bursts():
    """testTimeOutputRateLimitQuery3 (:166-221): bursts of 5 then 3 in
    consecutive windows = 8."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip output every 1 sec insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.arrived and c.remove_count == 0
    assert c.in_count == 8
    m.shutdown()


def test_time_rate_q4_first_every_sec():
    """testTimeOutputRateLimitQuery4 (:223-280): first of each window:
    .5 (w1), .9 (w2), .30 (w3) = 3."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip output first every 1 sec insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.3"])
    feed(h, 2100, ["192.10.1.9", "192.10.1.4"])
    feed(h, 3200, ["192.10.1.30"])
    tick.send(4500, [0])
    assert c.in_count == 3 and c.remove_count == 0
    assert [r[0] for r in c.in_rows] == ["192.10.1.5", "192.10.1.9", "192.10.1.30"]
    m.shutdown()


def test_time_rate_q5_last_every_sec():
    """testTimeOutputRateLimitQuery5 (:282-339): last of each window flushed
    on its tick: .3, .4, .30 = 3 (reference asserts >= 3 for timing slop;
    playback is exact)."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip output last every 1 sec insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.3"])
    feed(h, 2100, ["192.10.1.9", "192.10.1.4"])
    feed(h, 3200, ["192.10.1.30"])
    tick.send(4500, [0])
    assert c.in_count == 3 and c.remove_count == 0
    assert [r[0] for r in c.in_rows] == ["192.10.1.3", "192.10.1.4", "192.10.1.30"]
    m.shutdown()


def test_time_rate_q6_group_by_first():
    """testTimeOutputRateLimitQuery6 (:341-398): first-per-group per window:
    {.5,.3,.9,.4} then {.4,.30} = 6."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip group by ip output first every 1 sec "
        "insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 6 and c.remove_count == 0
    m.shutdown()


def test_time_rate_q7_group_by_last():
    """testTimeOutputRateLimitQuery7 (:400-457): last-per-group flushed per
    window: {.5,.3,.9,.4} then {.4,.30} = 6."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip group by ip output last every 1 sec "
        "insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 6 and c.remove_count == 0
    m.shutdown()


def test_time_rate_q8_batch_window_group_by_last():
    """testTimeOutputRateLimitQuery8 (:459-516): lengthBatch(2) batched
    group-by emits one current per group per batch; window flushes
    {.5,.3,.9} then {.4,.30} = 5."""
    m, rt, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(2) select ip group by ip "
        "output last every 1 sec insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 5 and c.remove_count == 0
    m.shutdown()


def test_time_rate_q9_batch_window_group_by_last_expired():
    """testTimeOutputRateLimitQuery9 (:518-575): `insert expired events`
    admits only EXPIRED selector outputs; windows flush {.5} then
    {.3,.9,.4} = 4 removes, zero currents."""
    m, rt, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(2) select ip group by ip "
        "output last every 1 sec insert expired events into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 0
    assert c.remove_count == 4
    m.shutdown()


def test_time_rate_q10_batch_window_group_by_first_expired():
    """testTimeOutputRateLimitQuery10 (:577-633): first-per-group over the
    expired-only stream: {.5} then {.3,.9,.4} = 4 removes."""
    m, rt, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(2) select ip, count() as total "
        "group by ip output first every 1 sec insert expired events into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 0
    assert c.remove_count == 4
    m.shutdown()


def test_time_rate_q11_batch_window_group_by_first_all_events():
    """testTimeOutputRateLimitQuery11 (:636-695): `insert all events` —
    first sighting of each group per window, expired or current: w1 emits
    cur .5, cur .3, cur .9 (exp .5 is a repeat sighting); w2 emits exp .3,
    exp .9, cur .4, cur .30 (the batch-collapse removed exp .4):
    in=5, remove=2."""
    m, rt, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(2) select ip, count() as total "
        "group by ip output first every 1 sec insert all events into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 5
    assert c.remove_count == 2
    m.shutdown()


def test_time_rate_q12_batch_window_group_by_last_all_events():
    """testTimeOutputRateLimitQuery12 (:697-756): last-per-group with type
    kept: w1 flush {.5:exp, .3:cur, .9:cur}; w2 flush {.3:exp, .9:exp,
    .4:cur, .30:cur} -> in=4, remove=3."""
    m, rt, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(2) select ip, count() as total "
        "group by ip output last every 1 sec insert all events into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 4
    assert c.remove_count == 3
    m.shutdown()


def test_time_rate_q13_batch_window_group_by_all_all_events():
    """testTimeOutputRateLimitQuery13 (:758-817): accumulate-everything per
    window: w1 = 3 cur + 1 exp, w2 = 3 cur + 2 exp (exp .4 collapsed away
    by the same-chunk current) -> in=6, remove=3."""
    m, rt, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(2) select ip, count() as total "
        "group by ip output all every 1 sec insert all events into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.4", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 6
    assert c.remove_count == 3
    m.shutdown()


def test_time_rate_q14_partitioned_group_by_last():
    """testTimeOutputRateLimitQuery14 (:819-873): partition by symbol +
    group-by + last every 1 sec, StreamCallback: one flush per window =
    .3, .4, .30 (3 events)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream LoginEvents (timestamp long, ip string, symbol string);
        define stream Tick (x int);
        partition with (symbol of LoginEvents) begin
          @info(name = 'query1')
          from LoginEvents
          select ip
          group by symbol
          output last every 1 sec
          insert into uniqueIps;
        end;
        from Tick select x insert into TickOut;
    """)
    rows = []
    cb = StreamCallback()
    cb.receive = lambda events: rows.extend(tuple(e.data) for e in events)
    rt.add_callback("uniqueIps", cb)
    rt.start()
    h = rt.get_input_handler("LoginEvents")
    tick = rt.get_input_handler("Tick")
    h.send(1000, [1000, "192.10.1.5", "WSO2"])
    h.send(1000, [1000, "192.10.1.3", "WSO2"])
    h.send(2100, [2100, "192.10.1.9", "WSO2"])
    h.send(2100, [2100, "192.10.1.4", "WSO2"])
    h.send(3200, [3200, "192.10.1.30", "WSO2"])
    tick.send(4500, [0])
    assert [r[0] for r in rows] == ["192.10.1.3", "192.10.1.4", "192.10.1.30"]
    m.shutdown()


def test_time_rate_q15_first_emits_immediately():
    """testTimeOutputRateLimitQuery15 (:875-928): `output first every 1 sec`
    emits the very first event synchronously — asserted BEFORE any tick."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip, count() as total output first every 1 sec "
        "insert all events into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    assert c.arrived
    assert c.in_count == 1
    assert c.remove_count == 0
    m.shutdown()


def test_time_rate_q16_group_by_first_emits_immediately():
    """testTimeOutputRateLimitQuery16 (:930-984): group-by first emits each
    new group synchronously: 4 groups -> in=4 before any tick."""
    m, rt, c, h, tick = build(
        "from LoginEvents select ip, count() as total group by ip "
        "output first every 1 sec insert all events into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    assert c.arrived
    assert c.in_count == 4
    assert c.remove_count == 0
    m.shutdown()


def test_time_rate_q17_batch_window_group_by_first_currents():
    """testTimeOutputRateLimitQuery17 (:986-1045): lengthBatch(2) + group-by
    + first, currents only: w1 emits .5,.3,.9; w2 emits .4 (batch3), .5 and
    .30 (batch4) -> in=6, remove=0."""
    m, rt, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(2) select ip, count() as total "
        "group by ip output first every 1 sec insert into uniqueIps;")
    feed(h, 1000, ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
                   "192.10.1.4"])
    feed(h, 2100, ["192.10.1.4", "192.10.1.5", "192.10.1.30"])
    tick.send(3500, [0])
    assert c.in_count == 6
    assert c.remove_count == 0
    m.shutdown()
