"""Reference order-by/limit/offset corpus — scenarios ported verbatim
from ``query/OrderByLimitTestCase.java`` (per-flush chunk sizes and
total counts over lengthBatch/length windows)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback

FEED8 = [
    ["IBM", 700.0, 0], ["WSO2", 60.5, 1], ["WSO2", 60.5, 2],
    ["WSO2", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
    ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
]


class Chunks(QueryCallback):
    def __init__(self):
        self.chunks = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.chunks.append([tuple(e.data) for e in in_events])


def _run(query, feed):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        f"@info(name = 'query1') {query}")
    q = Chunks()
    rt.add_callback("query1", q)
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    for r in feed:
        h.send(list(r))
    m.shutdown()
    return q.chunks


def test_limit_on_length_batch():
    """limitTest1 (:52-92): limit 2 caps each 4-event flush at 2."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, price, volume limit 2 insert into outputStream;",
        FEED8)
    assert [len(c) for c in chunks] == [2, 2]


def test_order_by_then_limit():
    """limitTest2 (:95-136): order by symbol, limit 3 — the first three in
    symbol order per flush."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, price, volume order by symbol limit 3 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["WSO2", 60.5, 1], ["AAA", 60.5, 2],
            ["IBM", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["IBM", 601.5, 6], ["BBB", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [3, 3]
    assert [r[0] for r in chunks[0]] == ["AAA", "IBM", "IBM"]
    assert [r[0] for r in chunks[1]] == ["BBB", "IBM", "IBM"]


def test_limit_with_ungrouped_aggregate():
    """limitTest3 (:139-179): an ungrouped sum collapses each flush to one
    row; limit 2 leaves it alone."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, sum(price) as totalPrice, volume limit 2 "
        "insert into outputStream;",
        FEED8)
    assert [len(c) for c in chunks] == [1, 1]


def test_order_by_with_ungrouped_aggregate():
    """limitTest4 (:182-223)."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, sum(price) as totalPrice, volume "
        "order by symbol limit 2 insert into outputStream;",
        FEED8)
    assert [len(c) for c in chunks] == [1, 1]


def test_order_by_two_keys():
    """limitTest5 (:226-268): order by price, totalVolume with group by
    symbol; limit 2 per flush."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, sum(volume) as totalVolume, volume, price "
        "group by symbol order by price, totalVolume limit 2 "
        "insert into outputStream;",
        [
            ["IBM", 60.5, 0], ["WSO2", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 60.5, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [2, 2]


def test_group_by_order_by_aggregate():
    """limitTest6 (:271-313): group-by flush rows ordered by totalPrice,
    limited to 2."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, sum(price) as totalPrice, volume "
        "group by symbol order by totalPrice limit 2 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["WSO2", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [2, 2]


def test_group_by_without_aggregate():
    """limitTest7 (:316-357): group by without an aggregate keeps the last
    row per group; limit 2."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, price, volume group by symbol order by price "
        "limit 2 insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [2, 2]


def test_sliding_window_limit_per_event():
    """limitTest9 (:362-402): a sliding length window emits per event;
    limit 2 never binds on 1-row chunks (8 outputs)."""
    chunks = _run(
        "from cseEventStream#window.length(4) "
        "select symbol, price, volume group by symbol order by price "
        "limit 2 insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [1] * 8


def test_order_by_desc():
    """limitTest10 (:406-447): order by totalPrice desc, limit 2 — the two
    biggest groups lead each flush."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, sum(price) as totalPrice, volume "
        "group by symbol order by totalPrice desc limit 2 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 7060.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [2, 2]
    assert chunks[0][0][0] == "WSO2"  # 7060.5 leads descending


def test_order_by_asc_sliding():
    """limitTest11 (:451-490): explicit `asc`, sliding window — 8 1-row
    chunks."""
    chunks = _run(
        "from cseEventStream#window.length(4) "
        "select symbol, price, volume order by price asc limit 2 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [1] * 8


def test_offset_drops_leading_rows():
    """limitTest12 (:494-536): offset 1 drops the top group per flush."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, sum(price) as totalPrice, volume "
        "group by symbol order by totalPrice desc offset 1 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 7060.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["XYZ", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [2, 2]


def test_offset_without_limit():
    """limitTest13 (:540-578): offset 2 on 4-row flushes leaves 2."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, price, volume order by price asc offset 2 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [2, 2]


def test_limit_and_offset():
    """limitTest14 (:583-625): limit 1 offset 1 — the runner-up group."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, sum(price) as totalPrice, volume "
        "group by symbol order by totalPrice desc limit 1 offset 1 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 7060.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["XYZ", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [1, 1]


def test_limit_and_offset_plain():
    """limitTest15 (:629-669): limit 2 offset 2 over 4-row flushes."""
    chunks = _run(
        "from cseEventStream#window.lengthBatch(4) "
        "select symbol, price, volume order by price asc limit 2 offset 2 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [2, 2]


def test_offset_beyond_chunk_silences_sliding():
    """limitTest16 (:673-712): sliding 1-row chunks with offset 1 emit
    nothing."""
    chunks = _run(
        "from cseEventStream#window.length(4) "
        "select symbol, price, volume order by price asc limit 1 offset 1 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert chunks == []


def test_offset_zero_is_noop():
    """limitTest17 (:715-756): offset 0 changes nothing — 8 chunks."""
    chunks = _run(
        "from cseEventStream#window.length(4) "
        "select symbol, price, volume order by price asc limit 1 offset 0 "
        "insert into outputStream;",
        [
            ["IBM", 700.0, 0], ["IBM", 60.5, 1], ["WSO2", 60.5, 2],
            ["XYZ", 60.5, 3], ["IBM", 700.0, 4], ["WSO2", 60.5, 5],
            ["WSO2", 60.5, 6], ["WSO2", 60.5, 7],
        ])
    assert [len(c) for c in chunks] == [1] * 8


@pytest.mark.parametrize("clause", ["limit -1 offset 0", "limit 1 offset -1"])
def test_negative_limit_offset_rejected(clause):
    """limitTest18/19 (:758-827): negative limit or offset fails at
    creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (symbol string, price float, "
            "volume long);"
            "@info(name = 'query1') from cseEventStream#window.length(4) "
            f"select symbol, price, volume order by price asc {clause} "
            "insert into outputStream;")
    m.shutdown()


def test_on_demand_string_order_by():
    """On-demand reads order string columns lexicographically too (same
    rank-table path as live queries)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (sym string, v int);"
        "define table T (sym string, v int);"
        "@info(name = 'q') from S insert into T;")
    rt.start()
    h = rt.get_input_handler("S")
    for row in [["zeta", 1], ["alpha", 2], ["mid", 3]]:
        h.send(row)
    events = rt.query("from T select sym, v order by sym;")
    assert [e.data[0] for e in events] == ["alpha", "mid", "zeta"]
    events = rt.query("from T select sym, v order by sym desc;")
    assert [e.data[0] for e in events] == ["zeta", "mid", "alpha"]
    m.shutdown()
