"""Reference sequence-corpus differential: scenarios ported verbatim from
``query/sequence/SequenceTestCase.java`` — Kleene ``*``/``+``/``?``
quantifiers, or-joined steps, and multi-stream chains, with the exact
inputs and expected outputs."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutputStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


TWO = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
"""


def _rows(c):
    return [tuple(round(v, 4) if isinstance(v, float) else v
                  for v in e.data) for e in c.events]


def test_seq1_basic_two_step():
    # SequenceTestCase.testQuery1: ',' sequence, one match, no re-arm
    m, rt, c = build(TWO + """
        from e1=Stream1[price>20], e2=Stream2[price>e1.price]
        select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["IBM", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "IBM")]


def test_seq3_trailing_star_completes_eagerly():
    # testQuery3: `every e1, e2*` — a trailing min-0 Kleene star is
    # already satisfied at e1, so each e1 match EMITS immediately with
    # an empty collection (reference processMinCountReached at min 0)
    m, rt, c = build(TWO + """
        from every e1=Stream1[price>20], e2=Stream2[price>e1.price]*
        select e1.symbol as s1, e2[0].symbol as s2, e2[1].symbol as s3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(1000, ["WSO2", 55.6, 100])
    s1.send(1100, ["IBM", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", None, None), ("IBM", None, None)]


def test_seq5_leading_star_collects_then_reference():
    # testQuery5: `every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]`
    m, rt, c = build(TWO + """
        from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]
        select e1[0].price as p1, e1[1].price as p2, e2.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 59.6, 100])
    s2.send(1100, ["WSO2", 55.6, 100])
    s2.send(1200, ["IBM", 55.7, 100])
    s1.send(1300, ["WSO2", 57.6, 100])
    m.shutdown()
    assert _rows(c) == [(55.6, 55.7, 57.6)]


def test_seq7_optional_question_mark():
    # testQuery7: `every e1=Stream2[price>20]?, e2=Stream1[price>e1[0].price]`
    m, rt, c = build(TWO + """
        from every e1=Stream2[price>20]?, e2=Stream1[price>e1[0].price]
        select e1[0].price as p1, e2.price as p3 insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 59.6, 100])
    s2.send(1100, ["WSO2", 55.6, 100])
    s2.send(1200, ["IBM", 55.7, 100])
    s1.send(1300, ["WSO2", 57.6, 100])
    m.shutdown()
    assert _rows(c) == [(55.7, 57.6)]


def test_seq8_or_joined_second_step():
    # testQuery8: `every e1, e2[...] or e3[symbol=='IBM']` — two matches
    m, rt, c = build(TWO + """
        from every e1=Stream2[price>20],
             e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM']
        select e1.price as p1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(1000, ["WSO2", 59.6, 100])
    s2.send(1100, ["WSO2", 55.6, 100])
    s2.send(1200, ["IBM", 55.7, 100])
    s2.send(1300, ["WSO2", 57.6, 100])
    m.shutdown()
    got = _rows(c)
    assert len(got) == 2
    assert (55.6, 55.7, None) in got
    assert (55.7, 57.6, None) in got


def test_seq10_plus_requires_one():
    # testQuery10: `every e1=Stream2[price>20]+, e2=Stream1[price>e1[0].price]`
    m, rt, c = build(TWO + """
        from every e1=Stream2[price>20]+, e2=Stream1[price>e1[0].price]
        select e1[0].price as p1, e1[1].price as p2, e2.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 59.6, 100])
    s2.send(1100, ["WSO2", 55.6, 100])
    s1.send(1200, ["WSO2", 57.6, 100])
    m.shutdown()
    assert _rows(c) == [(55.6, None, 57.6)]


def test_seq13_mid_star_between_filters():
    # testQuery13 (one-stream form): e1[hi], e2[low]*, e3[vol<=70]
    m, rt, c = build("""@app:playback
        define stream StockStream (symbol string, price float, volume int);
        from every e1=StockStream[ price >= 50 and volume > 100 ],
             e2=StockStream[price <= 40]*,
             e3=StockStream[volume <= 70]
        select e1.symbol as s1, e2[0].symbol as s2, e3.symbol as s3
        insert into OutputStream;
    """)
    h = rt.get_input_handler("StockStream")
    h.send(1000, ["IBM", 75.6, 105])
    h.send(1100, ["GOOG", 21.0, 81])
    h.send(1200, ["WSO2", 176.6, 65])
    m.shutdown()
    assert _rows(c) == [("IBM", "GOOG", "WSO2")]
