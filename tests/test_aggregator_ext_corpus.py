"""Reference attribute-aggregator corpus — scenarios ported verbatim from
``query/aggregator/``: And/Or over lengthBatch flushes, maxForever/
minForever running extremes, and arg-validation errors."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback


class Collect(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


def _run(app, stream, feed, out="outputStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collect()
    rt.add_callback(out, c)
    rt.start()
    h = rt.get_input_handler(stream)
    for r in feed:
        h.send(list(r))
    m.shutdown()
    return c.rows


CSC = "define stream cscStream(messageID string, isFraud bool, price double);"


def test_and_true_only():
    """testAndAggregatorTrueOnlyScenario (AndAggregatorExtension:49-95)."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(3) "
        "select messageID, and(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", True, 35.75]] * 3)
    assert rows == [("messageId1", True)]


def test_and_false_only():
    """testAndAggregatorFalseOnlyScenario (:97-143)."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(4) "
        "select messageID, and(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", False, 35.75]] * 4)
    assert rows == [("messageId1", False)]


def test_and_mixed():
    """testAndAggregatorTrueFalseScenario (:145-191)."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(4) "
        "select messageID, and(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", False, 35.75], ["messageId1", True, 35.75],
         ["messageId1", False, 35.75], ["messageId1", True, 35.75]])
    assert rows == [("messageId1", False)]


def test_and_two_batches():
    """testAndAggregatorMoreEventsBatchScenario (:193-241): each flush
    re-evaluates from its own events."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(2) "
        "select messageID, and(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", False, 35.75], ["messageId1", True, 35.75],
         ["messageId1", True, 35.75], ["messageId1", True, 35.75]])
    assert rows == [("messageId1", False), ("messageId1", True)]


def test_or_true_only():
    """testOrAggregatorTrueOnlyScenario (OrAggregatorExtension:49-95)."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(3) "
        "select messageID, or(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", True, 35.75]] * 3)
    assert rows == [("messageId1", True)]


def test_or_false_only():
    """testOrAggregatorFalseOnlyScenario (:97-143)."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(4) "
        "select messageID, or(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", False, 35.75]] * 4)
    assert rows == [("messageId1", False)]


def test_or_mixed():
    """testOrAggregatorTrueFalseScenario (:145-191)."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(4) "
        "select messageID, or(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", False, 35.75], ["messageId1", True, 35.75],
         ["messageId1", False, 35.75], ["messageId1", True, 35.75]])
    assert rows == [("messageId1", True)]


def test_or_two_batches():
    """testORAggregatorMoreEventsBatchScenario (:193-243)."""
    rows = _run(
        CSC + "@info(name = 'query1') from cscStream#window.lengthBatch(2) "
        "select messageID, or(isFraud) as isValidTransaction "
        "group by messageID insert all events into outputStream;",
        "cscStream",
        [["messageId1", False, 35.75], ["messageId1", False, 35.75],
         ["messageId1", True, 35.75], ["messageId1", True, 35.75]])
    assert rows == [("messageId1", False), ("messageId1", True)]


@pytest.mark.parametrize("agg", ["and", "or"])
def test_bool_aggregator_rejects_non_bool(agg):
    """andAggregatorTest5 / orAggregatorTest1 (:243+): and/or over a
    string attribute fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (name string, isFraud bool);"
            "@info(name = 'query1') from cseEventStream#window.lengthBatch(2) "
            f"select {agg}(name) as x insert into outputStream;")
    m.shutdown()


def test_max_forever_double():
    """testMaxForeverAggregatorExtension1 (MaxForever:47-110): running
    max that never expires — windowless per-event outputs."""
    rows = _run(
        "define stream inputStream (price1 double,price2 double, "
        "price3 double);"
        "@info(name = 'query1') from inputStream "
        "select maxForever(price1) as maxForeverValue "
        "insert into outputStream;",
        "inputStream",
        [[36.0, 36.75, 35.75], [37.88, 38.12, 37.62], [39.00, 39.25, 38.62],
         [36.88, 37.75, 36.75], [38.12, 38.12, 37.75], [38.12, 40.0, 37.75]])
    assert [r[0] for r in rows] == [36.0, 37.88, 39.0, 39.0, 39.0, 39.0]


def test_max_forever_int():
    """testMaxForeverAggregatorExtension2 (:112-162)."""
    rows = _run(
        "define stream inputStream (price1 int,price2 int, price3 int);"
        "@info(name = 'query1') from inputStream "
        "select maxForever(price1) as maxForeverValue "
        "insert into outputStream;",
        "inputStream",
        [[36, 38, 74], [78, 38, 37], [9, 39, 38]])
    assert [r[0] for r in rows] == [36, 78, 78]


def test_min_forever_double():
    """testMinForeverAggregatorExtension1 (MinForever:47-110)."""
    rows = _run(
        "define stream inputStream (price1 double,price2 double, "
        "price3 double);"
        "@info(name = 'query1') from inputStream "
        "select minForever(price1) as minForeverValue "
        "insert into outputStream;",
        "inputStream",
        [[36.0, 36.75, 35.75], [37.88, 38.12, 37.62], [39.00, 39.25, 38.62],
         [35.88, 37.75, 36.75]])
    assert [r[0] for r in rows] == [36.0, 36.0, 36.0, 35.88]


def test_min_forever_survives_window_expiry():
    """minForever keeps the all-time extreme even when the carrying event
    expires from a sliding window (the 'forever' semantics)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (v double);"
        "@info(name = 'query1') from S#window.length(2) "
        "select minForever(v) as mn insert into outputStream;")
    c = Collect()
    rt.add_callback("outputStream", c)
    rt.start()
    h = rt.get_input_handler("S")
    for v in [5.0, 9.0, 8.0, 7.0]:   # 5.0 expires after the 3rd event
        h.send([v])
    m.shutdown()
    assert [r[0] for r in c.rows] == [5.0, 5.0, 5.0, 5.0]


@pytest.mark.parametrize("sel", [
    "max(weight, deviceId)",        # MaxAggregatorExtension:105-143
    "min(weight, deviceId)",        # :144-182
    "maxForever(weight, deviceId)",  # MaxForever:279+
    "minForever(weight, deviceId)",  # MinForever:278+
])
def test_extreme_aggregators_reject_two_args(sel):
    """max/min/maxForever/minForever accept exactly one argument."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (weight double, deviceId string);"
            "@info(name = 'query1') from cseEventStream#window.lengthBatch(5) "
            f"select {sel} as m insert into outputStream;")
    m.shutdown()
