"""Table / named-window / trigger / on-demand-query tests — modeled on the
reference ``query/table/*``, ``core/window/*`` and ``query/trigger/*``
corpora."""

import time

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out=None):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    if out:
        runtime.add_callback(out, collector)
    return manager, runtime, collector


def test_insert_and_on_demand_find():
    m, rt, _ = build("""
        define stream StockStream (symbol string, price float, volume long);
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
    """)
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.5, 100])
    h.send(["IBM", 75.5, 200])
    rows = rt.query("from StockTable select symbol, price, volume")
    got = sorted(tuple(e.data) for e in rows)
    assert got == [("IBM", 75.5, 200), ("WSO2", 55.5, 100)]
    # condition + aggregation
    rows = rt.query("from StockTable on price > 60 select count() as c")
    assert rows[0].data == [1]
    m.shutdown()


def test_delete_from_table():
    m, rt, _ = build("""
        define stream StockStream (symbol string, price float);
        define stream DeleteStream (symbol string);
        define table StockTable (symbol string, price float);
        from StockStream insert into StockTable;
        from DeleteStream delete StockTable on StockTable.symbol == symbol;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.5])
    rt.get_input_handler("StockStream").send(["IBM", 75.5])
    rt.get_input_handler("DeleteStream").send(["WSO2"])
    rows = rt.query("from StockTable select symbol")
    assert [e.data for e in rows] == [["IBM"]]
    m.shutdown()


def test_update_table():
    m, rt, _ = build("""
        define stream UpdateStockStream (symbol string, price float);
        define stream StockStream (symbol string, price float);
        define table StockTable (symbol string, price float);
        from StockStream insert into StockTable;
        from UpdateStockStream
        update StockTable set StockTable.price = price
        on StockTable.symbol == symbol;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.5])
    rt.get_input_handler("StockStream").send(["IBM", 75.5])
    rt.get_input_handler("UpdateStockStream").send(["IBM", 100.5])
    rows = rt.query("from StockTable select symbol, price")
    assert sorted(tuple(e.data) for e in rows) == [("IBM", 100.5), ("WSO2", 55.5)]
    m.shutdown()


def test_update_or_insert():
    m, rt, _ = build("""
        define stream S (symbol string, price float);
        define table T (symbol string, price float);
        from S update or insert into T set T.price = price
        on T.symbol == symbol;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1.5])       # insert
    h.send(["B", 2.5])       # insert
    h.send(["A", 9.5])       # update
    rows = rt.query("from T select symbol, price")
    assert sorted(tuple(e.data) for e in rows) == [("A", 9.5), ("B", 2.5)]
    m.shutdown()


def test_join_with_table():
    m, rt, c = build("""
        define stream StockStream (symbol string, price float);
        define stream CheckStream (symbol string);
        define table StockTable (symbol string, price float);
        from StockStream insert into StockTable;
        from CheckStream join StockTable
        on CheckStream.symbol == StockTable.symbol
        select CheckStream.symbol as symbol, StockTable.price as price
        insert into OutStream;
    """, out="OutStream")
    rt.get_input_handler("StockStream").send(["WSO2", 55.5])
    rt.get_input_handler("CheckStream").send(["WSO2"])
    rt.get_input_handler("CheckStream").send(["IBM"])     # no match
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("WSO2", 55.5)]


def test_named_window_shared():
    # two queries aggregate over one shared window's emissions
    m, rt, c = build("""
        define stream S (symbol string, price float);
        define window W (symbol string, price float) length(2) output all events;
        from S insert into W;
        from W select symbol, sum(price) as total insert into OutStream;
    """, out="OutStream")
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["A", 2.0])
    h.send(["A", 4.0])    # window slides: 1.0 expires -> total 6-1=... sum over window = 2+4
    m.shutdown()
    totals = [e.data[1] for e in c.events if not e.is_expired]
    assert totals[:2] == [1.0, 3.0]
    assert totals[-1] == 6.0  # CURRENT for 4.0 arrives after expired 1.0
    got_final = [e.data[1] for e in c.events][-1]
    assert got_final == 6.0


def test_named_window_join():
    m, rt, c = build("""
        define stream S (symbol string, price float);
        define stream Check (symbol string);
        define window W (symbol string, price float) length(10) output all events;
        from S insert into W;
        from Check join W on Check.symbol == W.symbol
        select Check.symbol as symbol, W.price as price
        insert into OutStream;
    """, out="OutStream")
    rt.get_input_handler("S").send(["X", 7.5])
    rt.get_input_handler("Check").send(["X"])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("X", 7.5)]


def test_trigger_at_start():
    m, rt, c = build("""
        define trigger T at 'start';
        from T select triggered_time insert into OutStream;
    """, out="OutStream")
    rt.start()
    m.shutdown()
    assert len(c.events) == 1
    assert isinstance(c.events[0].data[0], int)


def test_trigger_periodic():
    m, rt, c = build("""
        define trigger T at every 100 milliseconds;
        from T select triggered_time insert into OutStream;
    """, out="OutStream")
    rt.start()
    # wall-clock trigger: poll with a deadline instead of one fixed sleep
    # (a cold jit compile of the pass-through step can eat several 100ms
    # periods on a loaded machine)
    deadline = time.time() + 15.0
    while len(c.events) < 2 and time.time() < deadline:
        time.sleep(0.05)
    m.shutdown()
    assert len(c.events) >= 2


def test_named_window_side_triggers_join():
    # reference semantics: events arriving into a named window trigger the
    # join too (WindowWindowProcessor side is event-driven)
    m, rt, c = build("""
        define stream S (symbol string, price float);
        define stream Check (symbol string);
        define window W (symbol string, price float) length(10) output all events;
        from S insert into W;
        from Check#window.length(10) join W on Check.symbol == W.symbol
        select Check.symbol as symbol, W.price as price
        insert into OutStream;
    """, out="OutStream")
    rt.get_input_handler("Check").send(["X"])      # nothing in W yet
    rt.get_input_handler("S").send(["X", 7.5])     # W emission triggers join
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("X", 7.5)]


def test_table_table_join_rejected():
    import pytest
    from siddhi_tpu.ops.expressions import CompileError
    m = SiddhiManager()
    with pytest.raises(CompileError):
        m.create_siddhi_app_runtime("""
            define table T1 (a int); define table T2 (a int);
            from T1 join T2 on T1.a == T2.a select T1.a as a insert into O;
        """)
    m.shutdown()


def test_update_or_insert_renamed_attrs():
    # insert fallback maps positionally even when names differ
    m, rt, _ = build("""
        define stream S (sym string, pr float);
        define table T (symbol string, price float);
        from S update or insert into T set T.price = pr on T.symbol == sym;
    """)
    h = rt.get_input_handler("S")
    h.send(["B", 1.5])
    h.send(["Q", 9.0])
    h.send(["B", 4.5])
    rows = rt.query("from T select symbol, price")
    assert sorted(tuple(e.data) for e in rows) == [("B", 4.5), ("Q", 9.0)]
    m.shutdown()
