"""Guarded-by runtime contracts (analysis/guards.py — R8's runtime
half): planted unlocked accesses raise under SIDDHI_TPU_SANITIZE=1,
everything is plain attributes with it off, and the descriptors are
transparent to the values they hold (pytrees round-trip untouched).

``guarded()`` reads the env at class-definition time (the same
construction-time gate as ``make_lock``), so each test defines its
plant class locally under monkeypatched env."""

from __future__ import annotations

import os
import subprocess
import sys
import threading

import pytest

from siddhi_tpu.analysis.guards import GuardViolation, _GuardedField, guarded
from siddhi_tpu.analysis.locks import make_lock

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _plant():
    """A threaded-class stand-in with one guarded field, defined under
    whatever env the caller monkeypatched."""

    @guarded
    class Table:
        GUARDED_BY = {"_pending": "pump"}

        def __init__(self):
            self._lock = make_lock("pump")
            self._pending = {}

        def put(self, k, v):
            with self._lock:
                self._pending[k] = v

        def get(self, k):
            with self._lock:
                return self._pending.get(k)

    return Table


# ----------------------------------------------------------- armed (on)

@pytest.fixture
def sanitized(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_SANITIZE", "1")


def test_unlocked_read_raises(sanitized):
    t = _plant()()
    t.put("a", 1)
    with pytest.raises(GuardViolation, match="unlocked read.*_pending"):
        _ = t._pending


def test_unlocked_write_raises(sanitized):
    t = _plant()()
    with pytest.raises(GuardViolation, match="unlocked write.*_pending"):
        t._pending = {}


def test_locked_access_passes(sanitized):
    t = _plant()()
    t.put("a", 1)
    assert t.get("a") == 1
    with t._lock:
        t._pending["b"] = 2     # direct access under the lock is fine
        assert t._pending == {"a": 1, "b": 2}


def test_constructor_is_exempt(sanitized):
    # __init__ populated _pending without the lock and did not raise
    t = _plant()()
    assert t.get("missing") is None


def test_violation_is_per_thread(sanitized):
    """Holding the rank on THIS thread does not license another."""
    t = _plant()()
    errs = []

    def other():
        try:
            _ = t._pending
        except GuardViolation as e:
            errs.append(e)

    with t._lock:
        th = threading.Thread(target=other)
        th.start()
        th.join()
    assert len(errs) == 1


def test_undeclared_rank_rejected(sanitized):
    with pytest.raises(ValueError, match="undeclared lock rank"):
        @guarded
        class Bad:
            GUARDED_BY = {"_x": "nonsense"}


def test_guarded_requires_own_declaration(sanitized):
    with pytest.raises(ValueError, match="no GUARDED_BY"):
        @guarded
        class Bare:
            pass


def test_values_round_trip_untouched(sanitized):
    """The descriptor stores by reference — pytree-ish values (nested
    containers, arrays) come back identical, so snapshot/restore code
    that walks guarded state under the lock sees the real objects."""
    import numpy as np

    t = _plant()()
    leaf = np.arange(4)
    tree = {"rows": [leaf, (1, 2)], "meta": {"seq": 7}}
    t.put("snap", tree)
    with t._lock:
        got = t._pending["snap"]
    assert got is tree
    assert got["rows"][0] is leaf


# ------------------------------------------------------------ off (cold)

def test_plain_attributes_without_env(monkeypatch):
    monkeypatch.delenv("SIDDHI_TPU_SANITIZE", raising=False)
    cls = _plant()
    # no descriptors installed: the class dict has no _GuardedField
    assert not any(isinstance(v, _GuardedField)
                   for v in vars(cls).values())
    t = cls()
    t._pending = {"x": 1}       # unlocked access is just an attribute
    assert t._pending == {"x": 1}
    assert "_pending" in t.__dict__     # no mangled slot indirection


def test_rank_names_validated_even_when_off(monkeypatch):
    monkeypatch.delenv("SIDDHI_TPU_SANITIZE", raising=False)
    with pytest.raises(ValueError, match="undeclared lock rank"):
        @guarded
        class Bad:
            GUARDED_BY = {"_x": "nonsense"}


# ------------------------------------------------- sanitized cluster run

def test_quick_cluster_check_sanitized():
    """The multi-process tier under every sanitizer: _child_env()
    propagates SIDDHI_TPU_SANITIZE to the workers, so the router,
    egress, supervisor and worker-side contracts are all enforced
    end-to-end. A missing lock anywhere fails this loudly."""
    env = dict(os.environ)
    env["SIDDHI_TPU_SANITIZE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "quick_cluster_check.py")],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "quick_cluster_check OK" in proc.stdout
