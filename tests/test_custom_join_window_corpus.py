"""Reference custom-join-window corpus — scenarios ported verbatim from
``window/CustomJoinWindowTestCase.java``: named windows joined with
tables, other named windows, raw streams, themselves, and fed from many
producer streams."""

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback


class QC(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


class SC(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def test_join_window_with_table():
    """testJoinWindowWithTable (CustomJoinWindowTestCase:55-125)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream StockStream (symbol string, price float, "
        "volume long); "
        "define stream CheckStockStream (symbol string); "
        "define window CheckStockWindow(symbol string) length(1) "
        "output all events; "
        "define table StockTable (symbol string, price float, "
        "volume long); "
        "@info(name = 'query0') from StockStream insert into StockTable ;"
        "@info(name = 'query1') from CheckStockStream "
        "insert into CheckStockWindow ;"
        "@info(name = 'query2') from CheckStockWindow join StockTable "
        " on CheckStockWindow.symbol==StockTable.symbol "
        "select CheckStockWindow.symbol as checkSymbol, "
        "StockTable.symbol as symbol, StockTable.volume as volume  "
        "insert into OutputStream ;")
    q = QC()
    rt.add_callback("query2", q)
    rt.start()
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("CheckStockStream").send(["WSO2"])
    m.shutdown()
    assert len(q.events) == 1
    assert q.events[0].data == ["WSO2", "WSO2", 100]
    assert q.expired == []


def test_join_window_with_window():
    """testJoinWindowWithWindow (:127-185): two named windows joined on
    roomNo — two temps above 30 match their regulators."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream TempStream(deviceID long, roomNo int, "
        "temp double); "
        "define stream RegulatorStream(deviceID long, roomNo int, "
        "isOn bool); "
        "define window TempWindow(deviceID long, roomNo int, "
        "temp double) time(1 min); "
        "define window RegulatorWindow(deviceID long, roomNo int, "
        "isOn bool) length(1); "
        "@info(name = 'query1') from TempStream[temp > 30.0] "
        "insert into TempWindow; "
        "@info(name = 'query2') from RegulatorStream[isOn == false] "
        "insert into RegulatorWindow; "
        "@info(name = 'query3') from TempWindow "
        "join RegulatorWindow "
        "on TempWindow.roomNo == RegulatorWindow.roomNo "
        "select TempWindow.roomNo, RegulatorWindow.deviceID, "
        "'start' as action insert into RegulatorActionStream;")
    c = SC()
    rt.add_callback("RegulatorActionStream", c)
    rt.start()
    t = rt.get_input_handler("TempStream")
    r = rt.get_input_handler("RegulatorStream")
    for room, temp in [(1, 20.0), (2, 25.0), (3, 30.0), (4, 35.0),
                       (5, 40.0)]:
        t.send([100, room, temp])
    for room in range(1, 6):
        r.send([100, room, False])
    m.shutdown()
    assert len(c.events) == 2
    assert sorted(e.data[0] for e in c.events) == [4, 5]


def test_join_window_with_stream():
    """testJoinWindowWithStream (:187-241): a named window joined with a
    filtered raw-stream side."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream TempStream(deviceID long, roomNo int, "
        "temp double); "
        "define stream RegulatorStream(deviceID long, roomNo int, "
        "isOn bool); "
        "define window TempWindow(deviceID long, roomNo int, "
        "temp double) time(1 min); "
        "@info(name = 'query1') from TempStream[temp > 30.0] "
        "insert into TempWindow;"
        "@info(name = 'query2') from TempWindow "
        "join RegulatorStream[isOn == false]#window.length(1) as R "
        "on TempWindow.roomNo == R.roomNo "
        "select TempWindow.roomNo, R.deviceID, 'start' as action "
        "insert into RegulatorActionStream;")
    c = SC()
    rt.add_callback("RegulatorActionStream", c)
    rt.start()
    t = rt.get_input_handler("TempStream")
    r = rt.get_input_handler("RegulatorStream")
    for room, temp in [(1, 20.0), (2, 25.0), (3, 30.0), (4, 35.0),
                       (5, 40.0)]:
        t.send([100, room, temp])
    for room in range(1, 6):
        r.send([100, room, False])
    m.shutdown()
    assert len(c.events) == 2
    assert sorted(e.data[0] for e in c.events) == [4, 5]


def test_multiple_streams_into_one_window():
    """testMultipleStreamsToWindow (:243-296): six producer streams feed
    one lengthBatch(5) window; the flush aggregates across them."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "".join(f"define stream Stream{i} (symbol string, price float, "
                f"volume long); " for i in range(1, 7))
        + "define window StockWindow (symbol string, price float, "
        "volume long) lengthBatch(5); "
        + "".join(f"from Stream{i} insert into StockWindow; "
                  for i in range(1, 7))
        + "@info(name = 'query1') from StockWindow "
        "select symbol, sum(price) as totalPrice, sum(volume) as volumes "
        "insert into OutputStream; ")
    c = SC()
    rt.add_callback("OutputStream", c)
    rt.start()
    for i in range(1, 7):
        rt.get_input_handler(f"Stream{i}").send(["WSO2", i * 10.0, 1])
    m.shutdown()
    assert len(c.events) == 1
    assert c.events[0].data == ["WSO2", 150.0, 5]


def test_join_window_with_itself():
    """testJoinWindowWithSameWindow (:654-700): a length(2) named window
    self-joined on symbol; 3 current matches and 1 expired-side match."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price float, "
        "volume int); "
        "define window cseEventWindow (symbol string, price float, "
        "volume int) length(2); "
        "@info(name = 'query0') from cseEventStream "
        "insert into cseEventWindow; "
        "@info(name = 'query1') from cseEventWindow as a "
        "join cseEventWindow as b on a.symbol== b.symbol "
        "select a.symbol as symbol, a.price as priceA, b.price as priceB "
        "insert all events into outputStream ;")
    q = QC()
    rt.add_callback("query1", q)
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])
    h.send(["IBM", 59.6, 100])
    m.shutdown()
    assert len(q.events) == 3
    assert len(q.expired) == 1
