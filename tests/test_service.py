"""REST service + doc generator tests (reference siddhi-service HTTP
surface / siddhi-doc-gen)."""

import json
import urllib.request

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore
from siddhi_tpu.service import SiddhiRestService
from siddhi_tpu.utils.docgen import generate_docs


def _req(port, method, path, body=None, as_json=True):
    url = f"http://127.0.0.1:{port}{path}"
    data = None
    headers = {}
    if body is not None:
        if as_json:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        else:
            data = body.encode()
            headers["Content-Type"] = "text/plain"
    req = urllib.request.Request(url, data=data, method=method, headers=headers)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_rest_service_lifecycle():
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    svc = SiddhiRestService(m).start()
    p = svc.port
    try:
        app = """
        @app:name('RestApp')
        @app:statistics('true')
        define stream S (sym string, price double);
        define table T (sym string, price double);
        from S[price > 10.0] insert into T;
        """
        got = _req(p, "POST", "/apps", app, as_json=False)
        assert got == {"app": "RestApp"}
        assert _req(p, "GET", "/apps")["apps"] == ["RestApp"]

        _req(p, "POST", "/apps/RestApp/events",
             {"stream": "S", "data": [["IBM", 55.5], ["X", 1.0]]})
        rows = _req(p, "POST", "/query",
                    {"app": "RestApp",
                     "query": "from T select sym, price return;"})["rows"]
        assert rows == [["IBM", 55.5]]

        stats = _req(p, "GET", "/apps/RestApp/statistics")
        assert stats["throughput"]["S"]["events"] == 2

        rev = _req(p, "POST", "/apps/RestApp/persist")["revision"]
        assert rev
        got = _req(p, "POST", "/apps/RestApp/restore", {})
        assert got["revision"] == rev

        assert _req(p, "DELETE", "/apps/RestApp") == {"removed": "RestApp"}
        assert _req(p, "GET", "/apps")["apps"] == []
    finally:
        svc.stop()
        m.shutdown()


def _req_status(port, method, path, body):
    import urllib.error
    try:
        _req(port, method, path, body)
        return 200
    except urllib.error.HTTPError as e:
        return e.code


def test_rest_trace_validation():
    # malformed trace bodies get 4xx, and the trace dir is confined to
    # the service's trace_base (no client-chosen filesystem paths)
    import tempfile

    m = SiddhiManager()
    base = tempfile.mkdtemp()
    svc = SiddhiRestService(m, trace_base=base).start()
    p = svc.port
    try:
        _req(p, "POST", "/apps",
             "@app:name('TrApp') define stream S (v int); "
             "from S select v insert into O;", as_json=False)
        # missing dir -> 400 (not an unhandled 500)
        assert _req_status(p, "POST", "/apps/TrApp/trace",
                           {"action": "start"}) == 400
        # path escape -> 400
        assert _req_status(p, "POST", "/apps/TrApp/trace",
                           {"action": "start", "dir": "../../etc"}) == 400
        # bad action -> 400
        assert _req_status(p, "POST", "/apps/TrApp/trace",
                           {"action": "zap"}) == 400
        # stop without start -> 4xx, never a 500
        assert _req_status(p, "POST", "/apps/TrApp/trace",
                           {"action": "stop"}) in (200, 409)
    finally:
        svc.stop()
        m.shutdown()


def test_doc_generator():
    m = SiddhiManager()

    class MyFn:
        """Doubles a value."""

    m.set_extension("function:double", MyFn)
    md = generate_docs(m)
    assert "## Windows (device)" in md
    assert "`hopping(windowT, hopT)`" in md
    assert "distinctCount" in md
    assert "`function:double` (MyFn) — Doubles a value." in md


def test_rest_error_paths():
    """SiddhiApiServiceImpl error behaviors (reference siddhi-service
    API tests): unknown app names, malformed apps/queries, and unknown
    streams surface as 4xx with an error body, never unhandled 500s or
    hangs."""
    m = SiddhiManager()
    svc = SiddhiRestService(m).start()
    p = svc.port
    try:
        # unknown app: events / statistics / persist / query / delete
        assert _req_status(p, "POST", "/apps/NoSuchApp/events",
                           {"stream": "S", "data": [[1]]}) == 400
        assert _req_status(p, "POST", "/apps/NoSuchApp/persist", {}) == 400
        assert _req_status(p, "POST", "/query",
                           {"app": "NoSuchApp",
                            "query": "from T select * return;"}) == 400
        assert _req_status(p, "DELETE", "/apps/NoSuchApp", None) == 400

        # malformed SiddhiQL app deploy
        assert _req_status(p, "POST", "/apps",
                           "define stream broken (") == 400

        # deploy a real app, then hit it with bad requests
        got = _req(p, "POST", "/apps", """
            @app:name('ErrApp')
            define stream S (sym string, price double);
            define table T (sym string, price double);
            from S insert into T;
        """, as_json=False)
        assert got == {"app": "ErrApp"}
        # unknown stream in event post
        assert _req_status(p, "POST", "/apps/ErrApp/events",
                           {"stream": "Nope", "data": [["X", 1.0]]}) == 400
        # malformed on-demand query
        assert _req_status(p, "POST", "/query",
                           {"app": "ErrApp",
                            "query": "from T select sym,  bogus("}) == 400
        # unknown attribute in on-demand query
        assert _req_status(p, "POST", "/query",
                           {"app": "ErrApp",
                            "query": "from T select nope return;"}) == 400
        # the app still works after the failed requests
        _req(p, "POST", "/apps/ErrApp/events",
             {"stream": "S", "data": [["IBM", 9.0]]})
        rows = _req(p, "POST", "/query",
                    {"app": "ErrApp",
                     "query": "from T select sym, price return;"})["rows"]
        assert rows == [["IBM", 9.0]]
    finally:
        svc.stop()
        m.shutdown()
