"""createSet / unionSet / sizeOfSet — set-valued attributes.

Mirrors reference ``CreateSetFunctionExecutor`` /
``UnionSetAttributeAggregatorExecutor`` / ``SizeOfSetFunctionExecutor``
semantics (FunctionTestCase createSet tests; the unionSet docstring
example pipeline) on the dense encoding: a singleton set travels as its
element's int64 identity code; unionSet keeps a per-group live multiset
value-table and emits bounded ``[B, H]`` element snapshots.
"""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.compiler.errors import SiddhiAppValidationException
from siddhi_tpu.ops.expressions import CompileError


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


def test_createset_singleton_decodes_to_set():
    m, rt, c = build("""
        define stream S (sym string, v int);
        from S select createSet(sym) as s, v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["IBM", 1])
    h.send(["WSO2", 2])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [
        frozenset({"IBM"}), frozenset({"WSO2"})]


@pytest.mark.parametrize("typ,val,expect", [
    ("int", 7, 7), ("long", 9, 9), ("double", 2.5, 2.5), ("bool", True, True),
])
def test_createset_primitive_types(typ, val, expect):
    m, rt, c = build(f"""
        define stream S (x {typ});
        from S select createSet(x) as s insert into OutStream;
    """)
    rt.get_input_handler("S").send([val])
    m.shutdown()
    assert c.events[0].data[0] == frozenset({expect})


def test_createset_arity_rejected():
    # reference FunctionTestCase.testFunctionQuery9: two parameters fail
    m = SiddhiManager()
    with pytest.raises((CompileError, SiddhiAppValidationException)):
        m.create_siddhi_app_runtime("""
            define stream S (sym string, d long);
            from S select createSet(sym, d) as s insert into OutStream;
        """)


def test_unionset_over_window_adds_and_removes():
    # the UnionSetAttributeAggregatorExecutor docstring pipeline: createSet
    # per event, union over a sliding window; processRemove drops departed
    # elements (multiset counting keeps duplicates alive)
    m, rt, c = build("""
        define stream S (sym string);
        from S#window.length(2)
        select unionSet(createSet(sym)) as syms insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["A"])
    h.send(["B"])
    h.send(["A"])     # evicts the first A — but A stays via the new one
    h.send(["C"])     # evicts B
    m.shutdown()
    got = [e.data[0] for e in c.events]
    assert got[0] == frozenset({"A"})
    assert got[1] == frozenset({"A", "B"})
    assert got[2] == frozenset({"A", "B"})
    assert got[3] == frozenset({"A", "C"})


def test_unionset_chain_across_streams_and_sizeofset():
    # canonical chain: createSet -> stream -> window+unionSet -> stream ->
    # sizeOfSet downstream (element metadata propagates across streams)
    m, rt, c = build("""
        define stream Stock (sym string, price double);
        from Stock select createSet(sym) as initialSet insert into InitStream;
        from InitStream#window.lengthBatch(3)
        select unionSet(initialSet) as distinctSyms insert into DistinctStream;
        from DistinctStream select sizeOfSet(distinctSyms) as n
        insert into OutStream;
    """)
    d = Collector()
    rt.add_callback("DistinctStream", d)
    h = rt.get_input_handler("Stock")
    h.send(["IBM", 10.0])
    h.send(["WSO2", 20.0])
    h.send(["IBM", 30.0])     # batch flushes: {IBM, WSO2}
    m.shutdown()
    sizes = [e.data[0] for e in c.events]
    assert sizes[-1] == 2
    assert d.events[-1].data[0] == frozenset({"IBM", "WSO2"})


def test_unionset_group_by_keeps_groups_separate():
    m, rt, c = build("""
        define stream S (user string, sym string);
        from S#window.length(10)
        select user, unionSet(createSet(sym)) as syms
        group by user insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["u1", "A"])
    h.send(["u2", "B"])
    h.send(["u1", "C"])
    m.shutdown()
    last = {}
    for e in c.events:
        last[e.data[0]] = e.data[1]
    assert last == {"u1": frozenset({"A", "C"}), "u2": frozenset({"B"})}


def test_sizeofset_on_singleton_and_requires_object():
    m, rt, c = build("""
        define stream S (sym string);
        from S select createSet(sym) as s insert into Mid;
        from Mid select sizeOfSet(s) as n insert into OutStream;
    """)
    rt.get_input_handler("S").send(["A"])
    m.shutdown()
    assert c.events[0].data[0] == 1

    m2 = SiddhiManager()
    with pytest.raises((CompileError, SiddhiAppValidationException)):
        m2.create_siddhi_app_runtime("""
            define stream S (v int);
            from S select sizeOfSet(v) as n insert into OutStream;
        """)


def test_unionset_survives_event_republish_path():
    # a query callback forces the Event (non-columnar) re-publish path:
    # multi-element sets must round-trip through Events into the next
    # query via the stream's multi/elem metadata (review finding: the
    # from_events re-ingest used to raise)
    from siddhi_tpu import QueryCallback

    class QC(QueryCallback):
        n = 0

        def receive(self, ts, in_events, remove_events):
            QC.n += 1

    m, rt, c = build("""
        define stream S (sym string);
        define stream Mid (u object);
        @info(name='q1')
        from S#window.length(4)
        select unionSet(createSet(sym)) as u insert into Mid;
        from Mid select sizeOfSet(u) as n insert into OutStream;
    """)
    rt.add_callback("q1", QC())     # forces Event materialization
    h = rt.get_input_handler("S")
    h.send(["A"])
    h.send(["B"])
    h.send(["C"])
    m.shutdown()
    assert QC.n >= 3
    assert [e.data[0] for e in c.events] == [1, 2, 3]


def test_consumer_defined_before_producer_sees_metadata():
    # review finding: one-pass assembly used to compile the consumer with
    # multi=False when it appeared before the producer in the app text
    m, rt, c = build("""
        define stream S (sym string);
        define stream Mid (u object);
        from Mid select sizeOfSet(u) as n insert into OutStream;
        from S#window.length(4)
        select unionSet(createSet(sym)) as u insert into Mid;
    """)
    h = rt.get_input_handler("S")
    h.send(["A"])
    h.send(["B"])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [1, 2]


def test_unionset_after_window_drops_snapshot_rejected():
    # review finding: folding a multi set's COUNT column as element codes
    # must be an error, not silent garbage
    import numpy as np

    m, rt, c = build("""
        define stream S (sym string);
        define stream Mid (u object);
        from S select unionSet(createSet(sym)) as u insert into Mid;
        from Mid#window.length(2)
        select unionSet(u) as uu insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    with pytest.raises(Exception, match="snapshot|companions|multi"):
        h.send(["A"])
        m.shutdown()


def test_unionset_requires_object_argument():
    m = SiddhiManager()
    with pytest.raises((CompileError, SiddhiAppValidationException)):
        m.create_siddhi_app_runtime("""
            define stream S (sym string);
            from S#window.length(2)
            select unionSet(sym) as s insert into OutStream;
        """)
