"""Process-global compiled-program cache (core/util/program_cache.py).

Round-15 tentpole: identical step programs compile ONCE per process and
share the immutable executable across SiddhiManager apps, while per-app
state pytrees stay private (donation is per-caller). These tests pin the
lifecycle edges the refcounting must survive:

- two identical apps -> one compile, hit accounting on the second app
- shared executable, private state: windowed outputs diverge per app,
  and cross-app snapshot/restore never aliases state
- blue/green replace: the replacement runtime hits the warm cache, and
  the OLD runtime's shutdown must not evict the survivor's program
  (owner tokens are identity-pinned, not name-keyed)
- refcount-zero eviction returns the size gauge to baseline
- `siddhi_tpu.program_cache: off` restores fully private compiles
"""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.stream.output.stream_callback import StreamCallback
from siddhi_tpu.core.util import program_cache
from siddhi_tpu.core.util.config import InMemoryConfigManager
from siddhi_tpu.observability.export import prometheus_text


class Collect(StreamCallback):
    def __init__(self):
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


FILTER_APP = """
@app:name('{name}')
define stream S (sym string, price float, vol long);
@info(name = 'q1')
from S[price > 10.0]
select sym, price * 2.0 as dbl, vol
insert into Out;
"""

WINDOW_APP = """
@app:name('{name}')
define stream S (sym string, price float, vol long);
@info(name = 'q1')
from S#window.length(3)
select sum(vol) as total
insert into Out;
"""


def _deploy(manager, sql, name):
    rt = manager.create_siddhi_app_runtime(sql.format(name=name))
    cb = Collect()
    rt.add_callback("Out", cb)
    rt.start()
    return rt, cb


def _feed(rt, rows):
    h = rt.get_input_handler("S")
    for i, row in enumerate(rows):
        h.send(100 + i, list(row))


def _entry_for(key):
    for e in program_cache.cache().snapshot()["entries"]:
        if key in e["keys"]:
            return e
    return None


def test_two_identical_apps_share_one_compile():
    program_cache.cache().drain()
    m = SiddhiManager()
    rt1, cb1 = _deploy(m, FILTER_APP, "pc_a1")
    rt2, cb2 = _deploy(m, FILTER_APP, "pc_a2")
    rows = [("x", 12.5, 3), ("y", 5.0, 1), ("z", 99.0, 7)]
    _feed(rt1, rows)
    _feed(rt2, rows)

    # bit-identical outputs through the SHARED executable
    assert cb1.rows == cb2.rows == [("x", 25.0, 3), ("z", 198.0, 7)]

    snap = program_cache.cache().snapshot()
    assert snap["size"] == 1
    entry = snap["entries"][0]
    assert entry["family"] == "query_step"
    assert entry["refcount"] == 2
    assert sorted(entry["shared_by"]) == ["pc_a1", "pc_a2"]
    assert entry["hits"] == 1

    # satellite 1: the second app's first call is a HIT, not a compile —
    # and batch-level hit accounting keeps counting on the shared fn
    j1 = rt1.app_context.telemetry.snapshot()["jit"]["query.q1.step"]
    j2 = rt2.app_context.telemetry.snapshot()["jit"]["query.q1.step"]
    assert j1["compiles"] == 1
    assert j2["compiles"] == 0 and j2["compile_ms"] == 0.0
    assert j2["hits"] >= 1

    # metrics surface: cache families render from the process registry
    text = prometheus_text(m)
    assert "siddhi_program_cache_hits_total" in text
    assert "siddhi_program_cache_misses_total" in text
    assert "siddhi_program_cache_size" in text
    m.shutdown()


def test_shared_program_private_state_and_snapshot_restore():
    program_cache.cache().drain()
    m = SiddhiManager()
    rt1, cb1 = _deploy(m, WINDOW_APP, "pc_w1")
    rt2, cb2 = _deploy(m, WINDOW_APP, "pc_w2")

    # DIFFERENT event streams -> windows must not alias
    _feed(rt1, [("a", 1.0, 1), ("a", 1.0, 2)])
    _feed(rt2, [("b", 1.0, 10)])
    # the attach happens at each step's FIRST call, so the shared entry
    # exists only now that both apps have run a batch
    assert program_cache.cache().snapshot()["size"] >= 1
    assert [r[0] for r in cb1.rows] == [1, 3]
    assert [r[0] for r in cb2.rows] == [10]

    # cross-app snapshot/restore: rolling rt1 back must not disturb rt2
    snap1 = rt1.snapshot()
    _feed(rt1, [("a", 1.0, 4)])
    _feed(rt2, [("b", 1.0, 20)])
    assert [r[0] for r in cb1.rows] == [1, 3, 7]
    rt1.restore(snap1)
    _feed(rt1, [("a", 1.0, 4)])
    # replay after restore reproduces the same fold...
    assert [r[0] for r in cb1.rows] == [1, 3, 7, 7]
    # ...and rt2's window only ever saw rt2's events
    _feed(rt2, [("b", 1.0, 30)])
    assert [r[0] for r in cb2.rows] == [10, 30, 60]
    m.shutdown()


def test_blue_green_replace_hits_warm_cache_and_survives_old_shutdown():
    program_cache.cache().drain()
    m_old = SiddhiManager()
    rt_old, cb_old = _deploy(m_old, FILTER_APP, "pc_bg")
    _feed(rt_old, [("x", 12.5, 3)])
    assert _entry_for("query.q1.step")["refcount"] == 1

    # green runtime: same name, fresh manager — must ATTACH, not compile
    m_new = SiddhiManager()
    rt_new, cb_new = _deploy(m_new, FILTER_APP, "pc_bg")
    _feed(rt_new, [("x", 12.5, 3)])
    entry = _entry_for("query.q1.step")
    assert entry["refcount"] == 2
    j_new = rt_new.app_context.telemetry.snapshot()["jit"]["query.q1.step"]
    assert j_new["compiles"] == 0

    # blue retires: identity-pinned owners mean the old runtime's
    # shutdown can only drop ITS ref — the survivor's program stays
    m_old.shutdown()
    entry = _entry_for("query.q1.step")
    assert entry is not None and entry["refcount"] == 1
    assert entry["shared_by"] == ["pc_bg"]

    # and the survivor keeps producing identical results afterwards
    _feed(rt_new, [("z", 99.0, 7)])
    assert cb_new.rows == [("x", 25.0, 3), ("z", 198.0, 7)]
    m_new.shutdown()


def test_eviction_at_refcount_zero_returns_size_to_baseline():
    program_cache.cache().drain()
    before = program_cache.cache().snapshot()
    assert before["size"] == 0
    m = SiddhiManager()
    rt1, _ = _deploy(m, FILTER_APP, "pc_e1")
    rt2, _ = _deploy(m, FILTER_APP, "pc_e2")
    _feed(rt1, [("x", 12.5, 3)])
    _feed(rt2, [("x", 12.5, 3)])
    assert program_cache.cache().snapshot()["size"] == 1
    ev0 = program_cache.cache().snapshot()["evictions"]

    rt1.shutdown()
    mid = program_cache.cache().snapshot()
    assert mid["size"] == 1           # rt2 still holds a ref
    assert mid["evictions"] == ev0
    rt2.shutdown()
    after = program_cache.cache().snapshot()
    assert after["size"] == 0         # size gauge back to baseline
    assert after["evictions"] == ev0 + 1
    m.shutdown()


def test_knob_off_restores_private_compiles():
    program_cache.cache().drain()
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.program_cache": "0"}))
    rt1, cb1 = _deploy(m, FILTER_APP, "pc_off1")
    rt2, cb2 = _deploy(m, FILTER_APP, "pc_off2")
    _feed(rt1, [("x", 12.5, 3)])
    _feed(rt2, [("x", 12.5, 3)])
    assert cb1.rows == cb2.rows == [("x", 25.0, 3)]
    # nothing cached, both apps compiled privately
    assert program_cache.cache().snapshot()["size"] == 0
    j1 = rt1.app_context.telemetry.snapshot()["jit"]["query.q1.step"]
    j2 = rt2.app_context.telemetry.snapshot()["jit"]["query.q1.step"]
    assert j1["compiles"] == 1 and j2["compiles"] == 1
    m.shutdown()


def test_family_tag_inventory_matches_call_sites():
    """analysis/step_registry.py declares which ``family=`` tag every
    step builder passes to ``instrument_jit``; the tag is part of the
    cache key, so a renamed/dropped tag MUST show up here. Each
    declared family must appear at an instrument_jit call site in its
    named module (literal or as an f-string/concatenation prefix)."""
    import importlib
    import inspect

    from siddhi_tpu.analysis import step_registry

    declared = {f for fams in step_registry.PROGRAM_CACHE_FAMILIES.values()
                for f in fams}
    assert declared == set(step_registry.PROGRAM_CACHE_FAMILY_SITES)
    for fam, module in step_registry.PROGRAM_CACHE_FAMILY_SITES.items():
        src = inspect.getsource(importlib.import_module(module))
        assert (f'family="{fam}' in src or f'family=f"{fam}' in src), (
            f"family tag '{fam}' not found at an instrument_jit call "
            f"site in {module} — update PROGRAM_CACHE_FAMILY_SITES")


def test_max_entries_cap_degrades_to_uncached():
    program_cache.cache().drain()
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.program_cache_max": "0"}))
    rt1, cb1 = _deploy(m, FILTER_APP, "pc_cap1")
    _feed(rt1, [("x", 12.5, 3)])
    # cap of zero: the program runs fine but is never cached
    assert cb1.rows == [("x", 25.0, 3)]
    assert program_cache.cache().snapshot()["size"] == 0
    m.shutdown()
