"""Long-tail parity: cron triggers, log(), script functions, per-group
rate limiters, ConfigManager/ConfigReader, SiddhiDebugger."""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.debugger import SiddhiDebugger
from siddhi_tpu.core.util.config import (
    ConfigReader,
    FileConfigManager,
    InMemoryConfigManager,
)


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def test_cron_trigger_parses_and_schedules():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define trigger FiveSec at '*/5 * * * * ?';
        from FiveSec select triggered_time insert into Out;
    """)
    tr = rt.trigger_runtimes[0]
    assert tr._cron is not None
    # schedule math: next fire strictly after now, on a 5s boundary
    nxt = tr._cron.next_fire(7_000)
    m.shutdown()
    assert nxt == 10_000


def test_script_function_python():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define function cube[python] return double { arg0 * arg0 * arg0 };
        define stream S (v double);
        from S select cube(v) as c insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    rt.get_input_handler("S").send([3.0])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [(27.0,)]


def test_log_function_passes_through():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v double);
        from S[log(v)] select v insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    rt.get_input_handler("S").send([1.5])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [(1.5,)]


def test_per_group_last_rate_limiter():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        from S select sym, v group by sym
        output last every 4 events
        insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    h.send(["b", 2])
    h.send(["a", 3])
    h.send(["b", 4])    # window of 4: last per group -> a:3, b:4
    got = sorted(tuple(e.data) for e in c.events)
    m.shutdown()
    assert got == [("a", 3), ("b", 4)]


def test_per_group_first_rate_limiter():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        from S select sym, v group by sym
        output first every 4 events
        insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(["a", 1])    # first a
    h.send(["a", 2])
    h.send(["b", 3])    # first b
    h.send(["a", 4])
    got = sorted(tuple(e.data) for e in c.events)
    m.shutdown()
    assert got == [("a", 1), ("b", 3)]


def test_config_manager_overrides_knobs(tmp_path):
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager({
        "siddhi_tpu.nfa_slots": "64",
        "source.inMemory.poll": "7",
    }))
    rt = m.create_siddhi_app_runtime("define stream S (v int); from S select v insert into Out;")
    assert rt.app_context.nfa_slots == 64
    reader = ConfigReader(m.siddhi_context.config_manager, "source.inMemory")
    assert reader.read("poll") == "7"
    assert reader.read("missing", "dflt") == "dflt"
    m.shutdown()

    p = tmp_path / "deploy.yaml"
    p.write_text("# deployment\nsiddhi_tpu.window_capacity: 128\n")
    fm = FileConfigManager(str(p))
    assert fm.get_property("siddhi_tpu.window_capacity") == "128"


def test_debugger_breakpoints():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        @info(name='q')
        from S[v > 0] select sym, v insert into Out;
    """)
    hits = []

    rt.add_callback("Out", Collector())
    dbg = rt.debug()
    dbg.set_debugger_callback(
        lambda events, name, terminal, d: hits.append((name, len(events))))
    dbg.acquire_break_point("q", SiddhiDebugger.QueryTerminal.IN)
    dbg.acquire_break_point("q", SiddhiDebugger.QueryTerminal.OUT)
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    h.send(["b", -1])    # filtered: IN fires, OUT does not
    assert ("q:IN", 1) in hits and ("q:OUT", 1) in hits
    n_before = len(hits)
    dbg.release_all_break_points()
    h.send(["c", 2])
    m.shutdown()
    assert len(hits) == n_before


def test_uuid_function_unique_per_row():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        from S select v, uuid() as id insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send([1])
    h.send([2])
    m.shutdown()
    ids = [e.data[1] for e in c.events]
    assert len(ids) == 2 and ids[0] != ids[1]
    assert all(isinstance(i, str) and len(i) == 36 for i in ids)
