"""Long-tail parity: cron triggers, log(), script functions, per-group
rate limiters, ConfigManager/ConfigReader, SiddhiDebugger."""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.debugger import SiddhiDebugger
from siddhi_tpu.core.util.config import (
    ConfigReader,
    FileConfigManager,
    InMemoryConfigManager,
)


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def test_cron_trigger_parses_and_schedules():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define trigger FiveSec at '*/5 * * * * ?';
        from FiveSec select triggered_time insert into Out;
    """)
    tr = rt.trigger_runtimes[0]
    assert tr._cron is not None
    # schedule math: next fire strictly after now, on a 5s boundary
    nxt = tr._cron.next_fire(7_000)
    m.shutdown()
    assert nxt == 10_000


def test_script_function_python():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define function cube[python] return double { arg0 * arg0 * arg0 };
        define stream S (v double);
        from S select cube(v) as c insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    rt.get_input_handler("S").send([3.0])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [(27.0,)]


def test_log_function_passes_through():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v double);
        from S[log(v)] select v insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    rt.get_input_handler("S").send([1.5])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [(1.5,)]


def test_per_group_last_rate_limiter():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        from S select sym, v group by sym
        output last every 4 events
        insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    h.send(["b", 2])
    h.send(["a", 3])
    h.send(["b", 4])    # window of 4: last per group -> a:3, b:4
    got = sorted(tuple(e.data) for e in c.events)
    m.shutdown()
    assert got == [("a", 3), ("b", 4)]


def test_per_group_first_rate_limiter():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        from S select sym, v group by sym
        output first every 4 events
        insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(["a", 1])    # first a
    h.send(["a", 2])
    h.send(["b", 3])    # first b
    h.send(["a", 4])
    got = sorted(tuple(e.data) for e in c.events)
    m.shutdown()
    assert got == [("a", 1), ("b", 3)]


def test_config_manager_overrides_knobs(tmp_path):
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager({
        "siddhi_tpu.nfa_slots": "64",
        "source.inMemory.poll": "7",
    }))
    rt = m.create_siddhi_app_runtime("define stream S (v int); from S select v insert into Out;")
    assert rt.app_context.nfa_slots == 64
    reader = ConfigReader(m.siddhi_context.config_manager, "source.inMemory")
    assert reader.read("poll") == "7"
    assert reader.read("missing", "dflt") == "dflt"
    m.shutdown()

    p = tmp_path / "deploy.yaml"
    p.write_text("# deployment\nsiddhi_tpu.window_capacity: 128\n")
    fm = FileConfigManager(str(p))
    assert fm.get_property("siddhi_tpu.window_capacity") == "128"


def test_debugger_breakpoints():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        @info(name='q')
        from S[v > 0] select sym, v insert into Out;
    """)
    hits = []

    def cb(events, name, terminal, d):
        hits.append((name, len(events)))
        d.play()          # release the suspended pump (reference idiom)

    rt.add_callback("Out", Collector())
    dbg = rt.debug()
    dbg.set_debugger_callback(cb)
    dbg.acquire_break_point("q", SiddhiDebugger.QueryTerminal.IN)
    dbg.acquire_break_point("q", SiddhiDebugger.QueryTerminal.OUT)
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    h.send(["b", -1])    # filtered: IN fires, OUT does not
    assert ("q:IN", 1) in hits and ("q:OUT", 1) in hits
    n_before = len(hits)
    dbg.release_all_break_points()
    h.send(["c", 2])
    m.shutdown()
    assert len(hits) == n_before


def test_debugger_next_single_steps_to_unacquired_checkpoint():
    # only IN is acquired; next() from the IN hit must break again at the
    # OUT checkpoint even though no breakpoint is acquired there
    # (SiddhiDebugger.java threadLocalNextFlag semantics)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        @info(name='q')
        from S[v > 0] select sym, v insert into Out;
    """)
    hits = []

    def cb(events, name, terminal, d):
        hits.append(name)
        if terminal is SiddhiDebugger.QueryTerminal.IN:
            d.next()      # single-step: break at the next checkpoint
        else:
            d.play()      # resume freely from OUT

    rt.add_callback("Out", Collector())
    dbg = rt.debug()
    dbg.set_debugger_callback(cb)
    dbg.acquire_break_point("q", SiddhiDebugger.QueryTerminal.IN)
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    m.shutdown()
    assert hits == ["q:IN", "q:OUT"]


def test_debugger_suspends_pump_until_play():
    # without next()/play() the pump thread stays BLOCKED at the
    # breakpoint — the lock-stepping the reference implements with its
    # breakPointLock semaphore (SiddhiDebugger.java:182-190)
    import threading
    import time

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        @info(name='q')
        from S[v > 0] select sym, v insert into Out;
    """)
    out = Collector()
    rt.add_callback("Out", out)
    dbg = rt.debug()
    fired = threading.Event()
    dbg.set_debugger_callback(
        lambda events, name, terminal, d: fired.set())
    dbg.acquire_break_point("q", SiddhiDebugger.QueryTerminal.IN)
    h = rt.get_input_handler("S")
    t = threading.Thread(target=lambda: h.send(["a", 1]), daemon=True)
    t.start()
    assert fired.wait(10.0)
    time.sleep(0.2)
    assert t.is_alive()            # suspended at the breakpoint
    assert not out.events          # nothing emitted while suspended
    dbg.play()
    t.join(10.0)
    assert not t.is_alive()
    assert [tuple(e.data) for e in out.events] == [("a", 1)]
    m.shutdown()


def test_debugger_get_query_state_while_suspended_at_out():
    # the suspend-inspect-resume workflow: the pump holds the query lock
    # across an OUT suspension; get_query_state must not deadlock
    import threading

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        @info(name='q')
        from S[v > 0] select sym, v insert into Out;
    """)
    rt.add_callback("Out", Collector())
    dbg = rt.debug()
    fired = threading.Event()
    dbg.set_debugger_callback(lambda *a: fired.set())
    dbg.acquire_break_point("q", SiddhiDebugger.QueryTerminal.OUT)
    h = rt.get_input_handler("S")
    t = threading.Thread(target=lambda: h.send(["a", 1]), daemon=True)
    t.start()
    assert fired.wait(10.0)
    st = dbg.get_query_state("q")     # pump suspended INSIDE the lock
    assert "state" in st
    dbg.play()
    t.join(10.0)
    assert not t.is_alive()
    m.shutdown()


def test_debugger_get_query_state():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        @info(name='q')
        from S#window.length(3) select sym, sum(v) as t insert into Out;
    """)
    rt.add_callback("Out", Collector())
    dbg = rt.debug()
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    st = dbg.get_query_state("q")
    assert st["state"] is not None
    m.shutdown()


def test_enforce_order_rejects_out_of_order_and_async():
    import numpy as np
    import pytest

    from siddhi_tpu.compiler.errors import SiddhiAppValidationException

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:enforceOrder @app:playback
        define stream S (v int);
        from S select v insert into Out;
    """)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    h.send(1000, [1])
    h.send(2000, [2])
    with pytest.raises(ValueError, match="enforceOrder"):
        h.send(1500, [3])          # behind the stream watermark
    with pytest.raises(ValueError, match="enforceOrder"):
        h.send_columns({"v": np.array([4, 5])},
                       timestamps=np.array([3000, 2500]))  # in-batch regress
    with pytest.raises(ValueError, match="enforceOrder"):
        from siddhi_tpu.core.event import Event

        # in-batch regression through the Event-list form too
        h.send([Event(timestamp=3000, data=[7]),
                Event(timestamp=2600, data=[8])])
    h.send(3000, [6])              # monotone again: fine
    m.shutdown()

    # @Async buffering can reorder across producers: rejected at build time
    with pytest.raises(SiddhiAppValidationException, match="enforceOrder"):
        m2 = SiddhiManager()
        m2.create_siddhi_app_runtime("""
            @app:enforceOrder
            @Async(buffer.size='64')
            define stream S (v int);
            from S select v insert into Out;
        """)


def test_uuid_function_unique_per_row():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        from S select v, uuid() as id insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send([1])
    h.send([2])
    m.shutdown()
    ids = [e.data[1] for e in c.events]
    assert len(ids) == 2 and ids[0] != ids[1]
    assert all(isinstance(i, str) and len(i) == 36 for i in ids)


def test_null_group_key_forms_its_own_group():
    """A null group-by key is its own group — distinct from every real
    string (including whichever string holds dict id 0) — matching the
    reference's String.valueOf(null) -> "null" keying
    (GroupByKeyGenerator.java:37). Regression: the null placeholder value
    0 used to alias the group of the first-encoded string."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v long);
        @info(name = 'q')
        from S select sym, sum(v) as s group by sym insert into Out;
    """)
    cb = Collector()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send(["a", 1])          # "a" takes dict id 0
    h.send([None, 10])        # null key must NOT join "a"'s group
    h.send(["a", 2])
    h.send([None, 20])
    m.shutdown()
    got = [(e.data[0], e.data[1]) for e in cb.events]
    assert got == [("a", 1), (None, 10), ("a", 3), (None, 30)], got


def test_null_int_group_key_distinct_from_zero():
    """Group-by on an int attribute: a null value (placeholder 0) must not
    merge with a genuine 0 key."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (k int, g string, v long);
        @info(name = 'q')
        from S select k, g, sum(v) as s group by k, g insert into Out;
    """)
    cb = Collector()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send([0, "x", 1])
    h.send([None, "x", 10])
    h.send([0, "x", 2])
    h.send([None, "x", 20])
    m.shutdown()
    got = [(e.data[0], e.data[2]) for e in cb.events]
    assert got == [(0, 1), (None, 10), (0, 3), (None, 30)], got
