"""Tables round 2: on-demand mutations, @primaryKey enforcement + hash
probe, RecordTable SPI (@store), FIFO/LRU/LFU cache — mirroring reference
``table/*TestCase`` + ``StoreQueryTableTestCase`` shapes.
"""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.table.record_table import InMemoryRecordTable, RecordTable, RowCache


APP = """
define stream StockStream (symbol string, price double, volume long);
define table StockTable (symbol string, price double, volume long);
from StockStream insert into StockTable;
"""


def test_on_demand_insert_and_find():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    rt.query("select 'WSO2', 55.5, 100L insert into StockTable;")
    got = rt.query("from StockTable select symbol, price return;")
    m.shutdown()
    assert [tuple(e.data) for e in got] == [("WSO2", 55.5)]


def test_on_demand_delete():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("StockStream")
    h.send(["A", 1.0, 1])
    h.send(["B", 2.0, 2])
    rt.query("delete StockTable on StockTable.symbol == 'A';")
    got = rt.query("from StockTable select symbol return;")
    m.shutdown()
    assert [tuple(e.data) for e in got] == [("B",)]


def test_on_demand_update():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("StockStream")
    h.send(["A", 1.0, 1])
    rt.query("update StockTable set StockTable.price = 9.5 "
             "on StockTable.symbol == 'A';")
    got = rt.query("from StockTable select symbol, price return;")
    m.shutdown()
    assert [tuple(e.data) for e in got] == [("A", 9.5)]


def test_on_demand_update_or_insert():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    rt.query("update or insert into StockTable set StockTable.price = 7.0 "
             "on StockTable.symbol == 'Z';")   # no match: inserts
    got = rt.query("from StockTable select price return;")
    m.shutdown()
    assert [tuple(e.data) for e in got] == [(7.0,)]


def test_primary_key_rejects_duplicates_and_probes():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double);
        @primaryKey('symbol')
        define table T (symbol string, price double);
        from S insert into T;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["A", 2.0])     # duplicate primary key: dropped
    h.send(["B", 3.0])
    got = rt.query("from T select symbol, price return;")
    table = rt.tables["T"]
    sid = rt.app_context.string_dictionary.encode("A")
    slot = table.find_by_pk((sid,))
    m.shutdown()
    assert sorted(tuple(e.data) for e in got) == [("A", 1.0), ("B", 3.0)]
    assert slot is not None


def test_record_table_store_roundtrip():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double);
        @store(type='inMemory')
        define table T (symbol string, price double);
        from S insert into T;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["B", 2.0])
    got1 = rt.query("from T select symbol, price return;")
    rt.query("delete T on T.symbol == 'A';")
    got2 = rt.query("from T select symbol return;")
    rt.query("update T set T.price = 5.0 on T.symbol == 'B';")
    got3 = rt.query("from T select symbol, price return;")
    m.shutdown()
    assert sorted(tuple(e.data) for e in got1) == [("A", 1.0), ("B", 2.0)]
    assert [tuple(e.data) for e in got2] == [("B",)]
    assert [tuple(e.data) for e in got3] == [("B", 5.0)]


def test_custom_record_table_extension():
    calls = []

    class TracingStore(InMemoryRecordTable):
        def add(self, records):
            calls.append(("add", len(records)))
            super().add(records)

        def read(self):
            calls.append(("read", None))
            return super().read()

    m = SiddhiManager()
    m.set_extension("store:traced", TracingStore)
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double);
        @store(type='traced')
        define table T (symbol string, price double);
        from S insert into T;
    """)
    rt.get_input_handler("S").send(["A", 1.0])
    got = rt.query("from T select symbol return;")
    m.shutdown()
    assert [tuple(e.data) for e in got] == [("A",)]
    assert ("add", 1) in calls and ("read", None) in calls


def test_table_store_join_side():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, qty int);
        define stream Q (symbol string);
        @store(type='inMemory')
        define table T (symbol string, qty int);
        from S insert into T;
        from Q join T on Q.symbol == T.symbol
        select T.symbol as symbol, T.qty as qty
        insert into Out;
    """)
    from siddhi_tpu import StreamCallback

    seen = []

    class C(StreamCallback):
        def receive(self, events):
            seen.extend(tuple(e.data) for e in events)

    rt.add_callback("Out", C())
    rt.get_input_handler("S").send(["A", 5])
    rt.get_input_handler("Q").send(["A"])
    m.shutdown()
    assert seen == [("A", 5)]


def test_row_cache_policies():
    fifo = RowCache(2, "FIFO")
    fifo.put(1, ["a"]); fifo.put(2, ["b"]); fifo.get(1); fifo.put(3, ["c"])
    assert 1 not in fifo and 2 in fifo and 3 in fifo

    lru = RowCache(2, "LRU")
    lru.put(1, ["a"]); lru.put(2, ["b"]); lru.get(1); lru.put(3, ["c"])
    assert 2 not in lru and 1 in lru and 3 in lru

    lfu = RowCache(2, "LFU")
    lfu.put(1, ["a"]); lfu.put(2, ["b"])
    lfu.get(1); lfu.get(1); lfu.get(2)
    lfu.put(3, ["c"])          # evicts key 2 (freq 1) not key 1 (freq 2)
    assert 2 not in lfu and 1 in lfu and 3 in lfu


def test_cached_store_pk_lookup():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double);
        @store(type='inMemory', @cache(size='2', cache.policy='LRU'))
        @primaryKey('symbol')
        define table T (symbol string, price double);
        from S insert into T;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["B", 2.0])
    h.send(["C", 3.0])
    t = rt.tables["T"]
    assert len(t.cache) == 2            # bounded by the cache size
    row = t.find_by_pk(("A",))          # miss -> loads from the store
    m.shutdown()
    assert row == ["A", 1.0]


def test_row_cache_retention_expiry():
    """CacheExpirer analog (util/cache/CacheExpirer.java): rows older than
    retention.period expire — both on the periodic sweep and lazily on
    get() so a stale row is never served between sweeps."""
    clock = {"t": 1_000}
    c = RowCache(8, "FIFO", retention_ms=500)
    c.now_fn = lambda: clock["t"]
    c.put(("a",), ["a", 1])
    clock["t"] += 400
    c.put(("b",), ["b", 2])
    assert c.get(("a",)) == ["a", 1]      # age 400 < 500: still served
    clock["t"] += 200                     # a: 600 > 500; b: 200 ok
    assert c.expire() == 1
    assert ("a",) not in c and c.get(("b",)) == ["b", 2]
    clock["t"] += 400                     # b now 600 old; no sweep yet
    assert c.get(("b",)) is None          # lazy expiry on read
    assert len(c) == 0


def test_cached_store_retention_sweep_scheduled():
    """@cache(retention.period=...) wires a periodic expirer onto the app
    scheduler (AbstractQueryableRecordTable.java:156-163: purge.interval
    defaults to the retention period)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream S (symbol string, price double);
        @store(type='inMemory',
               @cache(size='8', cache.policy='FIFO',
                      retention.period='1 sec'))
        @primaryKey('symbol')
        define table T (symbol string, price double);
        from S insert into T;
    """)
    h = rt.get_input_handler("S")
    h.send(1_000, [["A", 1.0]][0])
    t = rt.tables["T"]
    assert t.cache.retention_ms == 1_000
    assert t.cache.purge_interval_ms == 1_000
    assert ("A",) in t.cache
    # playback clock jumps past the retention period; the store keeps the
    # row (expiry only empties the CACHE), the next lookup re-loads it
    h.send(3_000, [["B", 2.0]][0])
    assert t.cache.get(("A",)) is None    # expired (lazily or by sweep)
    assert t.find_by_pk(("A",)) == ["A", 1.0]   # reloaded from the store
    m.shutdown()


def test_on_demand_group_by_returns_one_row_per_group():
    """Ported from OnDemandQueryTableTestCase.java test3 (:137-190): a
    grouped/aggregated FIND returns ONE row per group with the aggregate
    over the whole store (2 symbols -> 2 rows; having filters groups;
    3 (symbol, price) pairs -> 3 rows)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price double, volume long);
        define table StockTable (symbol string, price double, volume long);
        @info(name = 'query1')
        from StockStream insert into StockTable;
    """)
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])
    r = rt.query("from StockTable on price > 5 "
                 "select symbol, sum(volume) as totalVolume "
                 "group by symbol having totalVolume > 150")
    assert [e.data for e in r] == [["WSO2", 200]]
    r = rt.query("from StockTable on price > 5 "
                 "select symbol, sum(volume) as totalVolume group by symbol")
    assert len(r) == 2
    r = rt.query("from StockTable on price > 5 "
                 "select symbol, sum(volume) as totalVolume "
                 "group by symbol, price")
    assert len(r) == 3
    m.shutdown()
