"""Test harness config.

Multi-chip code paths are tested on a virtual 8-device CPU mesh (the driver
separately dry-runs the multichip path); env vars must be set before jax
first import, hence here at conftest import time.
"""

import os

# Persistent jit cache: the suite compiles many small step functions; cache
# them across runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

# Plugin platforms (the axon TPU tunnel) override JAX_PLATFORMS via
# jax.config.update at interpreter start, so env vars alone don't stick —
# force the virtual 8-device CPU platform through the config API.
from siddhi_tpu.parallel.mesh import force_host_devices  # noqa: E402

force_host_devices(8)
