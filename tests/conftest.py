"""Test harness config.

Multi-chip code paths are tested on a virtual 8-device CPU mesh (the driver
separately dry-runs the multichip path); env vars must be set before jax
first import, hence here at conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
