"""Test harness config.

Multi-chip code paths are tested on a virtual 8-device CPU mesh (the driver
separately dry-runs the multichip path); env vars must be set before jax
first import, hence here at conftest import time.
"""

import os

# Persistent jit cache: DISABLED. On this sandbox (gVisor) the on-disk
# cache poisons itself — reads of previously written entries segfault the
# process mid-compile and can return WRONG computation results (repro:
# tests/test_absent_corpus.py q16 flipped pass/fail/segfault with the
# cache on, 5/5 clean with it off). In-process jit caching is unaffected,
# and tier-1 is one process, so the persistent layer only ever saved
# cross-run startup time. Override the empty value to re-enable at your
# own risk.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

# Dispatch pipeline (core/query/completion.py): pin tier-1 to depth 2 so
# the WHOLE suite exercises the pipelined submit/drain path (sync sends
# flush before returning, so visible semantics stay synchronous), not
# just tests/test_pipeline.py. Matches the production default; set to 1
# to bisect a failure against the fully-synchronous path.
os.environ.setdefault("SIDDHI_TPU_PIPELINE_DEPTH", "2")

# Plugin platforms (the axon TPU tunnel) override JAX_PLATFORMS via
# jax.config.update at interpreter start, so env vars alone don't stick —
# force the virtual 8-device CPU platform through the config API.
from siddhi_tpu.parallel.mesh import force_host_devices  # noqa: E402

force_host_devices(8)

# Automatic GC during jax tracing segfaults this jaxlib build
# (deterministic repro with the persistent cache off: faulthandler shows
# "Garbage-collecting" inside a live trace). Collecting between tests is
# NOT safe either — finalizers on collected jaxlib objects abort the
# interpreter — so cycles leak for the session; the suite fits comfortably
# in memory.
import gc  # noqa: E402

gc.disable()

_exit_status = {"code": None}


def pytest_sessionfinish(session, exitstatus):
    _exit_status["code"] = int(exitstatus)


import atexit  # noqa: E402
import sys  # noqa: E402


@atexit.register
def _skip_interpreter_teardown():
    # Interpreter shutdown finalizes jaxlib objects out of dependency
    # order and segfaults AFTER the suite already finished, turning a
    # green run into rc=139. Once pytest has produced its verdict, skip
    # teardown and exit with the real status.
    if _exit_status["code"] is not None:
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(_exit_status["code"])
