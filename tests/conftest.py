"""Test harness config.

Multi-chip code paths are tested on a virtual 8-device CPU mesh (the driver
separately dry-runs the multichip path); env vars must be set before jax
first import, hence here at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent jit cache: the suite compiles many small step functions; cache
# them across runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
