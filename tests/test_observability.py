"""Observability subsystem tests: span nesting/thread-safety, histogram
percentile correctness vs numpy, @Async queue-depth gauges under a soak,
Prometheus exposition over REST, Chrome-trace structural validity, and
the bounded cluster-pull gauge."""

import json
import re
import threading
import time
import urllib.request
from collections import defaultdict

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.observability.histogram import Histogram
from siddhi_tpu.observability.tracing import TRACER, Tracer, span
from siddhi_tpu.observability.telemetry import global_registry


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


# ------------------------------------------------------------------ spans


def _complete_events(trace):
    evs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    for e in evs:
        for key in ("name", "pid", "tid", "ts", "dur"):
            assert key in e, f"chrome event missing '{key}': {e}"
        assert e["dur"] > 0
    return evs


def _assert_properly_nested(events):
    """Per tid, every pair of spans is either disjoint or contained —
    the Trace Event Format contract for complete ('X') events."""
    by_tid = defaultdict(list)
    for e in events:
        by_tid[e["tid"]].append(e)
    eps = 0.01   # ts/dur are rounded to 3 decimals of a microsecond
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in evs:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"] - eps:
                stack.pop()
            if stack:
                assert (e["ts"] + e["dur"]
                        <= stack[-1]["ts"] + stack[-1]["dur"] + eps), \
                    f"span {e} escapes its parent {stack[-1]}"
            stack.append(e)


def test_span_nesting_structure():
    t = Tracer(capacity=1024)
    t.start()
    with t.span("outer", kind="test"):
        with t.span("mid"):
            with t.span("inner"):
                time.sleep(0.001)
        with t.span("mid2"):
            time.sleep(0.001)
    trace = t.stop()
    evs = _complete_events(trace)
    assert {e["name"] for e in evs} == {"outer", "mid", "inner", "mid2"}
    _assert_properly_nested(evs)
    outer = next(e for e in evs if e["name"] == "outer")
    inner = next(e for e in evs if e["name"] == "inner")
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 0.01
    assert outer["args"] == {"kind": "test"}


def test_span_thread_safety():
    t = Tracer(capacity=100_000)
    t.start()
    n_threads, n_iters = 8, 200
    barrier = threading.Barrier(n_threads)   # all alive at once, so
    #                                          thread idents stay distinct

    def work():
        barrier.wait()
        for i in range(n_iters):
            with t.span("outer", i=i):
                with t.span("mid"):
                    with t.span("inner"):
                        pass

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    trace = t.stop()
    evs = _complete_events(trace)
    assert len(evs) == n_threads * n_iters * 3
    assert len({e["tid"] for e in evs}) == n_threads
    _assert_properly_nested(evs)


def test_span_ring_buffer_bound_and_disabled_noop():
    t = Tracer(capacity=16)
    t.start()
    for i in range(100):
        with t.span("s", i=i):
            pass
    assert len(t) == 16
    trace = t.stop()
    assert trace["otherData"]["dropped_spans"] == 84
    # newest survive the ring
    kept = [e["args"]["i"] for e in trace["traceEvents"]
            if e.get("ph") == "X"]
    assert sorted(kept) == list(range(84, 100))
    # disabled: the global helper returns the shared no-op
    assert not TRACER.enabled
    cm = span("ignored", x=1)
    with cm:
        pass
    assert len(TRACER) == 0


# -------------------------------------------------------------- histogram


def test_histogram_percentiles_match_numpy():
    rng = np.random.default_rng(7)
    for sample in (
        rng.lognormal(mean=1.0, sigma=1.5, size=20_000),     # heavy tail
        rng.uniform(0.01, 50.0, size=10_000),                # flat
        np.abs(rng.normal(5.0, 2.0, size=10_000)) + 0.05,    # bell
    ):
        h = Histogram()
        for v in sample:
            h.record(float(v))
        for q in (0.50, 0.95, 0.99):
            got = h.quantile(q)
            want = float(np.quantile(sample, q))
            assert got == pytest.approx(want, rel=0.08), \
                f"q={q}: hist {got} vs numpy {want}"
    assert h.count == 10_000
    assert h.mean == pytest.approx(float(sample.mean()), rel=1e-6)


def test_histogram_edges_and_reset():
    h = Histogram()
    assert h.quantile(0.5) == 0.0
    h.record(3.25)
    assert h.quantile(0.5) == pytest.approx(3.25, rel=0.08)
    assert h.quantile(0.0) == 3.25 and h.quantile(1.0) == 3.25
    h.record(-1.0)           # negative: clock-skew artifact, ignored
    h.record(float("nan"))   # ignored
    assert h.count == 1
    h.record(1e9)            # beyond the top bucket: clamped, counted
    assert h.count == 2 and h.max_seen == 1e9
    h.reset()
    assert h.count == 0 and h.quantile(0.99) == 0.0


def test_latency_tracker_has_percentiles():
    from siddhi_tpu.core.util.statistics import LatencyTracker

    t = LatencyTracker("q")
    for v in [1.0] * 90 + [100.0] * 10:
        t.record(v)
    assert t.p50_ms == pytest.approx(1.0, rel=0.1)
    assert t.p99_ms == pytest.approx(100.0, rel=0.1)
    assert t.avg_ms == pytest.approx(10.9, rel=1e-6)
    t.reset()
    assert t.p99_ms == 0.0


# ------------------------------------------------- @Async telemetry gauges


def test_queue_depth_gauge_under_async_soak():
    from siddhi_tpu.resilience import FaultInjector

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('SoakApp')
        @Async(buffer.size='256', batch.size='16')
        define stream S (sym string, v long);
        from S select sym, v insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    rt.start()
    tel = rt.app_context.telemetry
    inj = FaultInjector()
    j = rt.junctions["S"]
    h = rt.get_input_handler("S")
    try:
        inj.wedge_worker(j)
        h.send(["a", 0])                    # wakes the worker into the wedge
        assert inj.wait_wedged(10.0)
        for i in range(50):                 # soak against a wedged worker
            h.send(["a", i])
        depth = tel.read_gauges()["junction.S.queue_depth"]
        assert depth >= 50                  # queued behind the wedge
    finally:
        inj.release()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        g = tel.read_gauges()
        if (g["junction.S.queue_depth"] == 0
                and g["junction.S.inflight_batches"] == 0
                and len(c.events) == 51):
            break
        time.sleep(0.02)
    g = tel.read_gauges()
    m.shutdown()
    assert g["junction.S.queue_depth"] == 0
    assert g["junction.S.inflight_batches"] == 0
    assert len(c.events) == 51              # nothing lost across the soak


def test_backpressure_stall_counter():
    from siddhi_tpu.resilience import FaultInjector

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('StallApp')
        @Async(buffer.size='4', batch.size='4')
        define stream S (v long);
        from S select v insert into Out;
    """)
    rt.add_callback("Out", Collector())
    rt.start()
    inj = FaultInjector()
    j = rt.junctions["S"]
    h = rt.get_input_handler("S")
    inj.wedge_worker(j)
    h.send([0])
    assert inj.wait_wedged(10.0)

    def pump():
        for i in range(8):                  # overflows the 4-slot queue
            h.send([i])

    t = threading.Thread(target=pump)
    t.start()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if rt.app_context.telemetry.counters.get(
                "junction.S.backpressure_stalls", 0) > 0:
            break
        time.sleep(0.02)
    stalls = rt.app_context.telemetry.counters.get(
        "junction.S.backpressure_stalls", 0)
    inj.release()
    t.join(timeout=10)
    m.shutdown()
    assert stalls > 0


# ------------------------------------------------------------ REST surface


def _req(port, method, path, body=None, as_json=True, raw=False):
    url = f"http://127.0.0.1:{port}{path}"
    data = None
    headers = {}
    if body is not None:
        if as_json:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        else:
            data = body.encode()
            headers["Content-Type"] = "text/plain"
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    with urllib.request.urlopen(req) as r:
        payload = r.read()
        return payload.decode() if raw else json.loads(payload)


_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^}]*\})?'
    r' (?P<value>-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?|NaN))$')
_LABEL = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"')


def _parse_prometheus(text):
    """Minimal exposition-format parser: returns (types, samples) where
    samples is a list of (metric, labels dict, value). Raises on any
    malformed line — the 'parses' half of the acceptance criterion."""
    types = {}
    samples = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, ftype = rest.rsplit(" ", 1)
            assert ftype in ("counter", "gauge", "summary", "histogram")
            types[fam] = ftype
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        labels = {lm.group("k"): lm.group("v")
                  for lm in _LABEL.finditer(m.group("labels") or "")}
        samples.append((m.group("name"), labels, m.group("value")))
    # every sample belongs to a TYPE-declared family (summaries add
    # _sum/_count suffixes to the family name)
    for name, _labels, _v in samples:
        fam = name
        for suf in ("_sum", "_count"):
            if name.endswith(suf) and name[: -len(suf)] in types:
                fam = name[: -len(suf)]
        assert fam in types or name in types, f"undeclared family: {name}"
    return types, samples


OBS_APP = """
@app:name('ObsApp')
@app:statistics(level='detail')
define stream S (sym string, price double);
@Async(buffer.size='64', batch.size='8')
define stream Mid (sym string, price double);
@info(name='q1') from S[price > 1.0] select sym, price insert into Mid;
@info(name='q2') from Mid select sym, price insert into Out;
"""


def test_rest_metrics_prometheus_exposition():
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore
    from siddhi_tpu.service import SiddhiRestService

    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    svc = SiddhiRestService(m).start()
    p = svc.port
    try:
        assert _req(p, "POST", "/apps", OBS_APP,
                    as_json=False) == {"app": "ObsApp"}
        rt = m.get_siddhi_app_runtime("ObsApp")
        rt.enable_wal(max_batches=16)
        _req(p, "POST", "/apps/ObsApp/events",
             {"stream": "S", "data": [["IBM", 5.5], ["X", 2.0]]})
        time.sleep(0.4)                       # let the @Async hop deliver
        _req(p, "POST", "/apps/ObsApp/persist")
        _req(p, "POST", "/apps/ObsApp/events",
             {"stream": "S", "data": [["Y", 3.0]]})
        time.sleep(0.3)
        _req(p, "POST", "/apps/ObsApp/restore", {})   # replays the WAL
        time.sleep(0.4)

        text = _req(p, "GET", "/metrics", raw=True)
        types, samples = _parse_prometheus(text)

        def named(metric):
            return [(lb, v) for name, lb, v in samples if name == metric]

        # per-query latency percentiles (q1 runs on the ingest thread)
        quantiles = {lb["quantile"] for lb, _v in named("siddhi_latency_ms")
                     if lb.get("name") == "q1"}
        assert {"0.5", "0.95", "0.99"} <= quantiles
        assert types["siddhi_latency_ms"] == "summary"
        # junction queue-depth gauge for the @Async stream
        assert any(lb.get("stream") == "Mid"
                   for lb, _v in named("siddhi_junction_queue_depth"))
        # jit-compile counters
        jit_keys = {lb["key"] for lb, v in named("siddhi_jit_compiles_total")
                    if lb.get("app") == "ObsApp" and float(v) > 0}
        assert any(k.startswith("query.q1") for k in jit_keys)
        # resilience.* counters, the replayed-WAL one genuinely non-zero
        res = {lb["name"]: float(v) for lb, v in named("siddhi_counter_total")
               if lb.get("app") == "ObsApp"
               and lb.get("name", "").startswith("resilience.")}
        assert set(res) >= {
            "resilience.worker_restarts", "resilience.wal_replayed_batches",
            "resilience.wal_dropped_batches", "resilience.sink_retries"}
        assert res["resilience.wal_replayed_batches"] >= 1
        # WAL gauges ride the generic gauge family
        assert any(lb.get("name") == "wal.batches"
                   for lb, _v in named("siddhi_gauge"))

        # single-app scope + JSON snapshot
        text_one = _req(p, "GET", "/metrics/ObsApp", raw=True)
        _parse_prometheus(text_one)
        js = _req(p, "GET", "/metrics/ObsApp?format=json")
        assert list(js["apps"]) == ["ObsApp"]
        tel = js["apps"]["ObsApp"]["telemetry"]
        assert "junction.Mid.queue_depth" in tel["gauges"]
        lat = js["apps"]["ObsApp"]["statistics"]["latency"]["q1"]
        assert {"p50_ms", "p95_ms", "p99_ms"} <= set(lat)
        # unknown app -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(p, "GET", "/metrics/NoSuchApp", raw=True)
        assert ei.value.code == 404
    finally:
        svc.stop()
        m.shutdown()


def test_rest_trace_start_stop_dumps_chrome_json(tmp_path):
    from siddhi_tpu.service import SiddhiRestService

    m = SiddhiManager()
    svc = SiddhiRestService(m, trace_base=str(tmp_path)).start()
    p = svc.port
    try:
        _req(p, "POST", "/apps",
             "@app:name('TrSpanApp') define stream S (v int); "
             "from S[v > 0] select v insert into O;", as_json=False)
        got = _req(p, "POST", "/trace/start", {})
        assert got["tracing"] is True
        # double start -> 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(p, "POST", "/trace/start", {})
        assert ei.value.code == 409
        for i in range(3):
            _req(p, "POST", "/apps/TrSpanApp/events",
                 {"stream": "S", "data": [[i + 1]]})
        got = _req(p, "POST", "/trace/stop", {"file": "soak/spans.json"})
        assert got["tracing"] is False and got["events"] > 0
        # the span file is a loadable Chrome trace, confined to trace_base
        assert got["file"].startswith(str(tmp_path))
        with open(got["file"], encoding="utf-8") as f:
            trace = json.load(f)
        evs = _complete_events(trace)
        names = {e["name"] for e in evs}
        assert "junction.dispatch" in names and "query.step" in names
        _assert_properly_nested(evs)
        # stop without start -> 409; escape -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(p, "POST", "/trace/stop", {})
        assert ei.value.code == 409
        _req(p, "POST", "/trace/start", {})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(p, "POST", "/trace/stop", {"file": "../../etc/passwd"})
        assert ei.value.code == 400
        # "." resolves to the trace DIRECTORY itself: rejected, and the
        # rejection must NOT have stopped the running trace
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(p, "POST", "/trace/stop", {"file": "."})
        assert ei.value.code == 400
        assert TRACER.enabled
        _req(p, "POST", "/trace/stop", {})   # leave the tracer off
    finally:
        TRACER.enabled = False
        svc.stop()
        m.shutdown()


def test_wal_gauges_register_at_attach_not_only_create():
    """A WAL attached to a rebuilt runtime's context (the PeerRecovery
    path assigns ``app_context.ingest_wal`` directly) must still get its
    /metrics gauges — registration follows the ATTACH, not the create."""
    from siddhi_tpu.resilience.replay import IngestWAL, register_wal_gauges

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:name('WalGaugeApp') define stream S (v long); "
        "from S select v insert into Out;")
    survivor_wal = IngestWAL(max_batches=8)
    rt.app_context.ingest_wal = survivor_wal      # recovery-style attach
    register_wal_gauges(rt.app_context)
    rt.get_input_handler("S").send([1])
    g = rt.app_context.telemetry.read_gauges()
    assert g["wal.batches"] == 1 and g["wal.pending_events"] == 1
    register_wal_gauges(rt.app_context)           # idempotent
    assert rt.app_context.telemetry.read_gauges()["wal.batches"] == 1
    m.shutdown()


# ------------------------------------------------- bounded cluster pulls


def test_guarded_pull_outstanding_gauge_and_cap(monkeypatch):
    from siddhi_tpu.parallel import distributed as d

    release = threading.Event()

    class Blocker:
        def __array__(self, *a, **kw):
            release.wait(20)
            return np.zeros(1)

    base = d.outstanding_pulls()
    try:
        with pytest.raises(d.ClusterPeerError, match="terminal"):
            d.guarded_pull(Blocker(), 0.05, what="test pull")
        # the abandoned native wait is tracked as outstanding...
        assert d.outstanding_pulls() == base + 1
        # ...and exported as a process-global gauge
        g = global_registry().read_gauges()
        assert g["cluster.outstanding_pulls"] == base + 1
        # at the cap, new pulls fail fast instead of stacking threads
        monkeypatch.setattr(d, "_MAX_OUTSTANDING_PULLS", base + 1)
        with pytest.raises(d.ClusterPeerError, match="already outstanding"):
            d.guarded_pull(np.zeros(1), 5.0, what="capped pull")
    finally:
        release.set()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and d.outstanding_pulls() > base:
        time.sleep(0.02)
    assert d.outstanding_pulls() == base   # leaked thread drained
