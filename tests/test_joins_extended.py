"""Join completeness tests: group-by selectors, joins inside partitions,
host-window join sides, aggregation joins — mirroring reference
``query/join/*TestCase`` + ``aggregation/*AggregationTestCase`` join shapes.
"""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


STREAMS = """
    define stream OrderStream (symbol string, qty int);
    define stream PriceStream (symbol string, price double);
"""


def test_join_group_by_aggregation():
    m, rt, c = build(STREAMS + """
        from OrderStream#window.length(8) join PriceStream#window.length(8)
          on OrderStream.symbol == PriceStream.symbol
        select OrderStream.symbol as symbol, sum(OrderStream.qty) as total
        group by OrderStream.symbol
        insert into OutStream;
    """)
    ho = rt.get_input_handler("OrderStream")
    hp = rt.get_input_handler("PriceStream")
    hp.send(["A", 10.0])
    hp.send(["B", 20.0])
    ho.send(["A", 5])      # joins with A price: total(A) = 5
    ho.send(["A", 7])      # total(A) = 12
    ho.send(["B", 3])      # total(B) = 3
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    # each CURRENT match updates the group sum; EXPIRED never fires (window 8)
    assert ("A", 5) in got and ("A", 12) in got and ("B", 3) in got


def test_join_inside_partition():
    m, rt, c = build("""
        define stream L (k string, v int);
        define stream R (k string, w int);
        partition with (k of L, k of R)
        begin
          from L#window.length(4) join R#window.length(4)
          select L.v as v, R.w as w
          insert into OutStream;
        end;
    """)
    hl = rt.get_input_handler("L")
    hr = rt.get_input_handler("R")
    hl.send(["p1", 1])
    hl.send(["p2", 2])
    hr.send(["p1", 10])    # joins ONLY with p1's L rows
    hr.send(["p2", 20])    # joins ONLY with p2's L rows
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [(1, 10), (2, 20)]


def test_host_window_join_side():
    # sort window as a join side: contents() is the probe surface
    m, rt, c = build(STREAMS + """
        from OrderStream#window.sort(2, qty) join PriceStream#window.length(4)
          on OrderStream.symbol == PriceStream.symbol
        select OrderStream.qty as qty, PriceStream.price as price
        insert into OutStream;
    """)
    ho = rt.get_input_handler("OrderStream")
    hp = rt.get_input_handler("PriceStream")
    ho.send(["A", 5])
    ho.send(["A", 1])
    ho.send(["A", 9])      # sort(2) keeps the 2 smallest: {1, 5}
    c.events.clear()
    hp.send(["A", 10.0])   # probes the sort window's held rows
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [(1, 10.0), (5, 10.0)]


def test_aggregation_join():
    m, rt, c = build("""
        @app:playback
        define stream TradeStream (symbol string, price double, volume long);
        define stream QueryStream (symbol string);
        define aggregation TradeAgg
          from TradeStream
          select symbol, sum(price) as total, count() as n
          group by symbol
          aggregate every sec ... min;
        from QueryStream join TradeAgg
          on QueryStream.symbol == TradeAgg.symbol
          within 0L, 9999999999999L per 'seconds'
        select QueryStream.symbol as symbol, TradeAgg.total as total
        insert into OutStream;
    """)
    ht = rt.get_input_handler("TradeStream")
    hq = rt.get_input_handler("QueryStream")
    ht.send(10_000, ["A", 10.0, 1])
    ht.send(10_200, ["A", 15.0, 1])     # same second bucket: total 25
    ht.send(11_000, ["B", 50.0, 1])
    hq.send(12_000, ["A"])
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [("A", 25.0)]


def test_aggregation_join_multiple_buckets():
    m, rt, c = build("""
        @app:playback
        define stream TradeStream (symbol string, price double, volume long);
        define stream QueryStream (symbol string);
        define aggregation TradeAgg
          from TradeStream
          select symbol, sum(price) as total
          group by symbol
          aggregate every sec ... min;
        from QueryStream join TradeAgg
          on QueryStream.symbol == TradeAgg.symbol
          within 0L, 9999999999999L per 'seconds'
        select TradeAgg.AGG_TIMESTAMP as bucket, TradeAgg.total as total
        insert into OutStream;
    """)
    ht = rt.get_input_handler("TradeStream")
    hq = rt.get_input_handler("QueryStream")
    ht.send(10_000, ["A", 10.0, 1])
    ht.send(12_000, ["A", 5.0, 1])      # a different second bucket
    hq.send(13_000, ["A"])
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [(10_000, 10.0), (12_000, 5.0)]


def test_join_group_by_inside_partition():
    m, rt, c = build("""
        define stream L (k string, g string, v int);
        define stream R (k string, w int);
        partition with (k of L, k of R)
        begin
          from L#window.length(8) join R#window.length(8)
          select L.g as g, sum(R.w) as tw
          group by L.g
          insert into OutStream;
        end;
    """)
    hl = rt.get_input_handler("L")
    hr = rt.get_input_handler("R")
    hl.send(["p1", "x", 1])
    hr.send(["p1", 10])            # (p1, x): 10
    hl.send(["p2", "x", 2])
    hr.send(["p2", 30])            # (p2, x): 30 — separate key space
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [("x", 10), ("x", 30)]


def test_join_side_aliases():
    m, rt, c = build("""
        define stream L (sym string, v int);
        define stream R (sym string, w int);
        from L#window.length(5) as a join R#window.length(5) as b
             on a.sym == b.sym
        select a.sym as sym, a.v as v, b.w as w insert into OutStream;
    """)
    rt.get_input_handler("R").send(["A", 7])
    rt.get_input_handler("L").send(["A", 1])
    m.shutdown()
    assert ("A", 1, 7) in [tuple(e.data) for e in c.events]


def test_self_join_with_aliases():
    m, rt, c = build("""
        define stream S (sym string, v int);
        from S#window.length(5) as a join S#window.length(5) as b
             on a.sym == b.sym and a.v < b.v
        select a.v as lo, b.v as hi insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1])
    h.send(["A", 5])
    m.shutdown()
    assert sorted(tuple(e.data) for e in c.events) == [(1, 5)]
