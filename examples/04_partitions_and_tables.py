"""Partitions (per-key state), tables with primary keys, and on-demand
queries."""

from siddhi_tpu import SiddhiManager, StreamCallback


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime("""
        define stream Logins (user string, ok bool);
        @primaryKey('user')
        define table FailCounts (user string, fails long);

        partition with (user of Logins)
        begin
            from Logins[not ok]#window.length(100)
            select user, count() as fails
            insert into #tally;

            from #tally select user, fails update or insert into FailCounts
                set FailCounts.fails = fails
                on FailCounts.user == user;
        end;
    """)
    h = runtime.get_input_handler("Logins")
    for user, ok in [("alice", False), ("bob", False), ("alice", False)]:
        h.send([user, ok])

    rows = runtime.query("from FailCounts select user, fails")
    print("fail counts:", sorted(tuple(e.data) for e in rows))
    manager.shutdown()


if __name__ == "__main__":
    main()
