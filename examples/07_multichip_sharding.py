"""Shard a partitioned query's per-key state across a device mesh.

Runs on a virtual 8-device CPU mesh here; the same code shards over real
TPU chips (key-axis NamedSharding, collectives over ICI)."""

from siddhi_tpu.parallel.mesh import force_host_devices

force_host_devices(8)   # virtual CPU devices (skip on a real multi-chip host)

from siddhi_tpu import SiddhiManager, StreamCallback           # noqa: E402
from siddhi_tpu.parallel import make_mesh, shard_query_step    # noqa: E402


class PrintCallback(StreamCallback):
    def receive(self, events):
        for e in events:
            print("out:", e.data)


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime("""
        @app:playback
        define stream Ticks (sym string, v long);
        partition with (sym of Ticks)
        begin
            @info(name = 'persym')
            from Ticks#window.length(4)
            select sym, sum(v) as total
            insert into Out;
        end;
    """)
    runtime.add_callback("Out", PrintCallback())

    mesh = make_mesh(8)                       # 1-D mesh over 8 devices
    q = runtime.query_runtimes["persym"]
    shard_query_step(q, mesh)                 # [K, ...] state sharded by key

    h = runtime.get_input_handler("Ticks")
    for i in range(32):                       # 16 keys spread over the mesh
        h.send(1000 + i, [f"K{i % 16}", i])
    manager.shutdown()


if __name__ == "__main__":
    main()
