"""Deploy apps and inject events over HTTP (the service surface)."""

import json
import urllib.request

from siddhi_tpu import SiddhiManager
from siddhi_tpu.service.rest import SiddhiRestService


def _post(port, path, body):
    is_text = isinstance(body, str)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body.encode() if is_text else json.dumps(body).encode(),
        headers={"Content-Type": "text/plain" if is_text else "application/json"},
        method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def main():
    manager = SiddhiManager()
    svc = SiddhiRestService(manager, port=0).start()
    port = svc.port

    _post(port, "/apps", """
        @app:name('RestDemo')
        define stream S (sym string, v long);
        define table T (sym string, v long);
        from S select sym, v insert into T;
    """)
    _post(port, "/apps/RestDemo/events",
          {"stream": "S", "data": [["ACME", 7], ["GOOG", 9]]})
    rows = _post(port, "/query", {"app": "RestDemo",
                                  "query": "from T select sym, v"})
    print("rows over HTTP:", rows)
    svc.stop()
    manager.shutdown()


if __name__ == "__main__":
    main()
