"""Quick start: filter + projection (the reference's hello-world app)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class PrintCallback(StreamCallback):
    def receive(self, events):
        for e in events:
            print("out:", e.data)


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price double, volume long);

        @info(name = 'filter-query')
        from StockStream[price > 100.0]
        select symbol, price
        insert into HighPriceStream;
    """)
    runtime.add_callback("HighPriceStream", PrintCallback())
    stocks = runtime.get_input_handler("StockStream")
    stocks.send(["WSO2", 105.5, 100])
    stocks.send(["CHEAP", 20.0, 50])
    stocks.send(["GOOG", 220.0, 10])
    manager.shutdown()


if __name__ == "__main__":
    main()
