"""Sources/sinks over the InMemoryBroker, plus checkpoint/restore."""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore
from siddhi_tpu.extension import InMemoryBroker


def main():
    received = []

    class AlertTap:
        topic = "alerts"

        @staticmethod
        def on_message(payload):
            received.append(payload)

    InMemoryBroker.subscribe(AlertTap)

    manager = SiddhiManager()
    manager.set_persistence_store(InMemoryPersistenceStore())
    runtime = manager.create_siddhi_app_runtime("""
        @source(type='inMemory', topic='ticks', @map(type='passThrough'))
        define stream Ticks (symbol string, price double);

        @sink(type='inMemory', topic='alerts', @map(type='passThrough'))
        define stream Alerts (symbol string, price double);

        from Ticks[price > 100.0] select symbol, price insert into Alerts;
    """)
    runtime.start()
    InMemoryBroker.publish("ticks", ["ACME", 150.0])
    InMemoryBroker.publish("ticks", ["ACME", 50.0])

    revision = runtime.persist()            # checkpoint
    runtime.restore_revision(revision)      # and restore
    print("alerts:", received, "| revision:", revision)
    manager.shutdown()


if __name__ == "__main__":
    main()
