"""CEP pattern: every A followed by a higher-priced B within 5 seconds,
per key — the dense-NFA hot path."""

from siddhi_tpu import SiddhiManager, StreamCallback


class PrintCallback(StreamCallback):
    def receive(self, events):
        for e in events:
            print("match:", e.data)


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime("""
        @app:playback
        define stream Ticks (symbol string, price double);

        from every e1=Ticks -> e2=Ticks[symbol == e1.symbol and price > e1.price]
             within 5 sec
        select e1.symbol as symbol, e1.price as p1, e2.price as p2
        insert into Rises;
    """)
    runtime.add_callback("Rises", PrintCallback())
    h = runtime.get_input_handler("Ticks")
    h.send(1000, ["ACME", 10.0])
    h.send(2000, ["ACME", 12.0])     # match (10 -> 12)
    h.send(9000, ["ACME", 50.0])     # outside 'within' of the first pair
    manager.shutdown()


if __name__ == "__main__":
    main()
