"""Sliding window + group-by aggregation over 10k keys — the flagship
TPU shape: one fused device step per batch."""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback


class Last(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(e.data for e in events)


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime("""
        define stream Trades (symbol string, price double);
        from Trades#window.length(1000)
        select symbol, avg(price) as avgPrice, count() as n
        group by symbol
        insert into Averages;
    """)
    out = Last()
    runtime.add_callback("Averages", out)
    h = runtime.get_input_handler("Trades")

    # columnar bulk ingest: one device step for the whole batch
    rng = np.random.default_rng(0)
    n = 4096
    h.send_columns({
        "symbol": np.array([f"S{i}" for i in rng.integers(0, 100, n)]),
        "price": rng.random(n) * 50,
    }, timestamps=np.arange(n, dtype=np.int64))
    manager.shutdown()
    print("rows out:", len(out.rows), "sample:", out.rows[-1])


if __name__ == "__main__":
    main()
