"""Benchmark: events/sec on the BASELINE.json north-star shape —
10k-key length-window -> avg aggregation (config #2/#3 family).

Mirrors the reference harness pattern
(``SimpleFilterSingleQueryPerformance.java:44-56``: pump events, count
outputs, report events/sec per epoch). The JVM baseline cannot be run in
this image (no Java); ``vs_baseline`` is measured against the estimate
recorded below, derived from the reference's single-threaded per-event hot
path (expression-interpreter + per-event window clone + string group keys;
see BASELINE.md). Update it with a measured JVM number when available.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

# Estimated JVM StreamRuntime throughput on the same query shape
# (10k-key windowed agg, single-threaded InputHandler.send loop).
JVM_BASELINE_EVENTS_PER_SEC = 1.0e6

NUM_KEYS = 10_000
WINDOW = 1_000
BATCH = 8_192
WARMUP_BATCHES = 3
MEASURE_SECONDS = 10.0

_APP = """
define stream StockStream (symbol string, price float, volume long);
@info(name = 'bench')
from StockStream#window.length({W})
select symbol, avg(price) as avgPrice, sum(volume) as totalVolume
group by symbol
insert into OutStream;
""".format(W=WINDOW)


def main():
    import jax

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.event import HostBatch
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY

    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(_APP)
    rt.start()
    q = rt.query_runtimes["bench"]
    q.selector_plan.num_keys = 16_384  # >= NUM_KEYS, pow2

    rng = np.random.default_rng(0)

    def make_batch(i):
        cols = {
            TS_KEY: np.arange(i * BATCH, (i + 1) * BATCH, dtype=np.int64),
            TYPE_KEY: np.zeros(BATCH, np.int8),
            VALID_KEY: np.ones(BATCH, bool),
            "symbol": rng.integers(0, NUM_KEYS, BATCH, dtype=np.int64),
            "symbol?": np.zeros(BATCH, bool),
            "price": rng.random(BATCH, np.float32) * 100.0,
            "price?": np.zeros(BATCH, bool),
            "volume": rng.integers(1, 1000, BATCH, dtype=np.int64),
            "volume?": np.zeros(BATCH, bool),
            GK_KEY: rng.integers(0, NUM_KEYS, BATCH).astype(np.int32),
        }
        return cols

    state = q._init_state()
    step = jax.jit(q.build_step_fn(), donate_argnums=0)
    now = np.int64(0)

    batches = [make_batch(i) for i in range(8)]
    for i in range(WARMUP_BATCHES):
        state, out = step(state, batches[i % len(batches)], now)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    n_events = 0
    i = 0
    while True:
        state, out = step(state, batches[i % len(batches)], now)
        n_events += BATCH
        i += 1
        if i % 50 == 0:
            jax.block_until_ready(state)
            if time.perf_counter() - t0 >= MEASURE_SECONDS:
                break
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    eps = n_events / dt

    print(json.dumps({
        "metric": "events_per_sec_10k_key_length1000_avg",
        "value": round(eps, 1),
        "unit": "events/sec/chip",
        "vs_baseline": round(eps / JVM_BASELINE_EVENTS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
