"""Benchmark: the BASELINE.json north-star shapes on one chip.

Headline: events/sec on the 10k-key length(1000) -> avg/sum group-by
aggregation (BASELINE.json config #2/#3 family), measured against the
MEASURED single-threaded event-at-a-time native baseline
(tools/baseline_cpp/baseline.cpp — no JVM exists in this image; the C++
stand-in reproduces the reference hot path's per-event cost structure and
is, if anything, faster than the JVM it proxies, so vs_baseline is
conservative). Also measured and reported inside the same JSON line:

- e2e_events_per_sec: the same query driven through the REAL ingest path
  with GENUINE STRING ingest (object-dtype symbol arrays dictionary-encoded
  on every batch: InputHandler.send_columns -> StreamJunction ->
  QueryRuntime -> StreamCallback);
- e2e_preencoded_events_per_sec: the same with pre-encoded int64 symbol
  ids (isolates the dictionary-encode cost);
- e2e_cpu_events_per_sec: the string-ingest e2e on the CPU backend —
  isolates framework overhead from the axon tunnel's ~70 ms/pull link
  latency (PERF.md cost model);
- nfa_p99_ms / nfa_events_per_sec: per-batch latency of BASELINE.json
  config #4 (`every e1=A -> e2=B[e2.v>e1.v] within 5 sec` over 10k
  partition keys), p99 over the measured batches.

Harness design for a hostile single-client TPU tunnel (the round-2
failure mode — BENCH_r02 rc=124): every section runs ONCE in its own
subprocess with a short measure window; the cumulative result line is
printed and flushed after EVERY section so a later wedge can never void
an earlier number; a section that times out marks the tunnel wedged and
the remaining tunnel sections are skipped (timeout-killed clients
re-wedge the tunnel for minutes — never retry); CPU-backend sections run
last and cannot wedge. Worst case stays within BENCH_TOTAL_BUDGET
(default 780 s). Methodology mirrors the reference's
SimpleFilterSingleQueryPerformance.java:44-56 (pump events, count
outputs, divide by elapsed).

Prints ONE JSON line per completed section (cumulative); the LAST line
is the most complete record: {"metric", "value", "unit", "vs_baseline",
...}.
"""

from __future__ import annotations

import json
import os
import time

# Persistent compilation cache: DISABLED — on this sandbox the on-disk
# cache poisons itself (reads segfault mid-compile and can return wrong
# results; see tests/conftest.py). A wrong-answer bench is worse than a
# slow first compile; override the empty value to re-enable elsewhere.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

import numpy as np

# Measured on this host: tools/baseline_cpp/baseline.cpp, g++ -O2, 20M
# events (single-threaded event-at-a-time engine with the reference's
# per-event cost structure). See BASELINE.md.
MEASURED_BASELINE_EPS = 8.5e6

NUM_KEYS = 10_000
WINDOW = 1_000
BATCH = int(os.environ.get("BENCH_BATCH", 65_536))
MEASURE_SECONDS = float(os.environ.get("BENCH_SECONDS", 4.0))

_APP = """
define stream StockStream (symbol string, price float, volume long);
@info(name = 'bench')
from StockStream#window.length({W})
select symbol, avg(price) as avgPrice, sum(volume) as totalVolume
group by symbol
insert into OutStream;
""".format(W=WINDOW)


def bench_device():
    """Device-path throughput: pre-staged columnar batches through the
    fused query step (the selector/keyer warmed to full key capacity)."""
    import jax

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY

    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(_APP)
    rt.start()
    q = rt.query_runtimes["bench"]
    q.selector_plan.num_keys = 16_384  # >= NUM_KEYS, pow2: no growth re-jits

    rng = np.random.default_rng(0)

    def make_batch(i):
        sym = rng.integers(0, NUM_KEYS, BATCH, dtype=np.int64)
        return {
            TS_KEY: np.arange(i * BATCH, (i + 1) * BATCH, dtype=np.int64),
            TYPE_KEY: np.zeros(BATCH, np.int8),
            VALID_KEY: np.ones(BATCH, bool),
            "symbol": sym,
            "symbol?": np.zeros(BATCH, bool),
            "price": (rng.random(BATCH) * 100.0).astype(np.float32),
            "price?": np.zeros(BATCH, bool),
            "volume": rng.integers(1, 1000, BATCH, dtype=np.int64),
            "volume?": np.zeros(BATCH, bool),
            GK_KEY: sym.astype(np.int32),
        }

    state = q._init_state()
    step = jax.jit(q.build_step_fn(), donate_argnums=0)
    now = np.int64(0)
    batches = [jax.device_put(make_batch(i)) for i in range(4)]

    for i in range(3):
        state, out = step(state, batches[i % len(batches)], now)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    n_events = 0
    i = 0
    while True:
        state, out = step(state, batches[i % len(batches)], now)
        n_events += BATCH
        i += 1
        if i % 20 == 0:
            jax.block_until_ready(state)
            if time.perf_counter() - t0 >= MEASURE_SECONDS:
                break
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    manager.shutdown()
    return n_events / dt


def _make_e2e_runtime(pipeline_depth: int = 8):
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    manager = SiddhiManager()
    # dispatch-pipeline depth (core/query/completion.py; replaces the
    # deprecated defer_meta hold-N queue): synchronous sends flush per
    # batch, so depth only engages through @Async producers — kept here
    # for parity with bench_pipeline_curve's async shape
    manager.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.pipeline_depth": str(pipeline_depth)}))
    rt = manager.create_siddhi_app_runtime(_APP)

    class Counter(StreamCallback):
        n = 0

        def receive_batch(self, batch, junction):
            Counter.n += batch.size

        def receive(self, events):
            Counter.n += len(events)

    Counter.n = 0
    rt.add_callback("OutStream", Counter())
    rt.query_runtimes["bench"].selector_plan.num_keys = 16_384
    return manager, rt, Counter


def bench_e2e():
    """End-to-end: InputHandler.send_columns -> junction -> query ->
    StreamCallback (columnar), mirroring the reference harness methodology
    (SimpleFilterSingleQueryPerformance.java:44-56: pump, count outputs,
    events/sec). Two measured windows in one session: genuine STRING
    ingest (the dictionary encodes every batch — the cost the reference
    pays per event) and pre-encoded int ids (isolating that cost)."""
    manager, rt, Counter = _make_e2e_runtime()
    h = rt.get_input_handler("StockStream")

    rng = np.random.default_rng(1)
    B = BATCH
    sym_strings = np.array([f"S{i}" for i in range(NUM_KEYS)], dtype=object)

    def make_cols(i, strings: bool):
        ids = rng.integers(0, NUM_KEYS, B, dtype=np.int64)
        return {
            "symbol": sym_strings[ids] if strings else ids,
            "price": (rng.random(B) * 100.0).astype(np.float32),
            "volume": rng.integers(1, 1000, B, dtype=np.int64),
        }, np.arange(i * B, (i + 1) * B, dtype=np.int64)

    # warm at the MEASURED batch shape (pow2 padding would otherwise
    # compile a second shape): one B-row batch covering every key — string
    # ingest, so the dictionary also reaches its full size up front
    warm_sym = sym_strings[np.arange(B, dtype=np.int64) % NUM_KEYS]
    h.send_columns({"symbol": warm_sym,
                    "price": np.ones(B, np.float32),
                    "volume": np.ones(B, np.int64)},
                   timestamps=np.zeros(B, np.int64))

    def measure(strings: bool, seconds: float) -> float:
        pre = [make_cols(i + 1, strings) for i in range(4)]
        h.send_columns(pre[0][0], timestamps=pre[0][1])   # settle the shape
        t0 = time.perf_counter()
        n = 0
        i = 0
        while time.perf_counter() - t0 < seconds:
            cols, ts = pre[i % len(pre)]
            h.send_columns(cols, timestamps=ts)
            n += B
            i += 1
        return n / (time.perf_counter() - t0)

    eps_str = measure(strings=True, seconds=MEASURE_SECONDS)
    eps_pre = measure(strings=False, seconds=MEASURE_SECONDS)
    manager.shutdown()
    assert Counter.n > 0
    return eps_str, eps_pre


def bench_e2e_curve():
    """Operating-point curve (VERDICT r04 next #7): e2e throughput AND
    per-batch p99 at several (batch size, pipeline_depth) points — the
    trade-off surface the junction's adaptive batcher navigates
    (junction.py adaptive cap). Runs on whatever backend exists; the
    result record labels the backend (``e2e_curve_backend``), so a
    CPU-fallback curve is recorded rather than another null."""
    rng = np.random.default_rng(7)
    sym_strings = np.array([f"S{i}" for i in range(NUM_KEYS)], dtype=object)
    points = []
    for B, depth in ((16_384, 1), (16_384, 8), (65_536, 1), (65_536, 8)):
        manager, rt, Counter = _make_e2e_runtime(pipeline_depth=depth)
        h = rt.get_input_handler("StockStream")
        warm_sym = sym_strings[np.arange(B, dtype=np.int64) % NUM_KEYS]
        h.send_columns({"symbol": warm_sym,
                        "price": np.ones(B, np.float32),
                        "volume": np.ones(B, np.int64)},
                       timestamps=np.zeros(B, np.int64))
        pre = []
        for i in range(4):
            ids = rng.integers(0, NUM_KEYS, B, dtype=np.int64)
            pre.append(({
                "symbol": sym_strings[ids],
                "price": (rng.random(B) * 100.0).astype(np.float32),
                "volume": rng.integers(1, 1000, B, dtype=np.int64),
            }, np.arange(i * B, (i + 1) * B, dtype=np.int64)))
        h.send_columns(pre[0][0], timestamps=pre[0][1])
        lat = []
        n = 0
        i = 0
        t_end = time.perf_counter() + MEASURE_SECONDS / 2
        while time.perf_counter() < t_end:
            cols, ts = pre[i % 4]
            t0 = time.perf_counter()
            h.send_columns(cols, timestamps=ts)
            lat.append((time.perf_counter() - t0) * 1000.0)
            n += B
            i += 1
        manager.shutdown()
        assert Counter.n > 0
        lat = np.sort(np.asarray(lat))
        points.append({
            "batch": B, "pipeline_depth": depth,
            "eps": round(n / float(np.sum(lat) / 1000.0), 1),
            "p99_ms": round(float(
                lat[min(len(lat) - 1, int(len(lat) * 0.99))]), 3),
        })
    return points


def bench_pipeline_curve():
    """Dispatch-pipeline depth curve (ISSUE 5): the bench shape behind an
    @Async junction — the producer shape where the CompletionPump
    actually pipelines (the worker delivers back-to-back, so up to D
    device batches ride in flight while the next batch packs; sync sends
    flush per batch by design). D=1 is the old synchronous
    pull-per-batch engine. Records input events/sec send->fully-drained
    and the pump's metas-per-pull batching ratio per depth.

    On the TPU tunnel the expected win is the PERF.md cost model's
    ``max(pack, step+pull)`` vs ``pack + step + pull``; on a single-core
    CPU sandbox there is nothing to overlap with, so the acceptance bar
    is no-regression (depth-2 >= 0.95x depth-1)."""
    from siddhi_tpu.core.stream.junction import _NOTHING

    B = int(os.environ.get("BENCH_PIPELINE_BATCH", 8192))
    app = """
@Async(buffer.size='64')
define stream StockStream (symbol string, price float, volume long);
@info(name = 'bench')
from StockStream#window.length({W})
select symbol, avg(price) as avgPrice, sum(volume) as totalVolume
group by symbol
insert into OutStream;
""".format(W=WINDOW)
    rng = np.random.default_rng(23)
    sym_strings = np.array([f"S{i}" for i in range(NUM_KEYS)], dtype=object)

    def run_one(depth: int):
        from siddhi_tpu import SiddhiManager, StreamCallback
        from siddhi_tpu.core.util.config import InMemoryConfigManager

        manager = SiddhiManager()
        manager.set_config_manager(InMemoryConfigManager(
            {"siddhi_tpu.pipeline_depth": str(depth)}))
        rt = manager.create_siddhi_app_runtime(app)

        class Counter(StreamCallback):
            n = 0

            def receive_batch(self, batch, junction):
                Counter.n += batch.size

            def receive(self, events):
                Counter.n += len(events)

        rt.add_callback("OutStream", Counter())
        rt.query_runtimes["bench"].selector_plan.num_keys = 16_384
        rt.start()
        h = rt.get_input_handler("StockStream")
        j = rt.junctions["StockStream"]
        pump = rt.app_context.completion_pump

        def drained() -> bool:
            return (j._queue.empty() and j._inflight is _NOTHING
                    and not pump.has_pending)

        pre = []
        for i in range(4):
            ids = rng.integers(0, NUM_KEYS, B, dtype=np.int64)
            pre.append(({
                "symbol": sym_strings[ids],
                "price": (rng.random(B) * 100.0).astype(np.float32),
                "volume": rng.integers(1, 1000, B, dtype=np.int64),
            }, np.arange(i * B, (i + 1) * B, dtype=np.int64)))
        warm_sym = sym_strings[np.arange(B, dtype=np.int64) % NUM_KEYS]
        h.send_columns({"symbol": warm_sym,
                        "price": np.ones(B, np.float32),
                        "volume": np.ones(B, np.int64)},
                       timestamps=np.zeros(B, np.int64))
        h.send_columns(pre[0][0], timestamps=pre[0][1])
        deadline = time.perf_counter() + 30.0
        while not drained() and time.perf_counter() < deadline:
            time.sleep(0.002)

        t0 = time.perf_counter()
        n = 0
        i = 0
        t_end = t0 + MEASURE_SECONDS / 2
        while time.perf_counter() < t_end:
            cols, ts = pre[i % 4]
            h.send_columns(cols, timestamps=ts)   # blocks only on full queue
            n += B
            i += 1
        deadline = time.perf_counter() + 60.0
        while not drained() and time.perf_counter() < deadline:
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        tel = rt.app_context.telemetry.snapshot()
        metas = tel["counters"].get("pipeline.metas", 0)
        pulls = tel["counters"].get("pipeline.pulls", 0)
        stalls = tel["counters"].get("pipeline.stalls", 0)
        manager.shutdown()
        assert Counter.n > 0
        return {
            "depth": depth, "eps": round(n / dt, 1),
            "metas_per_pull": round(metas / pulls, 2) if pulls else None,
            "stalls": stalls,
        }

    return [run_one(d) for d in (1, 2, 4, 8)]


def bench_serving():
    """Serving-tier shard curve (ISSUE 6): ingest eps and on-demand store
    query p50/p99 under MIXED load, for 1/2/4/8 aggregation shards. An
    ingest thread pumps columnar batches into a grouped multi-granularity
    aggregation the whole time while two query threads fire canned
    `within ... per ...` reads (in-process `rt.query` — the REST hop is
    measured by tools/serve_soak.py). Sharded reads scatter per-shard
    epoch-pinned partials and ordered-merge them without the app barrier,
    so the signal is (a) ingest eps holding steady under the query storm
    and (b) query latency vs shard count."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.util.config import InMemoryConfigManager
    from siddhi_tpu.observability.histogram import Histogram

    app = """
@app:name('BenchServe')
define stream TradeStream (symbol string, price double, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec ... day;
"""
    KEYS, B, TS_RANGE = 50, 512, 600_000
    measure_s = float(os.environ.get("BENCH_SERVING_SECONDS", 8.0))
    rng = np.random.default_rng(11)
    syms = np.array([f"S{i}" for i in range(KEYS)], dtype=object)
    queries = [
        f"from TradeAgg within {lo}L, {lo + 300_000}L per '{p}' "
        f"select AGG_TIMESTAMP, symbol, total, n"
        for p in ("seconds", "minutes", "hours")
        for lo in (0, 150_000, 300_000)
    ]

    def run_one(shards: int):
        import threading

        manager = SiddhiManager()
        manager.set_config_manager(InMemoryConfigManager(
            {"siddhi_tpu.agg_shards": str(shards)}))
        rt = manager.create_siddhi_app_runtime(app)
        h = rt.get_input_handler("TradeStream")
        pre = []
        for i in range(4):
            ids = rng.integers(0, KEYS, B)
            pre.append({
                "symbol": syms[ids],
                "price": (rng.random(B) * 100.0).astype(np.float64),
                "ts": rng.integers(0, TS_RANGE, B, dtype=np.int64)})
        h.send_columns(pre[0], timestamps=np.arange(B, dtype=np.int64))
        for q in queries:    # warm the on-demand plans + jit shapes
            rt.query(q)

        stop = threading.Event()
        sent = {"n": 0}

        def ingest():
            i = 0
            while not stop.is_set():
                h.send_columns(pre[i % 4],
                               timestamps=np.arange(B, dtype=np.int64))
                sent["n"] += B
                i += 1

        hist = Histogram()
        qcount = {"n": 0}

        def querier(ci):
            qrng = np.random.default_rng(100 + ci)
            while not stop.is_set():
                q = queries[int(qrng.integers(0, len(queries)))]
                t0 = time.perf_counter()
                rt.query(q)
                hist.record((time.perf_counter() - t0) * 1000.0)
                qcount["n"] += 1

        threads = [threading.Thread(target=ingest, daemon=True)] + [
            threading.Thread(target=querier, args=(i,), daemon=True)
            for i in range(2)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(measure_s)
        stop.set()
        for t in threads:
            t.join(30)
        dt = time.perf_counter() - t0
        manager.shutdown()
        return {
            "shards": shards,
            "ingest_eps": round(sent["n"] / dt, 1),
            "queries": qcount["n"],
            "query_qps": round(qcount["n"] / dt, 1),
            "query_p50_ms": round(hist.quantile(0.50), 2),
            "query_p99_ms": round(hist.quantile(0.99), 2),
        }

    return [run_one(s) for s in (1, 2, 4, 8)]


def bench_host_pipeline():
    """Host-pipeline throughput with the device step STUBBED: the full
    ingest pump — string columns -> dictionary encode (native strdict.cpp)
    -> HostBatch -> junction -> group keyer -> step dispatch/defer/flush
    bookkeeping -> emit — with the jitted device function replaced by a
    host no-op. Isolates Python/host cost from device compute: on a live
    TPU the e2e ceiling is min(host_pipeline, device, encode-overlap).
    Reference counterpart: the whole JVM engine IS this pipeline
    (StreamJunction.java:156-165 -> ProcessStreamReceiver.java:74-184),
    measured at ~8.5M eps by tools/baseline_cpp.

    Also measures ingest_csv_eps: the same pump fed by the NATIVE CSV
    loader (csv_loader.cpp) parsing raw transport bytes, the analog of the
    reference's source->mapper->event path."""
    from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY

    manager, rt, Counter = _make_e2e_runtime()
    h = rt.get_input_handler("StockStream")
    q = rt.query_runtimes["bench"]

    rng = np.random.default_rng(3)
    B = BATCH
    sym_strings = np.array([f"S{i}" for i in range(NUM_KEYS)], dtype=object)

    def make_cols(i):
        ids = rng.integers(0, NUM_KEYS, B, dtype=np.int64)
        return {
            "symbol": sym_strings[ids],
            "price": (rng.random(B) * 100.0).astype(np.float32),
            "volume": rng.integers(1, 1000, B, dtype=np.int64),
        }, np.arange(i * B, (i + 1) * B, dtype=np.int64)

    warm_sym = sym_strings[np.arange(B, dtype=np.int64) % NUM_KEYS]
    h.send_columns({"symbol": warm_sym,
                    "price": np.ones(B, np.float32),
                    "volume": np.ones(B, np.int64)},
                   timestamps=np.zeros(B, np.int64))
    pre = [make_cols(i + 1) for i in range(4)]
    h.send_columns(pre[0][0], timestamps=pre[0][1])

    # stub the device step: state passes through untouched, the output is
    # an empty (all-invalid) packed batch whose __meta__ says
    # overflow=0/notify=-1/size=0 — every HOST stage still runs for real
    empty_meta = np.array([0, -1, 0], np.int64)

    def stub_step(state, cols, now):
        return state, {
            VALID_KEY: np.zeros(1, bool),
            TS_KEY: np.zeros(1, np.int64),
            TYPE_KEY: np.zeros(1, np.int8),
            "__meta__": empty_meta,
        }

    q._step = stub_step

    t0 = time.perf_counter()
    n = 0
    i = 0
    while time.perf_counter() - t0 < MEASURE_SECONDS:
        cols, ts = pre[i % len(pre)]
        h.send_columns(cols, timestamps=ts)
        n += B
        i += 1
    eps_pipeline = n / (time.perf_counter() - t0)

    # ---- native CSV ingest -> the same stubbed pump
    from siddhi_tpu.native import CsvLoader

    loader = CsvLoader(rt.stream_definitions["StockStream"],
                       rt.app_context.string_dictionary)
    lines = []
    ids = rng.integers(0, NUM_KEYS, B)
    prices = rng.random(B) * 100.0
    vols = rng.integers(1, 1000, B)
    for j in range(B):
        lines.append(f"S{ids[j]},{prices[j]:.4f},{vols[j]}")
    payload = ("\n".join(lines) + "\n").encode()
    cols0, nrows = loader.parse(payload)
    h.send_columns(cols0, timestamps=np.arange(nrows, dtype=np.int64))

    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < MEASURE_SECONDS:
        cols_j, nrows = loader.parse(payload)
        h.send_columns(cols_j, timestamps=np.arange(nrows, dtype=np.int64))
        n += nrows
    eps_csv = n / (time.perf_counter() - t0)
    manager.shutdown()
    return eps_pipeline, eps_csv


_PARTITIONED_APP = """
define stream StockStream (symbol string, price float, volume long);
partition with (symbol of StockStream)
begin
  @info(name = 'bench')
  from StockStream#window.length({W})
  select symbol, avg(price) as avgPrice, sum(volume) as totalVolume
  insert into OutStream;
end;
""".format(W=WINDOW)


def bench_mesh_scaling():
    """Strong scaling of the partitioned flagship (per-key length(1000)
    window -> avg/sum over 10k keys) under round-6 DEVICE-side
    repartitioning (``device_route_query_step``): the unrouted batch
    enters the jitted step B-sharded, owners are computed on device, rows
    exchange shard-to-shard with a dense all_to_all inside the shard_map
    body, and emitted rows re-merge into unsharded order on the way out —
    the round-5 host router (~75% of single-shard throughput, BENCH_r05)
    is gone from the loop entirely. Three numbers per run: the UNROUTED
    single-shard jit (the bar the 1-dev routed point must hold 0.9x of),
    the legacy host-routed 1-dev point (the before), and the
    device-routed 1/2/4/8 curve. Tunnel-independent: on the virtual CPU
    mesh shards share one host's cores, so the curve bounds overhead
    rather than demonstrating speedup."""
    import warnings

    import jax

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import PK_KEY, TS_KEY, TYPE_KEY, VALID_KEY
    from siddhi_tpu.parallel.mesh import (
        device_route_query_step, make_mesh, route_batch_to_shards,
        shard_keyed_query_step)

    rng = np.random.default_rng(5)
    B = BATCH

    def make_batch(i):
        sym = rng.integers(0, NUM_KEYS, B, dtype=np.int64)
        return {
            TS_KEY: np.arange(i * B, (i + 1) * B, dtype=np.int64),
            TYPE_KEY: np.zeros(B, np.int8),
            VALID_KEY: np.ones(B, bool),
            "symbol": sym,
            "symbol?": np.zeros(B, bool),
            "price": (rng.random(B) * 100.0).astype(np.float32),
            "price?": np.zeros(B, bool),
            "volume": rng.integers(1, 1000, B, dtype=np.int64),
            "volume?": np.zeros(B, bool),
            GK_KEY: sym.astype(np.int32),
            PK_KEY: sym.astype(np.int32),
        }

    def _pow2(n):
        k = 16
        while k < n:
            k *= 2
        return k

    batches = [make_batch(i) for i in range(4)]

    def timed_loop(fn):
        for i in range(3):
            st = fn(i)
        jax.block_until_ready(st)
        t0 = time.perf_counter()
        n = i = 0
        while True:
            st = fn(i)
            n += B
            i += 1
            if i % 10 == 0:
                jax.block_until_ready(st)
                if time.perf_counter() - t0 >= MEASURE_SECONDS / 2:
                    break
        jax.block_until_ready(st)
        return n / (time.perf_counter() - t0)

    def fresh_runtime(num_keys):
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(_PARTITIONED_APP)
        rt.start()
        q = rt.query_runtimes["bench"]
        q.selector_plan.num_keys = num_keys
        q._win_keys = num_keys
        return manager, q

    result = {}

    # --- unrouted single-shard baseline: the plain jitted step
    manager, q = fresh_runtime(16_384)
    step = jax.jit(q.build_step_fn(), donate_argnums=0)
    holder = {"st": q._init_state()}

    def run_plain(i):
        holder["st"], _out = step(holder["st"], batches[i % 4], np.int64(0))
        return holder["st"]

    result["unrouted_1dev"] = timed_loop(run_plain)
    manager.shutdown()

    # --- legacy host router at 1 dev (the round-5 "before" point)
    manager, q = fresh_runtime(16_384)
    hstep, hstate = shard_keyed_query_step(q, make_mesh(1), rows_per_shard=B)
    hold = {"st": hstate}

    def run_host(i):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            rb = route_batch_to_shards(batches[i % 4], 1, B)
        hold["st"], _out = hstep(hold["st"], rb, np.int64(0))
        return hold["st"]

    result["host_routed_1dev"] = timed_loop(run_host)
    manager.shutdown()

    # --- device-routed curve: routing inside the jitted step
    device_routed = {}
    for n_dev in (1, 2, 4, 8):
        manager, q = fresh_runtime(16_384)
        rows_per_shard = B if n_dev == 1 else int(B / n_dev * 1.25)
        step3, state = device_route_query_step(
            q, make_mesh(n_dev), rows_per_shard=rows_per_shard)
        hold = {"st": state}

        def run_dev(i):
            hold["st"], out = step3(hold["st"], batches[i % 4], np.int64(0))
            return hold["st"]

        device_routed[str(n_dev)] = timed_loop(run_dev)
        # balanced random keys must never trip the exchange quota here
        _st, out = step3(hold["st"], batches[0], np.int64(0))
        assert int(np.asarray(out["__meta__"])[3]) == 0, "exchange overflow"
        manager.shutdown()
    result["device_routed"] = device_routed
    result["routed_vs_unrouted_1dev"] = round(
        device_routed["1"] / result["unrouted_1dev"], 3)
    return result


def bench_nfa_p99():
    """Config #4: `every e1=A -> e2=B[e2.v > e1.v] within 5 sec` over 10k
    partition keys, through the loop-free two-step NFA kernel
    (ops/nfa.py `_apply_stream_fast`, round 5). Two operating points from
    one session: p99 per-batch latency at the LATENCY batch size (1024
    rows — the adaptive batcher's low-delay end), and aggregate events/sec
    at the THROUGHPUT batch size (4096 — amortizes per-step dispatch;
    the junction's adaptive cap picks this trade-off live)."""
    from siddhi_tpu import SiddhiManager, StreamCallback

    app = """
    @app:playback
    define stream AStream (k string, v double);
    define stream BStream (k string, v double);
    partition with (k of AStream, k of BStream)
    begin
      @info(name = 'nfa')
      from every e1=AStream -> e2=BStream[e2.v > e1.v] within 5 sec
      select e1.v as v1, e2.v as v2
      insert into MatchStream;
    end;
    """
    manager = SiddhiManager()
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    # config #4 holds at most a couple of pending matches per key: 8 slots
    # (vs the 32 default) quarters the [K, S] state and the emission pull;
    # pipeline_depth=2 lets the A-batch and B-batch dispatches ride the
    # pump back-to-back (completion.py; wait-free NFA plans are eligible)
    manager.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.nfa_slots": "8", "siddhi_tpu.pipeline_depth": "2"}))
    rt = manager.create_siddhi_app_runtime(app)

    class Counter(StreamCallback):
        n = 0

        def receive_batch(self, batch, junction):
            Counter.n += batch.size

        def receive(self, events):
            Counter.n += len(events)

    rt.add_callback("MatchStream", Counter())
    ha = rt.get_input_handler("AStream")
    hb = rt.get_input_handler("BStream")

    rng = np.random.default_rng(2)
    B_LAT = int(os.environ.get("BENCH_NFA_BATCH", 1024))
    B_THR = int(os.environ.get("BENCH_NFA_BATCH_THR", 4096))

    # pre-size the key space so key registration never grows capacity
    # mid-run (each pow2 growth would re-jit the [K, S] step), and warm
    # BOTH measured batch shapes — one compiled shape per (stream, B)
    q = rt.query_runtimes["nfa"]
    q._win_keys = 16_384
    q.selector_plan.num_keys = 16_384
    for B in {B_LAT, B_THR}:
        for c0 in range(0, NUM_KEYS, B):
            wk = np.array([f"K{i}" for i in range(c0, c0 + B)], dtype=object)
            wts = np.full(B, 1_000, np.int64)
            ha.send_columns({"k": wk, "v": np.zeros(B)}, timestamps=wts)
            hb.send_columns({"k": wk, "v": np.ones(B)}, timestamps=wts + 1)

    t_ms = 10_000

    def measure(B: int, seconds: float):
        nonlocal t_ms
        lat = []
        n = 0
        t_end = time.perf_counter() + seconds
        while time.perf_counter() < t_end:
            keys = rng.integers(0, NUM_KEYS, B)
            ka = np.array([f"K{i}" for i in keys], dtype=object)
            va = rng.random(B) * 100.0
            ts = np.full(B, t_ms, np.int64)
            t0 = time.perf_counter()
            ha.send_columns({"k": ka, "v": va}, timestamps=ts)
            hb.send_columns({"k": ka, "v": va + 1.0}, timestamps=ts + 1)
            lat.append((time.perf_counter() - t0) * 1000.0 / 2)  # per batch
            n += 2 * B
            t_ms += 10
        lat = np.sort(np.asarray(lat))
        p99 = float(lat[min(len(lat) - 1, int(len(lat) * 0.99))])
        return p99, n / float(np.sum(lat) * 2 / 1000.0)

    p99, _ = measure(B_LAT, MEASURE_SECONDS / 2)       # latency point
    measure(B_THR, 1.0)                                # settle the new shape
    _, eps = measure(B_THR, MEASURE_SECONDS)           # throughput point
    manager.shutdown()
    assert Counter.n > 0
    return p99, eps


def bench_fanout():
    """Fan-out amortization curve (ISSUE 4): N identical bench-shape
    queries (10k-key length(1000) -> avg/sum group by symbol) subscribed
    to ONE stream, fused (one jitted dispatch + one combined __meta__
    pull per junction batch — core/query/fused_fanout.py) vs unfused
    (N dispatches + N pulls). Records, per (n_queries, mode):
    input events/sec, p99 per-batch send latency, and the measured
    dispatches per batch (from the telemetry counters, not assumed).
    The batch size (BENCH_FANOUT_BATCH, default 8192) sits at the
    dispatch-bound end of the e2e curve, where fan-out overhead is the
    cost being amortized."""
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    B = int(os.environ.get("BENCH_FANOUT_BATCH", 8192))
    rng = np.random.default_rng(11)
    sym_strings = np.array([f"S{i}" for i in range(NUM_KEYS)], dtype=object)
    q_tmpl = """
    @info(name = 'q{I}')
    from StockStream#window.length({W})
    select symbol, avg(price) as avgPrice, sum(volume) as totalVolume
    group by symbol
    insert into Out{I};"""

    def run_one(n: int, fused: bool):
        app = ("define stream StockStream "
               "(symbol string, price float, volume long);\n")
        app += "\n".join(q_tmpl.format(I=i, W=WINDOW) for i in range(n))
        manager = SiddhiManager()
        manager.set_config_manager(InMemoryConfigManager(
            {"siddhi_tpu.fuse_fanout": "1" if fused else "0"}))
        rt = manager.create_siddhi_app_runtime(app)

        class Counter(StreamCallback):
            n_out = 0

            def receive_batch(self, batch, junction):
                Counter.n_out += batch.size

            def receive(self, events):
                Counter.n_out += len(events)

        for i in range(n):
            rt.add_callback(f"Out{i}", Counter())
            rt.query_runtimes[f"q{i}"].selector_plan.num_keys = 16_384
        h = rt.get_input_handler("StockStream")
        warm_sym = sym_strings[np.arange(B, dtype=np.int64) % NUM_KEYS]
        h.send_columns({"symbol": warm_sym,
                        "price": np.ones(B, np.float32),
                        "volume": np.ones(B, np.int64)},
                       timestamps=np.zeros(B, np.int64))
        pre = []
        for i in range(4):
            ids = rng.integers(0, NUM_KEYS, B, dtype=np.int64)
            pre.append(({
                "symbol": sym_strings[ids],
                "price": (rng.random(B) * 100.0).astype(np.float32),
                "volume": rng.integers(1, 1000, B, dtype=np.int64),
            }, np.arange(i * B, (i + 1) * B, dtype=np.int64)))
        h.send_columns(pre[0][0], timestamps=pre[0][1])   # settle the shape
        h.send_columns(pre[1][0], timestamps=pre[1][1])
        tel = rt.app_context.telemetry
        base = tel.snapshot()
        # three windows per mode, best-window eps: a single-core sandbox
        # jitters +-15% across 2 s windows, and the N=1 ratio (where
        # fused == unfused code paths exactly) must not drown in it
        lat = []
        n_batches = 0
        best_eps = 0.0
        i = 0
        for _w in range(3):
            w_lat = []
            t_end = time.perf_counter() + MEASURE_SECONDS / 3
            while time.perf_counter() < t_end:
                cols, ts = pre[i % 4]
                t0 = time.perf_counter()
                h.send_columns(cols, timestamps=ts)
                w_lat.append((time.perf_counter() - t0) * 1000.0)
                i += 1
            best_eps = max(best_eps,
                           len(w_lat) * B / float(np.sum(w_lat) / 1000.0))
            lat.extend(w_lat)
            n_batches += len(w_lat)
        snap = tel.snapshot()
        if fused and n > 1:
            dispatches = (snap["counters"]["fanout.StockStream.dispatches"]
                          - base["counters"]["fanout.StockStream.dispatches"])
        else:
            dispatches = 0
            for qi in range(n):
                rec = snap["jit"].get(f"query.q{qi}.step",
                                      {"compiles": 0, "hits": 0})
                rec0 = base["jit"].get(f"query.q{qi}.step",
                                       {"compiles": 0, "hits": 0})
                dispatches += (rec["compiles"] + rec["hits"]
                               - rec0["compiles"] - rec0["hits"])
        manager.shutdown()
        assert Counter.n_out > 0
        lat = np.sort(np.asarray(lat))
        return {
            "eps": round(best_eps, 1),
            "p99_ms": round(float(
                lat[min(len(lat) - 1, int(len(lat) * 0.99))]), 3),
            "dispatches_per_batch": round(dispatches / max(1, n_batches), 2),
        }

    points = []
    for n in (1, 2, 4, 8):
        unfused = run_one(n, fused=False)
        fused = run_one(n, fused=True)
        points.append({
            "n_queries": n, "batch": B,
            "eps_unfused": unfused["eps"], "eps_fused": fused["eps"],
            "speedup": round(fused["eps"] / unfused["eps"], 3),
            "p99_unfused_ms": unfused["p99_ms"],
            "p99_fused_ms": fused["p99_ms"],
            "dispatches_per_batch_unfused": unfused["dispatches_per_batch"],
            "dispatches_per_batch_fused": fused["dispatches_per_batch"],
        })
        print(json.dumps({"partial": points[-1]}), flush=True)
    return points


def bench_join():
    """Device join engine curve (ISSUE 9): a stream-stream length-window
    join driven through the real ingest path under two mixes —
    **probe-heavy** (the build side is pre-filled to its window capacity
    and held; every measured batch triggers probes against it) and
    **insert-heavy** (batches alternate sides under a selective ``on``
    condition, so window insert + directory upkeep dominate) — across
    join partition counts P in {1, 2, 4, 8} and pipeline depth {1, 2},
    plus the legacy synchronous probe path at depth 1 as the acceptance
    reference (the engine must hold >= 0.9x legacy at depth 1)."""
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    B = int(os.environ.get("BENCH_JOIN_BATCH", 2048))
    W = int(os.environ.get("BENCH_JOIN_WINDOW", 2048))
    K = 512                       # join key cardinality
    rng = np.random.default_rng(23)
    sym_strings = np.array([f"S{i}" for i in range(K)], dtype=object)
    app = f"""
define stream L (sym string, lv long);
define stream R (sym string, rv long);
@info(name='jq') from L#window.length({W}) join R#window.length({W})
  on L.sym == R.sym
  select L.sym as sym, L.lv as lv, R.rv as rv insert into JOut;
"""

    def batch(i, side):
        ids = rng.integers(0, K, B, dtype=np.int64)
        return ({"sym": sym_strings[ids],
                 ("lv" if side == "L" else "rv"):
                     rng.integers(0, 1000, B, dtype=np.int64)},
                np.arange(i * B, (i + 1) * B, dtype=np.int64))

    def run_one(mode: str, P: int, depth: int, mix: str) -> float:
        manager = SiddhiManager()
        manager.set_config_manager(InMemoryConfigManager({
            "siddhi_tpu.join_engine": mode,
            "siddhi_tpu.join_partitions": str(P),
            "siddhi_tpu.pipeline_depth": str(depth),
            "siddhi_tpu.window_capacity": str(W),
        }))
        rt = manager.create_siddhi_app_runtime(app)

        class Counter(StreamCallback):
            n_out = 0

            def receive_batch(self, b, junction):
                Counter.n_out += b.size

            def receive(self, events):
                Counter.n_out += len(events)

        rt.add_callback("JOut", Counter())
        hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
        if mix == "probe":
            # fill the build side to capacity once; measured batches all
            # probe (the PanJoin case: the partition directory cuts the
            # [B, W] condition surface ~P-fold)
            cols, ts = batch(0, "R")
            for j in range(W // B):
                hr.send_columns(cols, timestamps=ts)
        # warm both side steps' compiles out of the measure window
        for j in range(2):
            hl.send_columns(*batch(1 + j, "L"))
            hr.send_columns(*batch(3 + j, "R"))
        pre = [(side, batch(5 + j, side)) for j, side in enumerate(
            ["L"] * 8 if mix == "probe" else ["L", "R"] * 4)]
        n, i = 0, 0
        t0 = time.perf_counter()
        t_end = t0 + MEASURE_SECONDS / 2
        while time.perf_counter() < t_end:
            side, (cols, ts) = pre[i % len(pre)]
            (hl if side == "L" else hr).send_columns(cols, timestamps=ts)
            n += B
            i += 1
        eps = n / (time.perf_counter() - t0)
        manager.shutdown()
        assert Counter.n_out > 0
        return eps

    points = []
    for mix in ("probe", "insert"):
        ref = run_one("legacy", 1, 1, mix)
        rec = {"mix": mix, "batch": B, "window": W,
               "eps_legacy_d1": round(ref, 1), "device": []}
        for P in (1, 2, 4, 8):
            for depth in (1, 2):
                eps = run_one("device", P, depth, mix)
                rec["device"].append({
                    "P": P, "depth": depth, "eps": round(eps, 1),
                    "vs_legacy_d1": round(eps / ref, 3)})
                print(json.dumps({"partial": {"mix": mix, "P": P,
                                              "depth": depth,
                                              "eps": round(eps, 1)}}),
                      flush=True)
        points.append(rec)
    return points


def bench_ingest():
    """Multicore ingest front door curve (ISSUE 13): pack-path
    throughput over identical data for (a) the per-event
    ``HostBatch.from_events`` path, (b) the raw string-column
    ``from_columns`` path (dictionary encodes every batch), (c) the
    zero-copy wire path (``decode_frame`` LUT gather ->
    ``from_columns`` on pre-encoded ids — the POST /ingest/{stream}
    server cost), and (d) the parallel pack-pool curve over pool sizes
    {0, 2, 4} with a bit-identity assertion per point. The record
    carries ``host_cores`` explicitly: on a single-core sandbox the
    pool points bound coordination overhead, they cannot demonstrate
    the multicore speedup (the wire path's per-event-Python
    elimination is core-count-independent)."""
    from types import SimpleNamespace

    from siddhi_tpu.core.event import Event, HostBatch, StringDictionary
    from siddhi_tpu.core.stream.input.pack_pool import IngestPackPool
    from siddhi_tpu.core.stream.input.wire import (
        DecoderRegistry, WireEncoder, decode_frame)
    from siddhi_tpu.observability.telemetry import TelemetryRegistry
    from siddhi_tpu.query_api.definitions import (
        Attribute, AttrType, StreamDefinition)

    definition = StreamDefinition("StockStream", attributes=[
        Attribute("symbol", AttrType.STRING),
        Attribute("price", AttrType.FLOAT),
        Attribute("volume", AttrType.LONG)])
    B = BATCH
    rng = np.random.default_rng(17)
    ids = rng.integers(0, NUM_KEYS, B)
    syms = np.array([f"S{i}" for i in ids], dtype=object)
    price = (rng.random(B) * 100.0).astype(np.float32)
    volume = rng.integers(1, 1000, B, dtype=np.int64)
    ts = np.arange(B, dtype=np.int64)
    cols = {"symbol": syms, "price": price, "volume": volume}
    events = [Event(timestamp=int(t), data=[s, float(p), int(v)])
              for t, s, p, v in zip(ts, syms, price, volume)]

    def measure(fn, seconds=MEASURE_SECONDS / 2):
        fn()
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < seconds:
            fn()
            n += B
        return n / (time.perf_counter() - t0)

    d1 = StringDictionary()
    eps_events = measure(
        lambda: HostBatch.from_events(events, definition, d1))

    d2 = StringDictionary()
    eps_cols = measure(
        lambda: HostBatch.from_columns(cols, definition, d2,
                                       timestamps=ts))

    enc = WireEncoder()
    first = enc.encode(cols, timestamps=ts)
    frame = enc.encode(cols, timestamps=ts)     # steady state: no delta
    d3 = StringDictionary()
    reg = DecoderRegistry()
    decode_frame(first, definition, d3, reg)

    def wire_once():
        data, wts = decode_frame(frame, definition, d3, reg)
        HostBatch.from_columns(data, definition, d3, timestamps=wts)

    eps_wire = measure(wire_once)

    # --- parallel pack-pool curve, bit-identity asserted per point
    ref_d = StringDictionary()
    ref = HostBatch.from_events(events, definition, ref_d)
    pool_curve = []
    for workers in (0, 2, 4):
        if workers == 0:
            pool_curve.append({"pool": 0, "eps": round(eps_events, 1)})
            continue
        ctx = SimpleNamespace(name=f"bench-pool{workers}",
                              telemetry=TelemetryRegistry())
        pool = IngestPackPool(ctx, workers=workers, split_rows=8192)
        dp = StringDictionary()
        got = HostBatch.from_events(events, definition, dp, pool=pool)
        assert all(np.array_equal(got.cols[k], ref.cols[k])
                   for k in ref.cols), "pool pack diverged from inline"
        assert dp._to_str == ref_d._to_str, "dictionary order diverged"
        eps = measure(lambda: HostBatch.from_events(
            events, definition, dp, pool=pool))
        pool.shutdown()
        pool_curve.append({"pool": workers, "eps": round(eps, 1),
                           "vs_inline": round(eps / eps_events, 3)})

    return {
        "host_cores": os.cpu_count(),
        "batch": B,
        "frame_bytes": len(frame),
        "from_events_eps": round(eps_events, 1),
        "from_columns_str_eps": round(eps_cols, 1),
        "wire_eps": round(eps_wire, 1),
        "wire_vs_events": round(eps_wire / eps_events, 2),
        "pool_curve": pool_curve,
        "pool_identical": True,
    }


def bench_autopilot():
    """Closed-loop controller soak (ISSUE 16): one bursty "diurnal"
    feed — alternating quiet phases (idle gap before each batch) and
    burst phases (back-to-back) over an IDENTICAL chunk sequence —
    through the headline grouped-agg app under three configurations:
    the worst static operating point (depth 1, no ingest pool), the
    best static point (depth 4, pool 2), and autopilot ON starting
    from the worst point at an aggressive cadence. Records per-config
    events/sec + per-batch p99 + the controller's tick/freeze/decision
    counts, and asserts the autopilot run's output rows are
    bit-identical to both static runs — live actuation must never
    change semantics."""
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.autopilot import AutopilotController
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    B = 8_192
    N_KEYS = 1_024
    N_BATCH = 24
    rng = np.random.default_rng(23)
    sym_strings = np.array([f"S{i}" for i in range(N_KEYS)], dtype=object)
    chunks = []
    for i in range(N_BATCH):
        ids = rng.integers(0, N_KEYS, B, dtype=np.int64)
        chunks.append((
            {"symbol": sym_strings[ids],
             "price": (rng.random(B) * 100.0).astype(np.float32),
             "volume": rng.integers(1, 1000, B, dtype=np.int64)},
            np.arange(i * B, (i + 1) * B, dtype=np.int64)))
    # diurnal schedule: quiet-phase batches idle 20 ms before sending
    # (trough), burst-phase batches go back-to-back (peak); the SAME
    # batches in the SAME order for every configuration
    quiet = {i for i in range(N_BATCH) if (i // 4) % 2 == 0}

    def run(knobs, autopilot=False):
        manager = SiddhiManager()
        cfg = {"siddhi_tpu.ingest_split": "8"}
        cfg.update(knobs)
        if autopilot:
            cfg.update({"siddhi_tpu.autopilot": "on",
                        "siddhi_tpu.autopilot_interval_s": "0.05",
                        "siddhi_tpu.autopilot_cooldown_s": "0.1"})
        manager.set_config_manager(InMemoryConfigManager(cfg))
        rt = manager.create_siddhi_app_runtime(_APP)
        rows = []

        class Sink(StreamCallback):
            def receive(self, events):
                rows.extend(tuple(e.data) for e in events)

        rt.add_callback("OutStream", Sink())
        rt.start()
        rt.query_runtimes["bench"].selector_plan.num_keys = 2_048
        h = rt.get_input_handler("StockStream")
        # warm OUTSIDE the timed window: a full-key batch at the
        # measured shape settles the compiles every config would hit
        warm_ids = np.arange(B, dtype=np.int64) % N_KEYS
        h.send_columns({"symbol": sym_strings[warm_ids],
                        "price": np.ones(B, np.float32),
                        "volume": np.ones(B, np.int64)},
                       timestamps=np.zeros(B, np.int64))
        warm_rows = len(rows)
        ctl = AutopilotController.instance()
        lat = []
        t0 = time.perf_counter()
        for i, (cols, ts) in enumerate(chunks):
            if i in quiet:
                time.sleep(0.02)
            tb = time.perf_counter()
            h.send_columns(cols, timestamps=ts)
            lat.append(time.perf_counter() - tb)
            if autopilot and i % 4 == 3:
                # deterministic cadence on top of the interval thread —
                # the same manual-tick drive the soak and tests use
                ctl.tick(rt.name)
        elapsed = time.perf_counter() - t0
        ticks = freezes = applied = logged = 0
        if autopilot:
            rep = ctl.report(rt.name)["apps"][rt.name]
            ticks, freezes = rep["ticks"], rep["freezes"]
            logged = len(rep["decisions"])
            applied = sum(1 for d in rep["decisions"] if d.get("applied"))
        out_rows = rows[warm_rows:]
        manager.shutdown()
        return {
            "eps": round(N_BATCH * B / elapsed, 1),
            "p99_ms": round(float(np.percentile(
                np.array(lat) * 1e3, 99)), 3),
            "ticks": ticks,
            "freezes": freezes,
            "decisions_logged": logged,
            "decisions_applied": applied,
        }, out_rows

    worst, ref = run({"siddhi_tpu.pipeline_depth": "1"})
    best, ref_best = run({"siddhi_tpu.pipeline_depth": "4",
                          "siddhi_tpu.ingest_pool": "2"})
    ap, ap_rows = run({"siddhi_tpu.pipeline_depth": "1"}, autopilot=True)
    assert ref_best == ref, "static configs diverged"
    assert ap_rows == ref, "autopilot run diverged from static baseline"
    return {
        "batch": B,
        "batches": N_BATCH,
        "keys": N_KEYS,
        "static_worst": worst,
        "static_best": best,
        "autopilot": ap,
        "autopilot_vs_worst": round(ap["eps"] / worst["eps"], 3),
        "autopilot_vs_best": round(ap["eps"] / best["eps"], 3),
        "identical": True,
    }


def bench_cluster():
    """Cluster-fabric scaling curve (ISSUE 17): the soak driver's
    partitioned lengthBatch app over 1/2/4 REAL worker processes,
    no kill, exactness asserted against the single-process run. The
    soak tool owns the workload (tools/cluster_soak.py) so the bench
    number and the resilience soak measure the identical feed; this
    wrapper just reruns it in pure-scaling mode and reshapes the
    result. NOTE this host's core count bounds the curve — on a
    single-core container the honest ceiling is "no slowdown", not a
    speedup, so the record carries host_cpus alongside the points."""
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "cluster_soak.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, tool, "--workers", "1,2,4",
         "--batches", "48", "--rows", "256", "--no-kill"],
        capture_output=True, text=True, timeout=280, env=env)
    if r.returncode != 0:
        raise RuntimeError(f"cluster_soak failed rc={r.returncode}: "
                           f"{r.stderr[-1000:]}")
    soak = json.loads(r.stdout.strip().splitlines()[-1])
    assert soak["exact"], "cluster egress diverged from single-process"
    return {
        "host_cpus": soak["host_cpus"],
        "events": soak["events"],
        "single_process_eps": soak["single_process_events_per_s"],
        "points": {str(p["workers"]): p["events_per_s"]
                   for p in soak["curve"]},
        "exact": True,
    }


def bench_programs():
    """Compiled-program cache acceptance curve (ISSUE 20): the
    fleet-soak driver's 32-app fleet of IDENTICAL fuzz apps, cache on
    vs off. The soak tool owns the workload (tools/fleet_soak.py — live
    wire ingest, mid-soak blue/green replace, snapshot/restore, all
    bit-identity asserted in-process) so the bench number and the soak
    measure the identical feed; this wrapper reruns it in bench shape,
    enforces the acceptance floors, and records the install-time curve
    into BENCH_r10.json (`--section programs` is the writer — the main
    harness keeps owning BENCH_r09.json)."""
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "fleet_soak.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, tool, "--identical", "32", "--churn", "1",
         "--events", "8", "--compare-off"],
        capture_output=True, text=True, timeout=560, env=env)
    if r.returncode != 0:
        raise RuntimeError(f"fleet_soak failed rc={r.returncode}: "
                           f"{r.stderr[-1000:]}")
    soak = json.loads(r.stdout.strip().splitlines()[-1])
    # acceptance floors: each distinct program compiled exactly ONCE
    # across the 32-tenant fleet, and warm installs >= 3x faster than
    # program_cache: off
    assert soak["total_compiles"] == soak["distinct_programs"], soak
    assert soak["install_speedup_rest"] >= 3.0, (
        f"warm-install speedup {soak['install_speedup_rest']} < 3x")
    assert soak["snapshot_restore_exact"], soak
    record = {
        "fleet_apps": soak["tenants_per_case"],
        "distinct_programs": soak["distinct_programs"],
        "total_compiles": soak["total_compiles"],
        "cache_hits": soak["cache_hits"],
        "install_ms_curve_on": soak["install_ms_curve"],
        "install_ms_curve_off": soak["off_install_ms_curve"],
        "install_ms_rest_mean_on": soak["install_ms_rest_mean"],
        "install_ms_rest_mean_off": soak["off_install_ms_rest_mean"],
        "install_speedup_rest": soak["install_speedup_rest"],
        "blue_green_replacements": soak["churn_replacements"],
        "snapshot_restore_exact": True,
        "backend": "cpu",
    }
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r10.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"programs": record}, f, indent=1)
            f.write("\n")
    except OSError:
        pass
    return record


# --------------------------------------------------------------- harness


def _run_section_once(name: str, timeout_s: float):
    """Run one bench section in a fresh subprocess (each section gets its
    own axon tunnel session — in-process back-to-back sections wedge the
    single-client tunnel on the previous section's buffer teardown).

    ONE attempt only: a timeout-killed client re-wedges the tunnel for
    minutes, so retrying converts one stall into a voided bench (the
    round-2 failure). Returns (result dict | None, timed_out flag)."""
    import subprocess
    import sys

    if timeout_s < 30:
        print(f"[bench] skipping {name}: budget exhausted",
              file=sys.stderr, flush=True)
        return None, False
    print(f"[bench] {name} section (timeout {int(timeout_s)}s)…",
          file=sys.stderr, flush=True)
    env = dict(os.environ)
    if name.endswith("_cpu"):
        env["BENCH_FORCE_CPU"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--section",
             name.removesuffix("_cpu")],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
    except subprocess.TimeoutExpired:
        print(f"[bench] {name} TIMED OUT after {int(timeout_s)}s",
              file=sys.stderr, flush=True)
        return None, True
    if r.returncode != 0:
        print(f"[bench] {name} failed rc={r.returncode}:\n{r.stderr[-2000:]}",
              file=sys.stderr, flush=True)
        return None, False
    try:
        out = json.loads(r.stdout.strip().splitlines()[-1])
    except Exception:
        print(f"[bench] {name} emitted no JSON:\n{r.stdout[-500:]}",
              file=sys.stderr, flush=True)
        return None, False
    print(f"[bench] {name}: {out}", file=sys.stderr, flush=True)
    return out, False


def _probe_tunnel(timeout_s: float = 30.0) -> dict:
    """Cheap tunnel liveness probe: import jax + list devices in a fresh
    subprocess — NO jit, so a wedged tunnel costs ``timeout_s``, not a
    300 s bench section (VERDICT r04 next #1a). Returns a timestamped
    record that main() appends to the result's ``tunnel_probes`` log."""
    import datetime
    import subprocess
    import sys

    t0 = time.time()
    rec = {"t": datetime.datetime.now().isoformat(timespec="seconds"),
           "alive": False, "platform": None, "elapsed_s": None}
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "BENCH_FORCE_CPU")}
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env)
        if r.returncode == 0:
            rec["platform"] = r.stdout.strip().splitlines()[-1]
            rec["alive"] = rec["platform"] not in ("cpu", "", None)
    except subprocess.TimeoutExpired:
        pass
    rec["elapsed_s"] = round(time.time() - t0, 1)
    print(f"[bench] tunnel probe: {rec}", file=sys.stderr, flush=True)
    return rec


def main():
    import sys

    t_start = time.perf_counter()
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", 780.0))

    def remaining() -> float:
        return budget - (time.perf_counter() - t_start)

    result = {
        "metric": "events_per_sec_10k_key_length1000_avg",
        "value": None,
        "unit": "events/sec/chip",
        "vs_baseline": None,
        "baseline_events_per_sec": MEASURED_BASELINE_EPS,
        "baseline_source": "tools/baseline_cpp (measured; no JVM in image)",
        "device_backend": None,
        "e2e_events_per_sec": None,            # genuine string ingest
        "e2e_preencoded_events_per_sec": None,  # int ids (no dict encode)
        "e2e_cpu_events_per_sec": None,         # string ingest, CPU backend
        "e2e_curve": None,                      # [(batch, depth, eps, p99)]
        "e2e_curve_backend": None,
        "fanout_curve": None,                   # fused vs unfused, N queries
        "fanout_backend": None,
        "pipeline_curve": None,                 # [(depth, eps, metas/pull)]
        "pipeline_backend": None,
        "serving_curve": None,                  # shard-count mixed-load curve
        "serving_backend": None,
        "host_pipeline_events_per_sec": None,   # device step stubbed
        "ingest_csv_events_per_sec": None,      # native CSV loader -> pump
        "host_cores": os.cpu_count(),           # single-core caveat, explicit
        "ingest_curve": None,                   # wire + parallel-pack paths
        "autopilot_soak": None,                 # controller vs static configs
        "cluster_scaling": None,                # 1/2/4 worker processes (r09)
        "mesh_scaling_eps": None,               # {n_devices: eps}, key-sharded
        "mesh_scaling_backend": None,
        "nfa_p99_ms_per_batch": None,
        "nfa_events_per_sec": None,
        "nfa_backend": None,
        "batch": BATCH,
        "measure_seconds": MEASURE_SECONDS,
        # '_avg' in the metric name is the avg() aggregator in the query,
        # not run averaging; single run per section (see harness docstring)
        "runs": "once_per_section_incremental_flush",
        "sections_failed": [],
    }

    def emit():
        line = json.dumps(result)
        print(line, flush=True)
        # machine-readable perf-trajectory artifact (the r06 round landed
        # only prose — BENCH_r06.md): the cumulative record is rewritten
        # after EVERY section so a later wedge can never void it
        try:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_r09.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(result, f, indent=1)
                f.write("\n")
        except OSError:
            pass

    result["tunnel_probes"] = []

    def run_tunnel_sections():
        """device -> e2e -> nfa against the (probed-alive) tunnel; a
        section timeout marks the tunnel wedged and skips the rest."""
        # a revival re-run supersedes the first attempt's failure tags —
        # drop them so the record can't carry both a result and its failure
        stale = {"device", "e2e", "nfa", "e2e_curve", "fanout", "pipeline",
                 "e2e:skipped-wedged-tunnel",
                 "nfa:skipped-wedged-tunnel", "tunnel:probe-dead"}
        result["sections_failed"] = [
            s for s in result["sections_failed"] if s not in stale]
        wedged = False
        out, t_o = _run_section_once("device", min(300.0, remaining()))
        if out is not None:
            result["value"] = round(out["eps"], 1)
            result["vs_baseline"] = round(
                out["eps"] / MEASURED_BASELINE_EPS, 3)
            result["device_backend"] = out.get("platform", "tpu")
        else:
            result["sections_failed"].append("device")
            wedged |= t_o
        emit()

        if not wedged:
            out, t_o = _run_section_once("e2e", min(300.0, remaining()))
            if out is not None:
                result["e2e_events_per_sec"] = round(out["eps_str"], 1)
                result["e2e_preencoded_events_per_sec"] = round(
                    out["eps_pre"], 1)
            else:
                result["sections_failed"].append("e2e")
                wedged |= t_o
            emit()
        else:
            result["sections_failed"].append("e2e:skipped-wedged-tunnel")

        if not wedged:
            out, t_o = _run_section_once("nfa", min(300.0, remaining()))
            if out is not None:
                result["nfa_p99_ms_per_batch"] = round(out["p99_ms"], 3)
                result["nfa_events_per_sec"] = round(out["eps"], 1)
                result["nfa_backend"] = "tpu"
            else:
                result["sections_failed"].append("nfa")
                wedged |= t_o
            emit()
        else:
            result["sections_failed"].append("nfa:skipped-wedged-tunnel")

        if not wedged:
            out, t_o = _run_section_once("e2e_curve", min(240.0, remaining()))
            if out is not None:
                result["e2e_curve"] = out["points"]
                result["e2e_curve_backend"] = "tpu"
            else:
                result["sections_failed"].append("e2e_curve")
            emit()

        if not wedged:
            out, t_o = _run_section_once("fanout", min(300.0, remaining()))
            if out is not None:
                result["fanout_curve"] = out["points"]
                result["fanout_backend"] = "tpu"
            else:
                result["sections_failed"].append("fanout")
            emit()

        if not wedged:
            # the depth curve's overlap term (max(pack, step+pull) vs
            # pack+step+pull) only exists where the ~70 ms pull toll does
            # — measure on the live tunnel when it's up
            out, t_o = _run_section_once("pipeline", min(300.0, remaining()))
            if out is not None:
                result["pipeline_curve"] = out["points"]
                result["pipeline_backend"] = "tpu"
            else:
                result["sections_failed"].append("pipeline")
            emit()

    # ---- probe first: a wedged tunnel costs one 30 s probe, not a 300 s
    # section timeout; probe log rides the result line (VERDICT r04 #1)
    probe = _probe_tunnel(min(30.0, remaining()))
    result["tunnel_probes"].append(probe)
    emit()
    if probe["alive"]:
        run_tunnel_sections()
    else:
        result["sections_failed"].append("tunnel:probe-dead")
    if result["nfa_p99_ms_per_batch"] is None:
        # labeled CPU fallback: the p99 record must not be another null
        out, _ = _run_section_once("nfa_cpu", min(240.0, remaining()))
        if out is not None:
            result["nfa_p99_ms_per_batch"] = round(out["p99_ms"], 3)
            result["nfa_events_per_sec"] = round(out["eps"], 1)
            result["nfa_backend"] = "cpu-fallback"
        emit()

    # ---- CPU sections: can't wedge, run even after a tunnel stall
    out, _ = _run_section_once("host_pipeline_cpu", min(180.0, remaining()))
    if out is not None:
        result["host_pipeline_events_per_sec"] = round(out["eps_pipeline"], 1)
        result["ingest_csv_events_per_sec"] = round(out["eps_csv"], 1)
    else:
        result["sections_failed"].append("host_pipeline")
    emit()
    out, _ = _run_section_once("e2e_cpu", min(240.0, remaining()))
    if out is not None:
        result["e2e_cpu_events_per_sec"] = round(out["eps_str"], 1)
    else:
        result["sections_failed"].append("e2e_cpu")
    emit()
    # multicore ingest front door (ISSUE 13): pure host workload —
    # from_events vs wire-format vs parallel-pack pool, never tunnel-gated
    out, _ = _run_section_once("ingest_cpu", min(180.0, remaining()))
    if out is not None:
        result["ingest_curve"] = out["ingest"]
    else:
        result["sections_failed"].append("ingest")
    emit()
    # closed-loop autopilot soak (ISSUE 16): bursty feed, controller vs
    # best/worst static configs, bit-identity asserted inside the
    # section — pure host orchestration, never tunnel-gated
    out, _ = _run_section_once("autopilot_cpu", min(240.0, remaining()))
    if out is not None:
        result["autopilot_soak"] = out["autopilot"]
    else:
        result["sections_failed"].append("autopilot")
    emit()
    # cluster-fabric scaling (ISSUE 17): 1/2/4 REAL worker processes
    # through the router, exactness asserted in-section vs the
    # single-process run — plain sockets + CPU engines, never
    # tunnel-gated
    out, _ = _run_section_once("cluster_cpu", min(300.0, remaining()))
    if out is not None:
        result["cluster_scaling"] = out["cluster"]
    else:
        result["sections_failed"].append("cluster")
    emit()
    if result["e2e_curve"] is None:
        # the curve is no longer tunnel-gated: the adaptive batcher's
        # throughput/p99 trade-off gets a recorded artifact on whatever
        # backend exists, labeled so a live-TPU run supersedes it
        out, _ = _run_section_once("e2e_curve_cpu", min(240.0, remaining()))
        if out is not None:
            result["e2e_curve"] = out["points"]
            result["e2e_curve_backend"] = "cpu-fallback"
        else:
            result["sections_failed"].append("e2e_curve")
        emit()
    if result["fanout_curve"] is None:
        # fan-out amortization gets a recorded artifact on whatever
        # backend exists, labeled so a live-TPU run supersedes it
        out, _ = _run_section_once("fanout_cpu", min(300.0, remaining()))
        if out is not None:
            result["fanout_curve"] = out["points"]
            result["fanout_backend"] = "cpu-fallback"
        else:
            result["sections_failed"].append("fanout")
        emit()
    if result["pipeline_curve"] is None:
        # dispatch-pipeline depth curve (ISSUE 5): recorded on whatever
        # backend exists; on the tunnel the overlap term dominates, on a
        # single-core CPU it is a no-regression check
        out, _ = _run_section_once("pipeline_cpu", min(240.0, remaining()))
        if out is not None:
            result["pipeline_curve"] = out["points"]
            result["pipeline_backend"] = "cpu-fallback"
        else:
            result["sections_failed"].append("pipeline")
        emit()
    # device join engine curve (ISSUE 9): probe-heavy vs insert-heavy mix
    # over P x depth, vs the legacy synchronous probe path
    out, _ = _run_section_once("join_cpu", min(300.0, remaining()))
    if out is not None:
        result["join_curve"] = out["points"]
        result["join_backend"] = "cpu-fallback"
    else:
        result["sections_failed"].append("join")
    emit()
    # serving-tier shard curve (ISSUE 6): mixed ingest + on-demand store
    # queries over 1/2/4/8 aggregation shards; CPU-only workload today
    # (the rollup cube lives host-side), so never tunnel-gated
    out, _ = _run_section_once("serving_cpu", min(300.0, remaining()))
    if out is not None:
        result["serving_curve"] = out["points"]
        result["serving_backend"] = "cpu-fallback"
    else:
        result["sections_failed"].append("serving")
    emit()
    out, _ = _run_section_once("scaling_cpu", min(240.0, remaining()))
    if out is not None:
        mesh = out["mesh"]
        result["mesh_scaling_eps"] = {
            k: round(v, 1) for k, v in mesh["device_routed"].items()}
        result["mesh_unrouted_1dev_eps"] = round(mesh["unrouted_1dev"], 1)
        result["mesh_host_routed_1dev_eps"] = round(
            mesh["host_routed_1dev"], 1)
        result["mesh_routed_vs_unrouted_1dev"] = mesh[
            "routed_vs_unrouted_1dev"]
        result["mesh_scaling_backend"] = "cpu-8dev-virtual-mesh-device-routed"
    else:
        result["sections_failed"].append("scaling")
    emit()

    # ---- the tunnel has revived mid-round before (PERF.md r04): if the
    # start-of-run probe found it dead, spend a second probe at the END of
    # the budget and claim any revival window (VERDICT r04 #1c)
    if result["device_backend"] is None and remaining() > 90:
        probe = _probe_tunnel(min(30.0, remaining()))
        result["tunnel_probes"].append(probe)
        emit()
        if probe["alive"]:
            run_tunnel_sections()
    if result["value"] is None:
        # last-resort labeled fallback so the record always carries a
        # number: the device section on the CPU backend
        dev_cpu, _ = _run_section_once("device_cpu", min(240.0, remaining()))
        if dev_cpu is not None:
            result["value"] = round(dev_cpu["eps"], 1)
            result["vs_baseline"] = round(
                dev_cpu["eps"] / MEASURED_BASELINE_EPS, 3)
            result["device_backend"] = "cpu-fallback"
        emit()
    print(f"[bench] done in {time.perf_counter() - t_start:.0f}s; "
          f"failed={result['sections_failed']}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    import sys

    if len(sys.argv) >= 3 and sys.argv[1] == "--section":
        section = sys.argv[2]
        if os.environ.get("BENCH_FORCE_CPU"):
            # plugin platforms override JAX_PLATFORMS at interpreter start;
            # reset at the config level (see parallel/mesh.py). The
            # scaling section needs the full 8-device virtual mesh.
            from siddhi_tpu.parallel.mesh import force_host_devices

            force_host_devices(8 if section in ("scaling", "mesh") else 1)
        if section == "device":
            eps = bench_device()
            import jax

            print(json.dumps({"eps": eps,
                              "platform": jax.devices()[0].platform}))
        elif section == "e2e":
            eps_str, eps_pre = bench_e2e()
            print(json.dumps({"eps_str": eps_str, "eps_pre": eps_pre}))
        elif section == "host_pipeline":
            eps_pipeline, eps_csv = bench_host_pipeline()
            print(json.dumps({"eps_pipeline": eps_pipeline,
                              "eps_csv": eps_csv}))
        elif section == "nfa":
            p99, eps = bench_nfa_p99()
            print(json.dumps({"p99_ms": p99, "eps": eps}))
        elif section in ("scaling", "mesh"):
            print(json.dumps({"mesh": bench_mesh_scaling()}))
        elif section == "e2e_curve":
            print(json.dumps({"points": bench_e2e_curve()}))
        elif section == "fanout":
            print(json.dumps({"points": bench_fanout()}))
        elif section == "pipeline":
            print(json.dumps({"points": bench_pipeline_curve()}))
        elif section == "join":
            print(json.dumps({"points": bench_join()}))
        elif section == "ingest":
            print(json.dumps({"ingest": bench_ingest()}))
        elif section == "serving":
            print(json.dumps({"points": bench_serving()}))
        elif section == "autopilot":
            print(json.dumps({"autopilot": bench_autopilot()}))
        elif section == "cluster":
            print(json.dumps({"cluster": bench_cluster()}))
        elif section == "programs":
            print(json.dumps({"programs": bench_programs()}))
        else:
            raise SystemExit(f"unknown section {section}")
    else:
        main()
