"""Benchmark: the BASELINE.json north-star shapes on one chip.

Headline: events/sec on the 10k-key length(1000) -> avg/sum group-by
aggregation (BASELINE.json config #2/#3 family), measured against the
MEASURED single-threaded event-at-a-time native baseline
(tools/baseline_cpp/baseline.cpp — no JVM exists in this image; the C++
stand-in reproduces the reference hot path's per-event cost structure and
is, if anything, faster than the JVM it proxies, so vs_baseline is
conservative). Also measured and reported inside the same JSON line:

- e2e_events_per_sec: the same query driven through the REAL ingest path
  (InputHandler.send_columns -> StreamJunction -> QueryRuntime ->
  StreamCallback), not a pre-packed device loop;
- nfa_p99_ms / nfa_events_per_sec: per-batch latency of BASELINE.json
  config #4 (`every e1=A -> e2=B[e2.v>e1.v] within 5 sec` over 10k
  partition keys), p99 over the measured batches.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import time

# Persistent compilation cache: the three bench sections compile several
# large step graphs (~35s each over the axon tunnel on first run); cache
# them across runs so the driver's bench invocation stays fast.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

import numpy as np

# Measured on this host: tools/baseline_cpp/baseline.cpp, g++ -O2, 20M
# events (single-threaded event-at-a-time engine with the reference's
# per-event cost structure). See BASELINE.md.
MEASURED_BASELINE_EPS = 8.5e6

NUM_KEYS = 10_000
WINDOW = 1_000
BATCH = int(os.environ.get("BENCH_BATCH", 65_536))
MEASURE_SECONDS = float(os.environ.get("BENCH_SECONDS", 10.0))

_APP = """
define stream StockStream (symbol string, price float, volume long);
@info(name = 'bench')
from StockStream#window.length({W})
select symbol, avg(price) as avgPrice, sum(volume) as totalVolume
group by symbol
insert into OutStream;
""".format(W=WINDOW)


def bench_device():
    """Device-path throughput: pre-staged columnar batches through the
    fused query step (the selector/keyer warmed to full key capacity)."""
    import jax

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY

    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(_APP)
    rt.start()
    q = rt.query_runtimes["bench"]
    q.selector_plan.num_keys = 16_384  # >= NUM_KEYS, pow2: no growth re-jits

    rng = np.random.default_rng(0)

    def make_batch(i):
        sym = rng.integers(0, NUM_KEYS, BATCH, dtype=np.int64)
        return {
            TS_KEY: np.arange(i * BATCH, (i + 1) * BATCH, dtype=np.int64),
            TYPE_KEY: np.zeros(BATCH, np.int8),
            VALID_KEY: np.ones(BATCH, bool),
            "symbol": sym,
            "symbol?": np.zeros(BATCH, bool),
            "price": (rng.random(BATCH) * 100.0).astype(np.float32),
            "price?": np.zeros(BATCH, bool),
            "volume": rng.integers(1, 1000, BATCH, dtype=np.int64),
            "volume?": np.zeros(BATCH, bool),
            GK_KEY: sym.astype(np.int32),
        }

    state = q._init_state()
    step = jax.jit(q.build_step_fn(), donate_argnums=0)
    now = np.int64(0)
    batches = [jax.device_put(make_batch(i)) for i in range(4)]

    for i in range(3):
        state, out = step(state, batches[i % len(batches)], now)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    n_events = 0
    i = 0
    while True:
        state, out = step(state, batches[i % len(batches)], now)
        n_events += BATCH
        i += 1
        if i % 20 == 0:
            jax.block_until_ready(state)
            if time.perf_counter() - t0 >= MEASURE_SECONDS:
                break
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    manager.shutdown()
    return n_events / dt


def bench_e2e():
    """End-to-end: InputHandler.send_columns -> junction -> query ->
    StreamCallback (columnar), mirroring the reference harness methodology
    (SimpleFilterSingleQueryPerformance.java: pump, count outputs,
    events/sec) with the framework's bulk ingestion API."""
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    manager = SiddhiManager()
    # batch 8 step metas into one device->host round trip (the tunnel
    # charges ~70ms latency per pull — PERF.md); outputs drain every 8
    # batches and at shutdown
    manager.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.defer_meta": "8"}))
    rt = manager.create_siddhi_app_runtime(_APP)

    class Counter(StreamCallback):
        n = 0

        def receive_batch(self, batch, junction):
            Counter.n += batch.size

        def receive(self, events):
            Counter.n += len(events)

    rt.add_callback("OutStream", Counter())
    h = rt.get_input_handler("StockStream")
    q = rt.query_runtimes["bench"]
    q.selector_plan.num_keys = 16_384
    # register the symbol strings once so pre-encoded int ids decode cleanly
    dic = rt.app_context.string_dictionary
    for i in range(NUM_KEYS):
        dic.encode(f"S{i}")

    rng = np.random.default_rng(1)
    B = BATCH

    def make_cols(i):
        return {
            "symbol": rng.integers(0, NUM_KEYS, B, dtype=np.int64),
            "price": (rng.random(B) * 100.0).astype(np.float32),
            "volume": rng.integers(1, 1000, B, dtype=np.int64),
        }, np.arange(i * B, (i + 1) * B, dtype=np.int64)

    # warm at the MEASURED batch shape (pow2 padding would otherwise
    # compile a second shape): one B-row batch covering every key
    warm_sym = np.arange(B, dtype=np.int64) % NUM_KEYS
    h.send_columns({"symbol": warm_sym,
                    "price": np.ones(B, np.float32),
                    "volume": np.ones(B, np.int64)},
                   timestamps=np.zeros(B, np.int64))
    pre = [make_cols(i + 1) for i in range(4)]
    h.send_columns(pre[0][0], timestamps=pre[0][1])

    t0 = time.perf_counter()
    n = 0
    i = 0
    while time.perf_counter() - t0 < MEASURE_SECONDS:
        cols, ts = pre[i % len(pre)]
        h.send_columns(cols, timestamps=ts)
        n += B
        i += 1
    dt = time.perf_counter() - t0
    manager.shutdown()
    assert Counter.n > 0
    return n / dt


def bench_nfa_p99():
    """Config #4: `every e1=A -> e2=B[e2.v > e1.v] within 5 sec` over 10k
    partition keys; per-batch latency (ms) through the full host path,
    p99 over measured batches; plus aggregate events/sec."""
    from siddhi_tpu import SiddhiManager, StreamCallback

    app = """
    @app:playback
    define stream AStream (k string, v double);
    define stream BStream (k string, v double);
    partition with (k of AStream, k of BStream)
    begin
      @info(name = 'nfa')
      from every e1=AStream -> e2=BStream[e2.v > e1.v] within 5 sec
      select e1.v as v1, e2.v as v2
      insert into MatchStream;
    end;
    """
    manager = SiddhiManager()
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    # config #4 holds at most a couple of pending matches per key: 8 slots
    # (vs the 32 default) quarters the [K, S] state and the emission pull;
    # defer_meta=2 folds the A-batch and B-batch metas into one ~70ms
    # tunnel round trip per iteration (wait-free plan: safe to defer)
    manager.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.nfa_slots": "8", "siddhi_tpu.defer_meta": "2"}))
    rt = manager.create_siddhi_app_runtime(app)

    class Counter(StreamCallback):
        n = 0

        def receive_batch(self, batch, junction):
            Counter.n += batch.size

        def receive(self, events):
            Counter.n += len(events)

    rt.add_callback("MatchStream", Counter())
    ha = rt.get_input_handler("AStream")
    hb = rt.get_input_handler("BStream")

    rng = np.random.default_rng(2)
    B = 1024

    # pre-size the key space so key registration never grows capacity
    # mid-run (each pow2 growth would re-jit the [K, S] step), and warm
    # with B-row batches only — ONE compiled shape per stream
    q = rt.query_runtimes["nfa"]
    q._win_keys = 16_384
    q.selector_plan.num_keys = 16_384
    for c0 in range(0, NUM_KEYS, B):
        wk = np.array([f"K{i}" for i in range(c0, c0 + B)], dtype=object)
        wts = np.full(B, 1_000, np.int64)
        ha.send_columns({"k": wk, "v": np.zeros(B)}, timestamps=wts)
        hb.send_columns({"k": wk, "v": np.ones(B)}, timestamps=wts + 1)

    lat = []
    n = 0
    t_ms = 10_000
    t_end = time.perf_counter() + MEASURE_SECONDS
    while time.perf_counter() < t_end:
        keys = rng.integers(0, NUM_KEYS, B)
        ka = np.array([f"K{i}" for i in keys], dtype=object)
        va = rng.random(B) * 100.0
        ts = np.full(B, t_ms, np.int64)
        t0 = time.perf_counter()
        ha.send_columns({"k": ka, "v": va}, timestamps=ts)
        hb.send_columns({"k": ka, "v": va + 1.0}, timestamps=ts + 1)
        lat.append((time.perf_counter() - t0) * 1000.0 / 2)  # per batch
        n += 2 * B
        t_ms += 10
    manager.shutdown()
    assert Counter.n > 0
    lat = np.sort(np.asarray(lat))
    p99 = float(lat[min(len(lat) - 1, int(len(lat) * 0.99))])
    total_t = float(np.sum(lat) * 2 / 1000.0)
    return p99, n / total_t


def _run_section(name: str) -> dict:
    """Run one bench section in a fresh subprocess: each section gets its
    own axon tunnel session — in-process back-to-back sections wedge the
    single-client tunnel on the previous section's buffer teardown."""
    import subprocess
    import sys

    print(f"[bench] {name} section…", file=sys.stderr, flush=True)
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--section", name],
        capture_output=True, text=True, timeout=1200,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if r.returncode != 0:
        print(r.stderr[-2000:], file=sys.stderr, flush=True)
        raise RuntimeError(f"bench section {name} failed rc={r.returncode}")
    out = json.loads(r.stdout.strip().splitlines()[-1])
    print(f"[bench] {name}: {out}", file=sys.stderr, flush=True)
    return out


def _best_of(name: str, runs: int = 2) -> dict:
    """Best of N runs per section: the tunnel occasionally stalls for
    hundreds of ms (PERF.md cost model), which can crater one measurement
    window; the max-throughput / min-latency run is the honest capability
    number. A run that dies (tunnel wedge) is skipped as long as at least
    one run of the section succeeded — and a completely failed section
    returns None rather than sinking the whole bench."""
    import sys

    best = None
    for _ in range(runs):
        try:
            out = _run_section(name)
        except Exception as e:  # timeout / wedged tunnel / crash
            print(f"[bench] {name} run failed: {e}", file=sys.stderr, flush=True)
            continue
        if best is None:
            best = out
        elif "p99_ms" in out:
            if out["p99_ms"] < best["p99_ms"]:
                best = out
        elif out["eps"] > best["eps"]:
            best = out
    return best


def main():
    dev = _best_of("device")
    e2e = _best_of("e2e")
    nfa = _best_of("nfa")
    if dev is None:
        raise RuntimeError("device bench section failed on every attempt")
    eps_device = dev["eps"]
    print(json.dumps({
        "metric": "events_per_sec_10k_key_length1000_avg",
        "value": round(eps_device, 1),
        "unit": "events/sec/chip",
        "vs_baseline": round(eps_device / MEASURED_BASELINE_EPS, 3),
        "baseline_events_per_sec": MEASURED_BASELINE_EPS,
        "baseline_source": "tools/baseline_cpp (measured; no JVM in image)",
        "e2e_events_per_sec": round(e2e["eps"], 1) if e2e else None,
        "nfa_p99_ms_per_batch": round(nfa["p99_ms"], 3) if nfa else None,
        "nfa_events_per_sec": round(nfa["eps"], 1) if nfa else None,
        "batch": BATCH,
        # '_avg' in the metric name is the avg() aggregator in the query,
        # not run averaging; sections take the best of 2 runs (tunnel
        # stalls crater single windows — PERF.md cost model)
        "runs": "best_of_2",
    }))


if __name__ == "__main__":
    import sys

    if len(sys.argv) >= 3 and sys.argv[1] == "--section":
        section = sys.argv[2]
        if section == "device":
            print(json.dumps({"eps": bench_device()}))
        elif section == "e2e":
            print(json.dumps({"eps": bench_e2e()}))
        elif section == "nfa":
            p99, eps = bench_nfa_p99()
            print(json.dumps({"p99_ms": p99, "eps": eps}))
        else:
            raise SystemExit(f"unknown section {section}")
    else:
        main()
