"""Data-only query object model ("IR").

Mirrors the role of the reference's ``modules/siddhi-query-api`` (pure-data
AST consumed by the runtime parsers; reference ``SiddhiApp.java``,
``execution/query/Query.java``): the SiddhiQL compiler produces these
objects, and the planner lowers them into jitted step functions. Every class
is a plain dataclass so apps can also be built programmatically (the
reference exposes the same dual text/fluent-builder surface).
"""

from siddhi_tpu.query_api.annotations import Annotation
from siddhi_tpu.query_api.definitions import (
    Attribute,
    AttrType,
    StreamDefinition,
    TableDefinition,
    WindowDefinition,
    TriggerDefinition,
    AggregationDefinition,
    FunctionDefinition,
    TimePeriod,
)
from siddhi_tpu.query_api.expressions import (
    Expression,
    Constant,
    TimeConstant,
    Variable,
    Add,
    Subtract,
    Multiply,
    Divide,
    Mod,
    Compare,
    And,
    Or,
    Not,
    IsNull,
    InOp,
    AttributeFunction,
)
from siddhi_tpu.query_api.execution import (
    Query,
    OnDemandQuery,
    Partition,
    PartitionType,
    ValuePartitionType,
    RangePartitionType,
    SingleInputStream,
    JoinInputStream,
    StateInputStream,
    StreamHandler,
    Filter,
    Window,
    StreamFunction,
    StateElement,
    StreamStateElement,
    AbsentStreamStateElement,
    NextStateElement,
    EveryStateElement,
    CountStateElement,
    LogicalStateElement,
    Selector,
    OutputAttribute,
    OrderByAttribute,
    OutputStream,
    InsertIntoStream,
    DeleteStream,
    UpdateStream,
    UpdateOrInsertStream,
    UpdateSet,
    SetAttribute,
    ReturnStream,
    OutputRate,
    EventOutputRate,
    TimeOutputRate,
    SnapshotOutputRate,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp
