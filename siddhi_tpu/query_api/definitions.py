"""Definitions: streams, tables, windows, triggers, aggregations, functions.

Mirrors reference ``query-api definition/*.java`` (``StreamDefinition``,
``TableDefinition``, ``WindowDefinition``, ``AggregationDefinition``,
``TriggerDefinition``, ``FunctionDefinition``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional

from siddhi_tpu.query_api.annotations import Annotation


class AttrType(enum.Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"


@dataclass
class Attribute:
    name: str
    type: AttrType


@dataclass
class AbstractDefinition:
    id: str
    attributes: List[Attribute] = field(default_factory=list)
    annotations: List[Annotation] = field(default_factory=list)

    def attribute_names(self) -> List[str]:
        return [a.name for a in self.attributes]

    def attribute(self, name: str) -> Attribute:
        for a in self.attributes:
            if a.name == name:
                return a
        raise KeyError(f"attribute '{name}' not found in '{self.id}'")

    def attribute_position(self, name: str) -> int:
        for i, a in enumerate(self.attributes):
            if a.name == name:
                return i
        raise KeyError(f"attribute '{name}' not found in '{self.id}'")


@dataclass
class StreamDefinition(AbstractDefinition):
    pass


@dataclass
class TableDefinition(AbstractDefinition):
    pass


@dataclass
class WindowDefinition(AbstractDefinition):
    # The window handler, e.g. Window("", "time", [TimeConstant(...)]).
    window: object = None
    # OutputEventType: 'current', 'expired', 'all' (reference
    # WindowDefinition.java OutputEventType); default in Siddhi: ALL_EVENTS.
    output_event_type: str = "all"


@dataclass
class TriggerDefinition:
    id: str
    # Exactly one of: at_every (ms), cron expression, or 'start'.
    at_every: Optional[int] = None
    cron: Optional[str] = None
    at_start: bool = False
    annotations: List[Annotation] = field(default_factory=list)


class Duration(enum.Enum):
    SECONDS = "sec"
    MINUTES = "min"
    HOURS = "hour"
    DAYS = "day"
    MONTHS = "month"
    YEARS = "year"


@dataclass
class TimePeriod:
    """`aggregate every sec ... year` — range or interval of durations.

    Reference ``query-api aggregation/TimePeriod.java``.
    """

    operator: str = "range"  # 'range' or 'interval'
    durations: List[Duration] = field(default_factory=list)


@dataclass
class AggregationDefinition:
    """`define aggregation` — incremental time-series aggregation.

    Reference ``query-api definition/AggregationDefinition.java``.
    """

    id: str = ""
    input_stream: object = None  # SingleInputStream (usually)
    selector: object = None  # Selector
    aggregate_attribute: object = None  # Variable for `aggregate by <attr>`
    time_period: Optional[TimePeriod] = None
    annotations: List[Annotation] = field(default_factory=list)


@dataclass
class FunctionDefinition:
    """`define function name[lang] return type { body }`.

    Reference ``query-api definition/FunctionDefinition.java``.
    """

    id: str = ""
    language: str = ""
    return_type: Optional[AttrType] = None
    body: str = ""
