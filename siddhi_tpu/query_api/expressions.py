"""Expression AST.

Mirrors reference ``query-api expression/**`` (``Expression.java``,
``condition/{And,Or,Not,Compare,In,IsNull}.java``,
``math/{Add,Subtract,Multiply,Divide,Mod}.java``, ``constant/*.java``,
``Variable.java``, ``AttributeFunction.java``). Data-only: lowering to
numpy/jax lives in ``siddhi_tpu.ops.expressions``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from siddhi_tpu.query_api.definitions import AttrType


class Expression:
    pass


@dataclass
class Constant(Expression):
    value: object
    type: AttrType


@dataclass
class TimeConstant(Expression):
    """A `5 sec` / `1 min` literal, normalized to milliseconds (LONG)."""

    value: int  # milliseconds

    @property
    def type(self) -> AttrType:
        return AttrType.LONG


@dataclass
class Variable(Expression):
    attribute_name: str
    stream_id: Optional[str] = None
    # For pattern/sequence references like e1[0].price / e1[last].price.
    stream_index: Optional[object] = None  # int | 'last'
    function_id: Optional[str] = None  # aggregation ref inside `within`/`per`


@dataclass
class Add(Expression):
    left: Expression
    right: Expression


@dataclass
class Subtract(Expression):
    left: Expression
    right: Expression


@dataclass
class Multiply(Expression):
    left: Expression
    right: Expression


@dataclass
class Divide(Expression):
    left: Expression
    right: Expression


@dataclass
class Mod(Expression):
    left: Expression
    right: Expression


@dataclass
class Compare(Expression):
    left: Expression
    operator: str  # '<', '<=', '>', '>=', '==', '!='
    right: Expression


@dataclass
class And(Expression):
    left: Expression
    right: Expression


@dataclass
class Or(Expression):
    left: Expression
    right: Expression


@dataclass
class Not(Expression):
    expression: Expression


@dataclass
class IsNull(Expression):
    expression: Optional[Expression] = None
    # `e1 is null` for pattern stream-state null checks:
    stream_id: Optional[str] = None
    stream_index: Optional[object] = None


@dataclass
class InOp(Expression):
    expression: Expression
    source_id: str  # table/window to check membership in


@dataclass
class AttributeFunction(Expression):
    namespace: str
    name: str
    parameters: List[Expression] = field(default_factory=list)
