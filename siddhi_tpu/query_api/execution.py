"""Execution elements: queries, input streams, state (NFA) elements,
selectors, output streams, rate limiting, partitions.

Mirrors reference ``query-api execution/**`` (``query/Query.java``,
``query/input/stream/{Single,Join,State}InputStream.java``,
``query/input/state/*.java``, ``query/selection/Selector.java``,
``query/output/stream/*.java``, ``query/output/ratelimit/*.java``,
``partition/Partition.java``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional

from siddhi_tpu.query_api.annotations import Annotation
from siddhi_tpu.query_api.expressions import Expression, Variable


# ---------------------------------------------------------------- handlers

@dataclass
class StreamHandler:
    pass


@dataclass
class Filter(StreamHandler):
    expression: Expression


@dataclass
class Window(StreamHandler):
    namespace: str
    name: str
    parameters: List[Expression] = field(default_factory=list)


@dataclass
class StreamFunction(StreamHandler):
    namespace: str
    name: str
    parameters: List[Expression] = field(default_factory=list)


# ------------------------------------------------------------ input streams

@dataclass
class SingleInputStream:
    stream_id: str
    is_inner_stream: bool = False  # '#stream' inside partitions
    is_fault_stream: bool = False  # '!stream'
    stream_reference_id: Optional[str] = None  # `as e1` / pattern ref
    handlers: List[StreamHandler] = field(default_factory=list)

    @property
    def unique_stream_id(self) -> str:
        prefix = "#" if self.is_inner_stream else ("!" if self.is_fault_stream else "")
        return prefix + self.stream_id


class JoinType(enum.Enum):
    JOIN = "join"
    INNER_JOIN = "inner join"
    LEFT_OUTER_JOIN = "left outer join"
    RIGHT_OUTER_JOIN = "right outer join"
    FULL_OUTER_JOIN = "full outer join"


class EventTrigger(enum.Enum):
    LEFT = "left"
    RIGHT = "right"
    ALL = "all"


@dataclass
class JoinInputStream:
    left: SingleInputStream
    right: SingleInputStream
    type: JoinType = JoinType.JOIN
    on_compare: Optional[Expression] = None
    trigger: EventTrigger = EventTrigger.ALL
    within: Optional[Expression] = None  # join with aggregation
    per: Optional[Expression] = None


# -------------------------------------------------------- state (NFA) model

@dataclass
class StateElement:
    # `within <time>` scoped to this element
    within: Optional[int] = None  # milliseconds


@dataclass
class StreamStateElement(StateElement):
    stream: SingleInputStream = None


@dataclass
class AbsentStreamStateElement(StreamStateElement):
    # `not <stream> for <time>`
    waiting_time: Optional[int] = None  # milliseconds


@dataclass
class NextStateElement(StateElement):
    state: StateElement = None
    next: StateElement = None


@dataclass
class EveryStateElement(StateElement):
    state: StateElement = None


@dataclass
class CountStateElement(StateElement):
    ANY = -1
    state: StreamStateElement = None
    min_count: int = -1
    max_count: int = -1


@dataclass
class LogicalStateElement(StateElement):
    stream1: StreamStateElement = None
    type: str = "and"  # 'and' | 'or'
    stream2: StreamStateElement = None


class StateInputStreamType(enum.Enum):
    PATTERN = "pattern"
    SEQUENCE = "sequence"


@dataclass
class StateInputStream:
    state_type: StateInputStreamType
    state_element: StateElement = None
    within: Optional[int] = None  # milliseconds, whole-pattern `within`

    @property
    def all_stream_ids(self) -> List[str]:
        out: List[str] = []

        def walk(el):
            if isinstance(el, StreamStateElement):
                out.append(el.stream.stream_id)
            elif isinstance(el, NextStateElement):
                walk(el.state)
                walk(el.next)
            elif isinstance(el, EveryStateElement):
                walk(el.state)
            elif isinstance(el, CountStateElement):
                walk(el.state)
            elif isinstance(el, LogicalStateElement):
                walk(el.stream1)
                walk(el.stream2)

        walk(self.state_element)
        return out


# ----------------------------------------------------------------- selector

@dataclass
class OutputAttribute:
    rename: Optional[str]
    expression: Expression

    @property
    def name(self) -> str:
        if self.rename:
            return self.rename
        if isinstance(self.expression, Variable):
            return self.expression.attribute_name
        raise ValueError("projection expression needs an 'as' rename")


@dataclass
class OrderByAttribute:
    variable: Variable
    order: str = "asc"  # 'asc' | 'desc'


@dataclass
class Selector:
    selection_list: List[OutputAttribute] = field(default_factory=list)
    select_all: bool = False  # `select *` (or no select clause)
    group_by_list: List[Variable] = field(default_factory=list)
    having: Optional[Expression] = None
    order_by_list: List[OrderByAttribute] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


# ------------------------------------------------------------ output stream

@dataclass
class OutputStream:
    target_id: str = ""
    # Which event types flow to output: 'current', 'expired', 'all'
    # (reference OutputStream.OutputEventType).
    output_event_type: str = "current"


@dataclass
class InsertIntoStream(OutputStream):
    is_inner_stream: bool = False
    is_fault_stream: bool = False


@dataclass
class DeleteStream(OutputStream):
    on_delete: Expression = None


@dataclass
class SetAttribute:
    table_variable: Variable = None
    assignment: Expression = None


@dataclass
class UpdateSet:
    set_attributes: List[SetAttribute] = field(default_factory=list)


@dataclass
class UpdateStream(OutputStream):
    on_update: Expression = None
    update_set: Optional[UpdateSet] = None


@dataclass
class UpdateOrInsertStream(OutputStream):
    on_update: Expression = None
    update_set: Optional[UpdateSet] = None


@dataclass
class ReturnStream(OutputStream):
    """On-demand / store-query `return` output."""


# ------------------------------------------------------------- rate limits

@dataclass
class OutputRate:
    pass


@dataclass
class EventOutputRate(OutputRate):
    value: int = 1
    type: str = "all"  # 'all' | 'first' | 'last'


@dataclass
class TimeOutputRate(OutputRate):
    value: int = 1000  # milliseconds
    type: str = "all"


@dataclass
class SnapshotOutputRate(OutputRate):
    value: int = 1000  # milliseconds


# ----------------------------------------------------------------- queries

@dataclass
class Query:
    input_stream: object = None  # Single/Join/State InputStream
    selector: Selector = field(default_factory=Selector)
    output_stream: OutputStream = None
    output_rate: Optional[OutputRate] = None
    annotations: List[Annotation] = field(default_factory=list)

    @property
    def name(self) -> Optional[str]:
        for a in self.annotations:
            if a.name.lower() == "info":
                return a.element("name")
        return None


@dataclass
class OnDemandQuery:
    """Ad-hoc query against a table/window/aggregation (reference
    ``query-api execution/query/OnDemandQuery.java`` / StoreQuery)."""

    input_store: object = None  # InputStore
    selector: Selector = field(default_factory=Selector)
    output_stream: OutputStream = None
    type: str = "find"  # find | insert | delete | update | update_or_insert


@dataclass
class InputStore:
    store_id: str = ""
    store_reference_id: Optional[str] = None
    on_condition: Optional[Expression] = None
    within: Optional[Expression] = None
    per: Optional[Expression] = None


# --------------------------------------------------------------- partitions

@dataclass
class PartitionType:
    stream_id: str = ""


@dataclass
class ValuePartitionType(PartitionType):
    expression: Expression = None


@dataclass
class RangeCondition:
    partition_key: str = ""
    condition: Expression = None


@dataclass
class RangePartitionType(PartitionType):
    conditions: List[RangeCondition] = field(default_factory=list)


@dataclass
class Partition:
    partition_types: List[PartitionType] = field(default_factory=list)
    queries: List[Query] = field(default_factory=list)
    annotations: List[Annotation] = field(default_factory=list)
