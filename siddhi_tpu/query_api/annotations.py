"""Annotations attached to app/definitions/queries.

Mirrors reference ``query-api annotation/Annotation.java`` — a name plus
ordered key/value elements plus nested annotations (``@map`` inside
``@source`` etc.).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Annotation:
    name: str
    # Ordered (key, value) pairs; key may be None for positional elements.
    elements: List[tuple] = field(default_factory=list)
    annotations: List["Annotation"] = field(default_factory=list)

    def element(self, key: Optional[str] = None) -> Optional[str]:
        """Value for `key`; with key=None, the first positional value."""
        for k, v in self.elements:
            if k == key or (key is None and k is None):
                return v
        return None

    def elements_map(self) -> Dict[Optional[str], str]:
        return {k: v for k, v in self.elements}

    def annotation(self, name: str) -> Optional["Annotation"]:
        for a in self.annotations:
            if a.name.lower() == name.lower():
                return a
        return None


def find_annotation(annotations: List[Annotation], name: str) -> Optional[Annotation]:
    for a in annotations:
        if a.name.lower() == name.lower():
            return a
    return None


def find_annotations(annotations: List[Annotation], name: str) -> List[Annotation]:
    return [a for a in annotations if a.name.lower() == name.lower()]
