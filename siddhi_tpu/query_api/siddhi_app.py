"""Top-level SiddhiApp IR container.

Mirrors reference ``query-api SiddhiApp.java`` — holds all definitions and
execution elements in declaration order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from siddhi_tpu.query_api.annotations import Annotation
from siddhi_tpu.query_api.definitions import (
    AggregationDefinition,
    AttrType,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from siddhi_tpu.query_api.execution import Partition, Query


@dataclass
class SiddhiApp:
    annotations: List[Annotation] = field(default_factory=list)
    stream_definitions: Dict[str, StreamDefinition] = field(default_factory=dict)
    table_definitions: Dict[str, TableDefinition] = field(default_factory=dict)
    window_definitions: Dict[str, WindowDefinition] = field(default_factory=dict)
    trigger_definitions: Dict[str, TriggerDefinition] = field(default_factory=dict)
    aggregation_definitions: Dict[str, AggregationDefinition] = field(default_factory=dict)
    function_definitions: Dict[str, FunctionDefinition] = field(default_factory=dict)
    # Queries and partitions in declaration order.
    execution_elements: List[object] = field(default_factory=list)

    @property
    def name(self) -> Optional[str]:
        # `@app:name('X')` is stored as Annotation(name='app:name',
        # elements=[(None, 'X')]) (cf. reference SiddhiAppParser.java:91).
        for a in self.annotations:
            if a.name.lower() in ("app:name", "name"):
                return a.element(None) or a.element("name")
        return None

    def app_annotation(self, key: str) -> Optional[Annotation]:
        """Find `@app:<key>(...)` (e.g. playback, async, statistics)."""
        for a in self.annotations:
            if a.name.lower() == f"app:{key.lower()}":
                return a
        return None

    @property
    def queries(self) -> List[Query]:
        return [e for e in self.execution_elements if isinstance(e, Query)]

    @property
    def partitions(self) -> List[Partition]:
        return [e for e in self.execution_elements if isinstance(e, Partition)]

    def _check_duplicate(self, d, kind: str):
        """Same-id redefinitions must be attribute-identical; any same-id
        definition of a DIFFERENT kind conflicts (reference
        ``AbstractDefinition.checkEquivalency`` via SiddhiAppRuntimeBuilder's
        DuplicateDefinitionException paths)."""
        from siddhi_tpu.compiler.errors import DuplicateDefinitionException

        pools = {"stream": self.stream_definitions,
                 "table": self.table_definitions,
                 "window": self.window_definitions,
                 "trigger": self.trigger_definitions,
                 "aggregation": self.aggregation_definitions}
        for k, pool in pools.items():
            prev = pool.get(d.id)
            if prev is None:
                continue
            if k != kind:
                if {k, kind} == {"stream", "trigger"}:
                    # a trigger IS a `(triggered_time long)` stream — the id
                    # may collide with a stream of exactly that shape
                    # (TriggerTestCase testQuery3 vs testQuery4)
                    sdef = prev if k == "stream" else d
                    attrs = [(a.name, a.type)
                             for a in getattr(sdef, "attributes", [])]
                    if attrs == [("triggered_time", AttrType.LONG)]:
                        continue
                    raise DuplicateDefinitionException(
                        f"trigger '{d.id}' collides with a stream of a "
                        f"different attribute list")
                raise DuplicateDefinitionException(
                    f"'{d.id}' is already defined as a {k}")
            prev_attrs = [(a.name, a.type)
                          for a in getattr(prev, "attributes", [])]
            new_attrs = [(a.name, a.type)
                         for a in getattr(d, "attributes", [])]
            if prev_attrs != new_attrs:
                raise DuplicateDefinitionException(
                    f"{kind} '{d.id}' is already defined with a different "
                    f"attribute list")

    def define_stream(self, d: StreamDefinition) -> "SiddhiApp":
        self._check_duplicate(d, "stream")
        self.stream_definitions[d.id] = d
        return self

    def define_table(self, d: TableDefinition) -> "SiddhiApp":
        self._check_duplicate(d, "table")
        self.table_definitions[d.id] = d
        return self

    def define_window(self, d: WindowDefinition) -> "SiddhiApp":
        self._check_duplicate(d, "window")
        self.window_definitions[d.id] = d
        return self

    def define_trigger(self, d: TriggerDefinition) -> "SiddhiApp":
        self._check_duplicate(d, "trigger")
        self.trigger_definitions[d.id] = d
        return self

    def define_aggregation(self, d: AggregationDefinition) -> "SiddhiApp":
        self._check_duplicate(d, "aggregation")
        self.aggregation_definitions[d.id] = d
        return self

    def add_query(self, q: Query) -> "SiddhiApp":
        self.execution_elements.append(q)
        return self

    def add_partition(self, p: Partition) -> "SiddhiApp":
        self.execution_elements.append(p)
        return self
