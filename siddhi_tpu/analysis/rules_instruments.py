"""R6 — device-instrument parity.

The device telemetry plane (``observability/instruments.py``) is a
contract between three places: a step builder's ``instrument_slots()``
spec (``Slot(...)`` constructions), the drain consumers
(``_consume_check_slot`` implementations for structural slots, the
``device.<query>.<slot>`` exposition for data slots), and the
``DEVICE_SLOTS`` / ``DEVICE_CHECK_SLOTS`` declarations in
``observability/export.py`` that the exposition regexes are built from.
A slot computed on device but never declared would silently render as a
generic catch-all (or not at all); a check slot without a consumer
would ship lanes nobody verifies; a declared slot nobody computes is a
dead declaration. All of those are findings:

- a data ``Slot("name")`` whose name template matches no
  ``DEVICE_SLOTS`` entry;
- a ``Slot("name", kind="check")`` whose name appears in no
  ``_consume_check_slot`` implementation;
- a ``DEVICE_SLOTS`` entry no ``Slot(...)`` construction produces;
- a ``DEVICE_CHECK_SLOTS`` entry no ``Slot(..., kind="check")``
  construction produces.

F-string slot names normalize interpolations to ``*``
(``Slot(f"fill.{side}")`` matches ``fill.left``/``fill.right``), same
as R3's template discipline.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from siddhi_tpu.analysis.engine import Finding, LintContext, Rule


def _literal_template(node: ast.AST) -> Optional[str]:
    """Literal (or f-string, interpolations -> ``*``) string template."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _template_matches(template: str, name: str) -> bool:
    rx = re.escape(template).replace(r"\*", ".*")
    return bool(re.fullmatch(rx, name))


class InstrumentParityRule(Rule):
    id = "R6"
    title = "device-instrument parity"

    @staticmethod
    def _slot_calls(tree: ast.AST) -> List[Tuple[ast.Call, str, str]]:
        """(call, name_template, kind) of every ``Slot(...)``
        construction with a resolvable literal name."""
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = getattr(fn, "attr", getattr(fn, "id", None))
            if fname != "Slot":
                continue
            name_node = node.args[0] if node.args else None
            kind = "gauge"
            for kw in node.keywords:
                if kw.arg == "name" and name_node is None:
                    name_node = kw.value
                if (kw.arg == "kind" and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    kind = kw.value.value
            tpl = _literal_template(name_node) if name_node is not None \
                else None
            if tpl is not None:
                out.append((node, tpl, kind))
        return out

    @staticmethod
    def _check_consumer_literals(tree: ast.AST) -> List[str]:
        """String constants inside ``_consume_check_slot``
        implementations — the names a drain actually handles."""
        lits = []
        for node in ast.walk(tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == "_consume_check_slot"):
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, str)):
                        lits.append(sub.value)
        return lits

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        declared = tuple(getattr(ctx, "device_slots", ()) or ())
        declared_checks = tuple(
            getattr(ctx, "device_check_slots", ()) or ())
        slot_calls: List[Tuple[str, int, str, str]] = []
        consumers: List[str] = []
        for mod in ctx.modules:
            if mod.path.startswith("tests/"):
                continue
            consumers.extend(self._check_consumer_literals(mod.tree))
            for call, tpl, kind in self._slot_calls(mod.tree):
                slot_calls.append((mod.path, call.lineno, tpl, kind))
        if not slot_calls and not declared:
            return findings    # tree without the instrument plane
        for path, line, tpl, kind in slot_calls:
            if kind == "check":
                if declared_checks and not any(
                        _template_matches(tpl, c) or tpl == c
                        for c in declared_checks):
                    findings.append(Finding(
                        self.id, path, line,
                        f"check slot '{tpl}' is not declared in "
                        f"DEVICE_CHECK_SLOTS (observability/export.py)"))
                if not any(_template_matches(tpl, c) or c == tpl
                           for c in consumers):
                    findings.append(Finding(
                        self.id, path, line,
                        f"check slot '{tpl}' has no drain consumer — no "
                        f"_consume_check_slot implementation handles it"))
            else:
                if declared and not any(
                        _template_matches(tpl, d) for d in declared):
                    findings.append(Finding(
                        self.id, path, line,
                        f"instrument slot '{tpl}' matches no DEVICE_SLOTS "
                        f"entry in observability/export.py — its "
                        f"device.* telemetry would render as an "
                        f"undeclared catch-all"))
        # dead declarations: a declared slot nobody computes
        exp = ctx.module(ctx.export_path) or ctx.module("export.py")
        exp_path = exp.path if exp is not None else "export.py"
        data_tpls = [t for _p, _l, t, k in slot_calls if k != "check"]
        check_tpls = [t for _p, _l, t, k in slot_calls if k == "check"]
        for d in declared:
            if not any(_template_matches(t, d) for t in data_tpls):
                findings.append(Finding(
                    self.id, exp_path, 1,
                    f"DEVICE_SLOTS declares '{d}' but no Slot(...) "
                    f"construction produces it — remove the dead "
                    f"declaration"))
        for c in declared_checks:
            if not any(_template_matches(t, c) or t == c
                       for t in check_tpls):
                findings.append(Finding(
                    self.id, exp_path, 1,
                    f"DEVICE_CHECK_SLOTS declares '{c}' but no "
                    f"Slot(..., kind='check') construction produces it"))
        return findings
