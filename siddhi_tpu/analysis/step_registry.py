"""Declarative registry of every jitted step BUILDER in the engine.

``tools/hlo_audit.py`` used to audit a hand-kept list of step kinds;
a new builder (the device join engine, the sharded-agg selector) only
got audited when somebody remembered. This registry is the contract:
every entry here names a production code path that compiles a step
with ``jax.jit``, and hlo_audit asserts its decorated audit set covers
ALL of them — adding a builder without an audit fails the quick tier
by construction.

Entries are (dotted module path, attribute) so the registry is
importable without jax and verifiable by a plain resolve.
"""

from __future__ import annotations

import importlib
from typing import Dict, Tuple

# audit name -> (module, attr) of the builder that jits the step
JIT_STEP_BUILDERS: Dict[str, Tuple[str, str]] = {
    # per-query single-stream step (QueryRuntime._make_step -> jax.jit)
    "query_step": ("siddhi_tpu.core.query.runtime", "QueryRuntime"),
    # fused sibling queries: one jitted step per junction group
    "fused_fanout": ("siddhi_tpu.core.query.fused_fanout",
                     "FusedFanoutRuntime"),
    # GSPMD keyed sharding (round-4) + host-routed shard_map (round-5)
    "gspmd_replicated_batch": ("siddhi_tpu.parallel.mesh",
                               "shard_query_step"),
    "shard_map_routed": ("siddhi_tpu.parallel.mesh",
                         "shard_keyed_query_step"),
    # device-side repartitioning (round-6): routing inside the step
    "device_routed": ("siddhi_tpu.parallel.mesh",
                      "device_route_query_step"),
    # device join engine: fused insert+probe side step
    "device_join": ("siddhi_tpu.core.join.engine", "DeviceJoinEngine"),
    # serving tier: sharded incremental aggregation's on-demand
    # selector steps over per-shard device views
    "sharded_agg": ("siddhi_tpu.serving.sharded_aggregation",
                    "ShardedIncrementalAggregation"),
}


# Builders whose steps carry a device-instrument meta suffix
# (observability/instruments.py): their hlo_audit functions must ALSO
# assert the packed meta matches the runtime's declared
# instrument_slots() spec — one module, zero extra transfers, lanes
# accounted for. A builder gaining a suffix without joining this tuple
# (or vice versa) fails the audit's coverage check.
INSTRUMENTED_STEP_BUILDERS = (
    "query_step",      # win_fill / groups lanes
    "device_routed",   # route slots + aggregated inner lanes
    "device_join",     # seq + per-partition fill lanes
)


# Program-cache participation (core/util/program_cache.py, round 15):
# audit name -> the ``family=`` tag(s) its builder passes to
# ``instrument_jit``. The tag is part of the cache key — wrapper
# shardings (``in_shardings=...``) are invisible in the traced jaxpr,
# so two builders jitting the same function under different shardings
# must never alias; tests/test_program_cache.py asserts each declared
# tag still appears at a call site in the named module (a builder
# gaining/renaming a tag without updating this inventory fails there).
# ``sharded_agg`` is absent by design: its on-demand selectors fold
# host-side — there is no production jit to cache (hlo_audit builds
# its probe program ad hoc).
PROGRAM_CACHE_FAMILIES: Dict[str, Tuple[str, ...]] = {
    "query_step": ("query_step", "selector"),
    "fused_fanout": ("fused_fanout",),
    "gspmd_replicated_batch": ("gspmd_replicated_batch",),
    "shard_map_routed": ("shard_map_routed",),
    "device_routed": ("device_routed",),
    # NFA steps ride QueryRuntime's module (pattern/sequence queries)
    "nfa_step": ("nfa_step", "nfa_timer"),
    # join sides tag per side at the call site: device_join.left/right
    "device_join": ("device_join",),
}

# family tags above that are PREFIXES of the call-site tag (the call
# site appends a dynamic suffix, e.g. ``device_join.left``)
PROGRAM_CACHE_PREFIX_FAMILIES = ("device_join", "device_routed")

# module that carries each family's instrument_jit call site (may
# differ from the builder's own module — NFA steps live in
# core/query/nfa_runtime, join sides in core/query/join_runtime)
PROGRAM_CACHE_FAMILY_SITES: Dict[str, str] = {
    "query_step": "siddhi_tpu.core.query.runtime",
    "selector": "siddhi_tpu.core.query.runtime",
    "fused_fanout": "siddhi_tpu.core.query.fused_fanout",
    "gspmd_replicated_batch": "siddhi_tpu.parallel.mesh",
    "shard_map_routed": "siddhi_tpu.parallel.mesh",
    "device_routed": "siddhi_tpu.parallel.mesh",
    "nfa_step": "siddhi_tpu.core.query.nfa_runtime",
    "nfa_timer": "siddhi_tpu.core.query.nfa_runtime",
    "device_join": "siddhi_tpu.core.query.join_runtime",
}


def resolve(name: str):
    """Import and return the registered builder (audit-time sanity:
    a renamed/moved builder fails loudly, not silently unaudited)."""
    module, attr = JIT_STEP_BUILDERS[name]
    return getattr(importlib.import_module(module), attr)
