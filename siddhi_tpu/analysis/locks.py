"""Ranked lock factory — the runtime half of the R4 lock-order rule.

Production code creates its ordered locks through ``make_lock(rank)``
instead of ``threading.RLock()``. With sanitizers off (the default)
this returns a plain ``threading.RLock`` — zero overhead, zero behavior
change. Under ``SIDDHI_TPU_SANITIZE=1`` it returns a ``CheckedRLock``
that tracks per-thread held ranks and raises ``LockOrderError`` the
moment an acquisition inverts the partial order declared in
``analysis/lockorder.py`` — turning a would-be deadlock that needs two
racing threads to reproduce into a deterministic single-thread failure.
"""

from __future__ import annotations

import threading

from siddhi_tpu.analysis import lockorder


class LockOrderError(RuntimeError):
    """A lock acquisition inverted the declared partial order."""


_TLS = threading.local()


def _held():
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def held_ranks() -> frozenset:
    """Ranks of every ``CheckedRLock`` the CALLING thread currently
    holds — the guarded-by sanitizer's (``analysis/guards.py``) oracle.
    Empty when sanitize is off (plain RLocks leave no trace)."""
    return frozenset(rank for rank, _ in _held())


class CheckedRLock:
    """Re-entrant lock that asserts the declared acquisition order.

    Same-rank nesting is allowed (owner locks chain down emit cascades);
    re-entry on the SAME lock object is always allowed (RLock
    semantics). Only cross-rank inversions raise."""

    __slots__ = ("_lock", "rank")

    def __init__(self, rank: str):
        if rank not in lockorder.RANKS:
            raise ValueError(f"undeclared lock rank '{rank}' — add it to "
                             "analysis/lockorder.py RANKS")
        self._lock = threading.RLock()
        self.rank = rank

    def _check(self) -> None:
        stack = _held()
        for held_rank, held_id in stack:
            if held_id == id(self):
                return      # re-entrant on the same lock: always fine
            if lockorder.inversion(held_rank, self.rank):
                raise LockOrderError(
                    f"lock-order inversion: acquiring '{self.rank}' "
                    f"({lockorder.RANKS[self.rank]}) while holding "
                    f"'{held_rank}' ({lockorder.RANKS[held_rank]}) — "
                    f"declared order requires '{self.rank}' before "
                    f"'{held_rank}' (analysis/lockorder.py)")

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._check()
        got = self._lock.acquire(blocking, timeout)
        if got:
            _held().append((self.rank, id(self)))
        return got

    def release(self) -> None:
        self._lock.release()
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == id(self):
                del stack[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # --- threading.Condition protocol (OrderedEgress wraps its ranked
    # lock in a Condition). wait() fully releases the inner RLock via
    # _release_save and reacquires via _acquire_restore; the rank stays
    # on the held stack across the wait on purpose — the waiting thread
    # acquires nothing while blocked, and the predicate runs with the
    # lock (logically and physically) held.

    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        return self._lock._release_save()

    def _acquire_restore(self, state):
        self._lock._acquire_restore(state)


def make_lock(rank: str):
    """A ranked re-entrant lock: plain ``threading.RLock`` normally, a
    ``CheckedRLock`` under ``SIDDHI_TPU_SANITIZE=1``."""
    from siddhi_tpu.analysis import sanitize

    if sanitize.enabled():
        return CheckedRLock(rank)
    return threading.RLock()
