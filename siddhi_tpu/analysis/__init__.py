"""Static analysis + runtime sanitizers for the engine's own bug classes.

- ``engine``/``rules_*`` — graftlint: an AST lint suite distilled from
  the repo's regression history (R1 import-time backend init, R2 ad-hoc
  config-knob reads, R3 metric-registration parity, R4 lock order, R5
  host pulls in step code, R6 instrument parity, R7 actuator parity,
  R8 guarded-by lock coverage). Driver: ``tools/graftlint.py``.
- ``lockorder`` — the declared lock partial order (shared by R4 and the
  runtime shim).
- ``locks`` — ``make_lock(rank)`` factory; plain RLock normally,
  order-asserting ``CheckedRLock`` under ``SIDDHI_TPU_SANITIZE=1``.
- ``guards`` — ``GUARDED_BY`` lock-coverage contracts (the runtime
  half of R8): descriptor-asserted field access under sanitize, plain
  attributes off.
- ``sanitize`` — the ``SIDDHI_TPU_SANITIZE=1`` runtime detectors
  (transfer guard + portable pull guard, post-warmup recompile
  watchdog, lock-order + lock-coverage assertions).
- ``step_registry`` — declarative list of every jitted step builder;
  ``tools/hlo_audit.py`` asserts audit coverage against it.
"""

from siddhi_tpu.analysis.engine import (  # noqa: F401
    Finding,
    LintContext,
    ModuleInfo,
    Rule,
    default_rules,
    load_modules,
    run_lint,
)
from siddhi_tpu.analysis.guards import guarded  # noqa: F401
from siddhi_tpu.analysis.locks import make_lock  # noqa: F401
