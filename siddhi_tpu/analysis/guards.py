"""Guarded-by field contracts — the runtime half of graftlint R8.

Lock *ordering* became data in PR-10 (``lockorder.py``); this module
does the same for lock *coverage*: which fields a lock actually
protects. A threaded class declares the contract next to its state:

    class CompletionPump:
        GUARDED_BY = {"_pending": "pump"}

        def __init__(self):
            self._lock = make_lock("pump")
            self._pending = {}
    guarded(CompletionPump)          # or @guarded above the class

Ranks come from ``lockorder.RANKS``. Two enforcement layers consume the
declaration:

- the static rule ``analysis/rules_guards.py`` (graftlint R8) flags any
  ``self._field`` read/write in the declaring class that is not
  lexically inside a ``with`` on a lock of the declared rank, at review
  time;
- under ``SIDDHI_TPU_SANITIZE=1`` this module installs a data
  descriptor per declared field that asserts on EVERY access — from any
  module, any thread — that the calling thread holds a lock of the
  guarding rank (``analysis/locks.py`` per-thread holdings), raising
  ``GuardViolation`` otherwise.

With sanitize off (the default) ``guarded()`` validates the rank names
and returns the class untouched: declared fields stay plain instance
attributes — zero descriptors, zero indirection, zero cost (the
``tools/obs_overhead.py`` bar covers this).

``__init__`` is exempt: construction happens before the instance is
shared, so the constructor populates fields without the lock (the same
reasoning the static rule applies).

Fields deliberately left OUT of ``GUARDED_BY`` (single-writer beat
counters read by gauge lambdas, lock-free fast-path probes) are simply
not contracts — both layers ignore them.
"""

from __future__ import annotations

from siddhi_tpu.analysis import lockorder


class GuardViolation(RuntimeError):
    """A guarded field was accessed without its declared lock held."""


_CONSTRUCTING = "_guard_constructing"


class _GuardedField:
    """Data descriptor enforcing one ``GUARDED_BY`` entry. The value
    lives in the instance ``__dict__`` under a mangled slot key (a data
    descriptor always wins over a same-named instance attribute, so the
    check cannot be bypassed by plain assignment)."""

    __slots__ = ("name", "rank", "cls_name", "slot")

    def __init__(self, name: str, rank: str, cls_name: str):
        self.name = name
        self.rank = rank
        self.cls_name = cls_name
        self.slot = f"_guarded__{name}"

    def _check(self, obj, op: str) -> None:
        from siddhi_tpu.analysis.locks import held_ranks

        if obj.__dict__.get(_CONSTRUCTING, False):
            return      # constructor: the instance is not shared yet
        if self.rank in held_ranks():
            return
        raise GuardViolation(
            f"sanitizer: {op} of {self.cls_name}.{self.name} without "
            f"holding a '{self.rank}'-ranked lock "
            f"({lockorder.RANKS.get(self.rank, '?')}) — the class "
            f"declares GUARDED_BY[{self.name!r}] = {self.rank!r}; "
            f"acquire the lock or amend the contract")

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj, "unlocked read")
        try:
            return obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(
                f"{self.cls_name!r} object has no attribute "
                f"{self.name!r}") from None

    def __set__(self, obj, value):
        self._check(obj, "unlocked write")
        obj.__dict__[self.slot] = value

    def __delete__(self, obj):
        self._check(obj, "unlocked delete")
        try:
            del obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(
                f"{self.cls_name!r} object has no attribute "
                f"{self.name!r}") from None


def _wrap_init(cls) -> None:
    import functools

    orig = cls.__init__

    @functools.wraps(orig)
    def __init__(self, *args, **kwargs):
        self.__dict__[_CONSTRUCTING] = True
        try:
            orig(self, *args, **kwargs)
        finally:
            self.__dict__.pop(_CONSTRUCTING, None)

    cls.__init__ = __init__


def guarded(cls):
    """Class decorator (or plain call) activating the class's
    ``GUARDED_BY`` declaration. Always validates the declared ranks;
    installs the checking descriptors only when ``SIDDHI_TPU_SANITIZE=1``
    was set at class-definition time (same construction-time gate as
    ``make_lock``)."""
    from siddhi_tpu.analysis import sanitize

    declared = cls.__dict__.get("GUARDED_BY", None)
    if declared is None:
        raise ValueError(
            f"@guarded class {cls.__name__} has no GUARDED_BY "
            f"declaration of its own")
    for name, rank in declared.items():
        if rank not in lockorder.RANKS:
            raise ValueError(
                f"{cls.__name__}.GUARDED_BY[{name!r}] names undeclared "
                f"lock rank {rank!r} — add it to analysis/lockorder.py "
                f"RANKS")
    if not sanitize.enabled() or not declared:
        return cls
    for name, rank in declared.items():
        setattr(cls, name, _GuardedField(name, rank, cls.__name__))
    _wrap_init(cls)
    return cls
