"""R4 — lock-order discipline.

The hot-path acquisition orders (owner -> pump, shard -> WAL-record,
everything under the app barrier) used to be enforced by prose in
docstrings; ``analysis/lockorder.py`` now declares them as a partial
order, the runtime shim (``analysis/locks.py``) asserts them under
``SIDDHI_TPU_SANITIZE=1``, and this rule flags LEXICALLY nested
acquisitions that invert them at review time.

Rank resolution (static side):

1. a first pass learns ``(class, attr) -> rank`` from every
   ``self.<attr> = make_lock("<rank>")`` assignment in the tree;
2. ``with self.<attr>:`` resolves through the enclosing class;
3. ``with <var>._lock:`` (or a single-assignment alias of it, incl.
   ``getattr(<var>, "_lock", ...)``) resolves through
   ``lockorder.VARIABLE_RANKS`` on the variable name — ``owner._lock``
   is an owner lock wherever it appears;
4. ``self._barrier`` / ``<var>._barrier`` is always the barrier.

Unranked locks are invisible to the rule. Acquiring rank B inside rank
A is a finding when the declared closure says B must precede A.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from siddhi_tpu.analysis import lockorder
from siddhi_tpu.analysis.engine import Finding, LintContext, Rule


def _rank_of_expr(node: ast.AST, class_ranks: Dict[Tuple[str, str], str],
                  cls_name: Optional[str],
                  aliases: Dict[str, str]) -> Optional[str]:
    """Resolve a with-item expression to a declared rank, or None."""
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    if not isinstance(node, ast.Attribute):
        return None
    if node.attr in lockorder.BARRIER_ATTRS:
        return "barrier"
    if isinstance(node.value, ast.Name):
        base = node.value.id
        if base == "self" and cls_name is not None:
            rank = class_ranks.get((cls_name, node.attr))
            if rank is not None:
                return rank
        if node.attr == "_lock":
            return lockorder.VARIABLE_RANKS.get(base)
    return None


def _alias_rank(value: ast.AST, class_ranks, cls_name, aliases):
    """Rank of an assignment's RHS: a direct lock expr or
    ``getattr(<var>, "_lock", ...)``."""
    rank = _rank_of_expr(value, class_ranks, cls_name, aliases)
    if rank is not None:
        return rank
    if (isinstance(value, ast.Call) and isinstance(value.func, ast.Name)
            and value.func.id == "getattr" and len(value.args) >= 2):
        tgt, attr = value.args[0], value.args[1]
        if (isinstance(attr, ast.Constant) and attr.value == "_lock"
                and isinstance(tgt, ast.Name)):
            return lockorder.VARIABLE_RANKS.get(tgt.id)
    return None


class LockOrderRule(Rule):
    id = "R4"
    title = "lock-order discipline"

    def run(self, ctx: LintContext) -> List[Finding]:
        class_ranks: Dict[Tuple[str, str], str] = {}
        for mod in ctx.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)
                            and isinstance(sub.value.func, ast.Name)
                            and sub.value.func.id == "make_lock"
                            and sub.value.args
                            and isinstance(sub.value.args[0], ast.Constant)):
                        for tgt in sub.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                class_ranks[(node.name, tgt.attr)] = \
                                    sub.value.args[0].value

        findings: List[Finding] = []
        for mod in ctx.modules:
            if mod.path.startswith("tests/"):
                continue
            self._scan(mod, mod.tree, None, class_ranks, findings)
        return findings

    def _scan(self, mod, tree, cls_name, class_ranks, findings) -> None:
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.ClassDef):
                self._scan(mod, node, node.name, class_ranks, findings)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_func(mod, node, cls_name, class_ranks, findings)
            else:
                self._scan(mod, node, cls_name, class_ranks, findings)

    def _scan_func(self, mod, func, cls_name, class_ranks, findings):
        aliases: Dict[str, str] = dict(lockorder.VARIABLE_RANKS)

        def walk(body, held: List[Tuple[str, int]]):
            for st in body:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # nested defs run later, under unknown held-locks
                    self._scan_func(mod, st, cls_name, class_ranks,
                                    findings)
                    continue
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name):
                    rank = _alias_rank(st.value, class_ranks, cls_name,
                                       aliases)
                    if rank is not None:
                        aliases[st.targets[0].id] = rank
                if isinstance(st, ast.With):
                    acquired = []
                    for item in st.items:
                        rank = _rank_of_expr(item.context_expr,
                                             class_ranks, cls_name,
                                             aliases)
                        if rank is None:
                            continue
                        for held_rank, held_line in held:
                            if lockorder.inversion(held_rank, rank):
                                findings.append(Finding(
                                    self.id, mod.path, st.lineno,
                                    f"acquiring '{rank}' lock while "
                                    f"holding '{held_rank}' (line "
                                    f"{held_line}) inverts the declared "
                                    f"order '{rank}' -> '{held_rank}' "
                                    f"(analysis/lockorder.py)"))
                        acquired.append((rank, st.lineno))
                    walk(st.body, held + acquired)
                    continue
                # descend into compound-statement bodies (if/for/while/
                # try/except) statement-by-statement, keeping the held
                # stack — alias assignments inside them are learned too
                for sub in ast.iter_child_nodes(st):
                    if isinstance(sub, ast.ExceptHandler):
                        walk(sub.body, held)
                    elif isinstance(sub, ast.stmt):
                        walk([sub], held)
        walk(func.body, [])
