"""graftlint engine: rule registry, module loader, suppressions.

An AST-based lint harness distilled from this repo's own regression
history (see ``tools/graftlint.py`` for the driver and the per-rule
modules ``rules_*.py`` for the checks). Design:

- **ModuleInfo** — parsed source + per-line suppression table. A line
  containing ``# graftlint: disable=R1`` (comma-separated ids, or
  ``all``) suppresses findings on that line; ``# graftlint:
  disable-file=R3`` anywhere in the file suppresses the whole file for
  that rule. Suppressions are deliberate, reviewable escape hatches —
  prefer fixing the finding.
- **Rule** — ``id``/``title`` plus ``run(ctx)`` over ALL modules (rules
  that learn facts in one file and check another — lock ranks, metric
  declarations — need the whole tree).
- **LintContext** — the loaded modules plus declarations parsed from
  ``observability/export.py`` and ``analysis/lockorder.py``; tests
  override it to point rules at fixture trees.

The engine itself never imports jax — graftlint must run anywhere,
instantly, with no backend in sight (that being rather the point of R1).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

_SUPPRESS = re.compile(r"#\s*graftlint:\s*disable(?P<scope>-file)?="
                       r"(?P<ids>[A-Za-z0-9_,\s]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


@dataclass
class ModuleInfo:
    path: str           # repo-relative, forward slashes
    src: str
    tree: ast.AST
    line_suppress: Dict[int, set] = field(default_factory=dict)
    file_suppress: set = field(default_factory=set)

    @classmethod
    def load(cls, path: str, rel: str) -> "ModuleInfo":
        with open(path, encoding="utf-8") as f:
            src = f.read()
        mod = cls(path=rel.replace(os.sep, "/"), src=src,
                  tree=ast.parse(src, filename=rel))
        for lineno, line in enumerate(src.splitlines(), 1):
            m = _SUPPRESS.search(line)
            if not m:
                continue
            ids = {s.strip() for s in m.group("ids").split(",") if s.strip()}
            if m.group("scope"):
                mod.file_suppress |= ids
            else:
                mod.line_suppress.setdefault(lineno, set()).update(ids)
        return mod

    def suppressed(self, rule_id: str, line: int) -> bool:
        if {"all", rule_id} & self.file_suppress:
            return True
        ids = self.line_suppress.get(line)
        return bool(ids and {"all", rule_id} & ids)


class Rule:
    id: str = "R?"
    title: str = ""

    def run(self, ctx: "LintContext") -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclass
class LintContext:
    modules: List[ModuleInfo]
    # R3/R6 declarations parsed out of observability/export.py
    # (overridable by fixture tests)
    telemetry_prefixes: Sequence[str] = ()
    unremoved_gauge_allow: Sequence[str] = ()
    device_slots: Sequence[str] = ()
    device_check_slots: Sequence[str] = ()
    export_path: str = "siddhi_tpu/observability/export.py"

    def module(self, suffix: str) -> Optional[ModuleInfo]:
        for m in self.modules:
            if m.path.endswith(suffix):
                return m
        return None


def iter_py_files(roots: Sequence[str], base: str) -> List[str]:
    out = []
    for root in roots:
        full = os.path.join(base, root)
        if os.path.isfile(full):
            out.append(full)
            continue
        for d, dirs, files in os.walk(full):
            dirs[:] = [x for x in dirs if x != "__pycache__"]
            out.extend(os.path.join(d, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(set(out))


def load_modules(roots: Sequence[str], base: str) -> List[ModuleInfo]:
    mods = []
    for path in iter_py_files(roots, base):
        rel = os.path.relpath(path, base)
        mods.append(ModuleInfo.load(path, rel))
    return mods


def _parse_export_declarations(ctx: LintContext) -> None:
    """Pull the R3 declaration tuples out of export.py's AST (the
    declarations live WITH the exposition code so they cannot drift
    from it in a separate config file)."""
    exp = ctx.module(ctx.export_path) or ctx.module("export.py")
    if exp is None:
        return
    for node in ast.walk(exp.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if tgt.id in ("TELEMETRY_PREFIXES", "PROCESS_LIFETIME_GAUGES",
                      "DEVICE_SLOTS", "DEVICE_CHECK_SLOTS"):
            try:
                val = tuple(ast.literal_eval(node.value))
            except (ValueError, SyntaxError):
                continue
            if tgt.id == "TELEMETRY_PREFIXES":
                ctx.telemetry_prefixes = val
            elif tgt.id == "PROCESS_LIFETIME_GAUGES":
                ctx.unremoved_gauge_allow = val
            elif tgt.id == "DEVICE_SLOTS":
                ctx.device_slots = val
            else:
                ctx.device_check_slots = val


def default_rules() -> List[Rule]:
    from siddhi_tpu.analysis.rules_actuators import ActuatorParityRule
    from siddhi_tpu.analysis.rules_backend import BackendInitRule
    from siddhi_tpu.analysis.rules_config import ConfigKnobRule
    from siddhi_tpu.analysis.rules_guards import GuardedByRule
    from siddhi_tpu.analysis.rules_hotpath import HostPullRule
    from siddhi_tpu.analysis.rules_instruments import InstrumentParityRule
    from siddhi_tpu.analysis.rules_locks import LockOrderRule
    from siddhi_tpu.analysis.rules_metrics import MetricParityRule

    return [BackendInitRule(), ConfigKnobRule(), MetricParityRule(),
            LockOrderRule(), HostPullRule(), InstrumentParityRule(),
            ActuatorParityRule(), GuardedByRule()]


def run_lint(modules: List[ModuleInfo],
             rules: Optional[Sequence[Rule]] = None,
             ctx: Optional[LintContext] = None) -> List[Finding]:
    if ctx is None:
        ctx = LintContext(modules=modules)
    else:
        ctx.modules = modules
    if not ctx.telemetry_prefixes:
        _parse_export_declarations(ctx)
    findings: List[Finding] = []
    by_path = {m.path: m for m in modules}
    for rule in (rules if rules is not None else default_rules()):
        for f in rule.run(ctx):
            mod = by_path.get(f.path)
            if mod is not None and mod.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
