"""R7 — actuator parity (autopilot control surface).

The autopilot's control surface is a contract between three places: the
typed knob registry (``core/util/knobs.py`` ``Knob(...)``
declarations), the actuator table (``siddhi_tpu/autopilot/actuators.py``
``Actuator(...)`` constructions), and the policy rules that reference
actuators by name (``siddhi_tpu/autopilot/policy.py``
``PolicyRule(...)`` constructions). An actuator driving an undeclared
knob would bypass the R2 discipline (one sanctioned ``read_knob`` site,
parseable config surface); a policy rule naming an actuator nobody
declares is an actuation path that silently never fires; an actuator no
rule references is dead control surface the operator reads about in
``GET /autopilot`` but the policy can never exercise. All three are
findings, bidirectional like R3 (metric prefixes) and R6 (instrument
slots):

- an ``Actuator(...)`` whose ``knob=`` names no ``Knob(...)`` key in
  ``core/util/knobs.py``;
- a ``PolicyRule(...)`` whose ``actuator=`` matches no declared
  ``Actuator(...)`` name (undeclared actuation path);
- an ``Actuator(...)`` referenced by no ``PolicyRule(...)`` (dead
  declaration).

The rule is silent on trees with neither construction (graftlint must
run on foreign trees), and skips ``tests/`` like R6 — fixtures and unit
tests construct throwaway actuators on purpose.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from siddhi_tpu.analysis.engine import Finding, LintContext, Rule

KNOBS_PATH_SUFFIX = "core/util/knobs.py"


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_named(node: ast.AST, name: str) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    return getattr(fn, "attr", getattr(fn, "id", None)) == name


class ActuatorParityRule(Rule):
    id = "R7"
    title = "actuator parity"

    @staticmethod
    def _knob_keys(tree: ast.AST) -> Set[str]:
        """First-arg literals of every ``Knob(...)`` construction — the
        typed knob registry's declared key set."""
        keys: Set[str] = set()
        for node in ast.walk(tree):
            if _call_named(node, "Knob") and node.args:
                key = _literal_str(node.args[0])
                if key is not None:
                    keys.add(key)
        return keys

    @staticmethod
    def _actuator_calls(tree: ast.AST) -> List[
            Tuple[int, Optional[str], Optional[str]]]:
        """(line, name, knob) of every ``Actuator(...)`` construction
        with resolvable literal kwargs (positional first arg = name)."""
        out = []
        for node in ast.walk(tree):
            if not _call_named(node, "Actuator"):
                continue
            name = _literal_str(node.args[0]) if node.args else None
            knob = None
            for kw in node.keywords:
                if kw.arg == "name" and name is None:
                    name = _literal_str(kw.value)
                elif kw.arg == "knob":
                    knob = _literal_str(kw.value)
            out.append((node.lineno, name, knob))
        return out

    @staticmethod
    def _rule_calls(tree: ast.AST) -> List[Tuple[int, Optional[str]]]:
        """(line, actuator) of every ``PolicyRule(...)`` construction
        (second positional arg = actuator)."""
        out = []
        for node in ast.walk(tree):
            if not _call_named(node, "PolicyRule"):
                continue
            actuator = (_literal_str(node.args[1])
                        if len(node.args) >= 2 else None)
            for kw in node.keywords:
                if kw.arg == "actuator" and actuator is None:
                    actuator = _literal_str(kw.value)
            out.append((node.lineno, actuator))
        return out

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        knob_keys: Set[str] = set()
        actuators: List[Tuple[str, int, Optional[str], Optional[str]]] = []
        rules: List[Tuple[str, int, Optional[str]]] = []
        for mod in ctx.modules:
            if mod.path.startswith("tests/"):
                continue
            if mod.path.endswith(KNOBS_PATH_SUFFIX):
                knob_keys |= self._knob_keys(mod.tree)
            for line, name, knob in self._actuator_calls(mod.tree):
                actuators.append((mod.path, line, name, knob))
            for line, actuator in self._rule_calls(mod.tree):
                rules.append((mod.path, line, actuator))
        if not actuators and not rules:
            return findings    # tree without an autopilot plane
        declared = {name for _p, _l, name, _k in actuators
                    if name is not None}
        referenced = {a for _p, _l, a in rules if a is not None}
        for path, line, name, knob in actuators:
            if knob is not None and knob not in knob_keys:
                findings.append(Finding(
                    self.id, path, line,
                    f"actuator '{name}' drives knob '{knob}' which is "
                    f"not a Knob(...) declaration in "
                    f"{KNOBS_PATH_SUFFIX} — actuation must ride the "
                    f"typed knob registry"))
            if name is not None and name not in referenced:
                findings.append(Finding(
                    self.id, path, line,
                    f"actuator '{name}' is referenced by no "
                    f"PolicyRule(...) — dead control surface the "
                    f"policy can never exercise"))
        for path, line, actuator in rules:
            if actuator is not None and actuator not in declared:
                findings.append(Finding(
                    self.id, path, line,
                    f"policy rule references actuator '{actuator}' "
                    f"which no Actuator(...) construction declares — "
                    f"an actuation path that silently never fires"))
        return findings
