"""R5 — no host pull in hot-path step code.

Inside a jit-compiled step, a ``float()``/``int()``/``bool()``/
``.item()``/``np.asarray()`` on a traced value either fails at trace
time or — worse, on concrete leaves that escaped tracing — forces a
synchronous device->host transfer per batch, the exact per-pull tunnel
round trip the CompletionPump exists to amortize. The rule scans
``core/query``, ``core/join`` and ``parallel`` for functions that are
jit-compiled (decorated with ``jax.jit``/``partial(jax.jit, ...)``,
passed to a ``jax.jit(...)`` call in the same scope, or named like a
step kernel) and flags host-pull calls in their bodies.

Shape arithmetic is exempt: ``int(x.shape[0])`` and friends are static
under jit and idiomatic.
"""

from __future__ import annotations

import ast
from typing import List, Set

from siddhi_tpu.analysis.engine import Finding, LintContext, Rule

_HOT_DIRS = ("core/query/", "core/join/", "parallel/")
# the codebase's convention for traced kernels built by closures: a
# NESTED def named `step`/`fn`/`kernel` inside a builder is the body
# that jax.jit traces (build_step_fn / build_side_step_fn / _make_step)
_KERNEL_NAMES = ("step", "fn", "kernel", "fused", "sharded", "one_dev")
_PULL_BUILTINS = ("float", "int", "bool")
_STATIC_ATTRS = ("shape", "ndim", "size", "dtype", "itemsize", "nbytes")


def _is_jit_call(node: ast.Call) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``partial(jax.jit, ...)``."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "jit":
        return True
    if isinstance(fn, ast.Name) and fn.id == "jit":
        return True
    if isinstance(fn, ast.Name) and fn.id == "partial" and node.args:
        first = node.args[0]
        return (isinstance(first, (ast.Attribute, ast.Name))
                and getattr(first, "attr", getattr(first, "id", None))
                == "jit")
    return False


def _jitted_names(tree: ast.AST) -> Set[str]:
    """Function names referenced as the first argument of a jit call
    anywhere in the module (``jax.jit(fn, donate_argnums=0)``)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                names.add(arg.id)
    return names


def _is_static_arg(node: ast.AST) -> bool:
    """True when the expression is shape/metadata arithmetic — static
    under jit, never a device pull."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            return True
    return False


class HostPullRule(Rule):
    id = "R5"
    title = "no host pull in hot-path step code"

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.modules:
            if not any(d in mod.path for d in _HOT_DIRS):
                continue
            jitted = _jitted_names(mod.tree)
            # nested = defined inside another function (a builder)
            nested: Set[int] = set()
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for sub in ast.walk(node):
                        if sub is not node and isinstance(
                                sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            nested.add(id(sub))
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if not self._is_step_fn(node, jitted,
                                        id(node) in nested):
                    continue
                self._scan_step(mod, node, findings)
        return findings

    def _is_step_fn(self, node, jitted: Set[str], is_nested: bool) -> bool:
        if node.name in jitted:
            return True
        if is_nested and node.name in _KERNEL_NAMES:
            return True
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and _is_jit_call(dec):
                return True
            if (isinstance(dec, ast.Attribute) and dec.attr == "jit") or \
                    (isinstance(dec, ast.Name) and dec.id == "jit"):
                return True
        return False

    def _scan_step(self, mod, func, findings) -> None:
        # the candidate's OWN body only: nested defs are host-side
        # helpers or separate candidates in their own right
        todo = list(ast.iter_child_nodes(func))
        body: list = []
        while todo:
            n = todo.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            body.append(n)
            todo.extend(ast.iter_child_nodes(n))
        for node in body:
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in _PULL_BUILTINS:
                if node.args and not _is_static_arg(node.args[0]):
                    findings.append(Finding(
                        self.id, mod.path, node.lineno,
                        f"{fn.id}() on a device value inside step "
                        f"'{func.name}' forces a synchronous host pull "
                        f"— keep the value on device or ride it in the "
                        f"packed __meta__"))
            elif isinstance(fn, ast.Attribute):
                if fn.attr == "item":
                    findings.append(Finding(
                        self.id, mod.path, node.lineno,
                        f".item() inside step '{func.name}' is a "
                        f"synchronous host pull — batch it through the "
                        f"meta/device_get path"))
                elif (fn.attr in ("asarray", "array")
                      and isinstance(fn.value, ast.Name)
                      and fn.value.id in ("np", "numpy")):
                    findings.append(Finding(
                        self.id, mod.path, node.lineno,
                        f"np.{fn.attr}() inside step '{func.name}' "
                        f"pulls to host — step code must stay on "
                        f"device (use jnp, or hoist the host work out "
                        f"of the step)"))
