"""R1 — no-backend-init-at-import.

The PR-7 breaker class: a module-level ``jnp.int64(...)`` constant in
``parallel/mesh.py`` materialized a device array at import, silently
initializing the jax backend before ``force_host_devices`` could
configure the virtual mesh — every multi-device script collapsed to one
device with no error. The rule flags ANY evaluation of the module's
``jax.numpy`` alias outside a function body — module level, class
bodies, default argument values and decorators all execute at import —
plus module-level calls into jax's eager/backend APIs.

Fix pattern: numpy for constants (``np.int64(2**62)`` promotes
identically inside jitted arithmetic), lazy init for anything that
really needs a device.
"""

from __future__ import annotations

import ast
from typing import List

from siddhi_tpu.analysis.engine import Finding, LintContext, Rule

# jax.<name>(...) calls that initialize or query the backend
_EAGER_JAX_CALLS = {
    "devices", "local_devices", "device_count", "local_device_count",
    "default_backend", "device_put", "device_get", "make_mesh",
}


def _jnp_aliases(tree: ast.AST) -> set:
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                # bare `import jax.numpy` binds the NAME `jax` (the
                # package) — jax.config.update at module level is fine;
                # the dotted `jax.numpy` access is caught separately in
                # _scan_expr, so only an explicit asname is an alias
                if a.name == "jax.numpy" and a.asname:
                    aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and node.level == 0:
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
    return aliases


class BackendInitRule(Rule):
    id = "R1"
    title = "no backend init at import"

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.modules:
            aliases = _jnp_aliases(mod.tree)
            self._scan_body(mod, mod.tree.body, aliases, findings)
        return findings

    # ------------------------------------------------------------------

    def _scan_body(self, mod, body, aliases, findings) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the BODY runs lazily, but defaults and decorators
                # evaluate at import time
                for n in (st.args.defaults
                          + [d for d in st.args.kw_defaults if d is not None]
                          + st.decorator_list):
                    self._scan_expr(mod, n, aliases, findings)
                continue
            if isinstance(st, ast.ClassDef):
                for n in st.decorator_list + st.bases:
                    self._scan_expr(mod, n, aliases, findings)
                self._scan_body(mod, st.body, aliases, findings)
                continue
            if isinstance(st, ast.If) and self._is_main_guard(st.test):
                # `if __name__ == "__main__":` runs as a script entry
                # point, never at import
                continue
            self._scan_expr(mod, st, aliases, findings)

    @staticmethod
    def _is_main_guard(test: ast.AST) -> bool:
        return (isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == "__name__")

    @staticmethod
    def _walk_eager(node):
        """ast.walk that does not descend into lazily-evaluated bodies
        (functions and lambdas defined at module level run later) —
        but a nested def's defaults and decorators DO evaluate at
        import, even inside a module-level if/try block."""
        todo = [node]
        while todo:
            n = todo.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                todo.extend(n.args.defaults)
                todo.extend(d for d in n.args.kw_defaults if d is not None)
                todo.extend(n.decorator_list)
                continue
            if isinstance(n, ast.Lambda):
                todo.extend(n.args.defaults)
                todo.extend(d for d in n.args.kw_defaults if d is not None)
                continue
            todo.extend(ast.iter_child_nodes(n))

    def _scan_expr(self, mod, node, aliases, findings) -> None:
        for sub in self._walk_eager(node):
            if (isinstance(sub, ast.Name) and sub.id in aliases
                    and isinstance(sub.ctx, ast.Load)):
                findings.append(Finding(
                    self.id, mod.path, sub.lineno,
                    f"module-level evaluation of jax.numpy alias "
                    f"'{sub.id}' runs at import and can initialize the "
                    f"jax backend (the force_host_devices breaker class)"
                    f" — use numpy or compute lazily inside a function"))
            elif (isinstance(sub, ast.Attribute) and sub.attr == "numpy"
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "jax"):
                # dotted access: `jax.numpy.int64(...)` via plain
                # `import jax` — same breaker class, no alias involved
                findings.append(Finding(
                    self.id, mod.path, sub.lineno,
                    "module-level jax.numpy evaluation runs at import "
                    "and can initialize the jax backend (the "
                    "force_host_devices breaker class) — use numpy or "
                    "compute lazily inside a function"))
            elif isinstance(sub, ast.Call):
                fn = sub.func
                if (isinstance(fn, ast.Attribute)
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == "jax"
                        and fn.attr in _EAGER_JAX_CALLS):
                    findings.append(Finding(
                        self.id, mod.path, sub.lineno,
                        f"module-level jax.{fn.attr}() initializes the "
                        f"backend at import — defer it into a function"))
