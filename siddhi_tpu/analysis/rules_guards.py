"""R8 — guarded-by lock coverage.

R4 checks the ORDER locks are taken in; nothing checked which fields a
lock actually protects — that contract lived in prose ("callers hold
the pump lock", "caller holds the shard lock") and every post-review
race fix in CHANGES.md is a field that escaped it. A threaded class now
declares the contract as data:

    class CompletionPump:
        GUARDED_BY = {"_pending": "pump"}

(ranks from ``analysis/lockorder.py``; the runtime half is
``analysis/guards.py`` — descriptor-asserted access under
``SIDDHI_TPU_SANITIZE=1``). This rule learns every declaration
tree-wide and flags, in the declaring class:

- any ``self._field`` read/write outside a ``with`` on a lock of the
  declared rank (``__init__`` is exempt — construction precedes
  sharing; methods named ``*_locked`` are exempt — the suffix is the
  repo's caller-holds-the-lock idiom, and the runtime descriptors still
  verify them);
- a ``GUARDED_BY`` rank not declared in ``lockorder.RANKS``;
- a declared field with ZERO locked accesses anywhere in the class — a
  stale declaration guards nothing;
- and, bidirectionally: a class that spawns threads, shares an
  obviously-mutable field (dict/list/set/deque built in ``__init__``,
  written in other methods) and declares NO ``GUARDED_BY`` at all —
  undeclared shared state in threaded code is the original sin this
  rule exists to retire.

Lock-rank resolution is R4's: ``self.<attr> = make_lock("<rank>")``
learned per class, ``<var>._lock`` through ``VARIABLE_RANKS``,
``_barrier`` attributes, plus ``threading.Condition(self.<lock>)``
aliases (a ``with self._cv:`` holds the wrapped lock's rank).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from siddhi_tpu.analysis import lockorder
from siddhi_tpu.analysis.engine import Finding, LintContext, Rule

# mutating calls that count as writes for the undeclared-shared-state
# check (ast attribute name on the field)
_MUTATORS = frozenset({
    "append", "appendleft", "add", "update", "setdefault", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "extend",
    "insert",
})

_MUTABLE_BUILDERS = frozenset({
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter",
})


def _dict_literal(node: ast.AST) -> Optional[Dict[str, str]]:
    """A ``{"field": "rank", ...}`` literal, or None."""
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, str] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            return None
        out[k.value] = v.value
    return out


def _is_mutable_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        return name in _MUTABLE_BUILDERS
    return False


class _ClassFacts:
    """Everything R8 learns about one class definition."""

    def __init__(self, node: ast.ClassDef, mod_path: str):
        self.node = node
        self.mod_path = mod_path
        self.guarded: Dict[str, str] = {}
        self.guarded_line: int = node.lineno
        self.lock_ranks: Dict[str, str] = {}    # self.<attr> -> rank
        self.spawns_threads = False
        self._learn()

    def _learn(self) -> None:
        for sub in ast.walk(self.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Name) and tgt.id == "GUARDED_BY":
                    declared = _dict_literal(sub.value)
                    if declared is not None:
                        self.guarded = declared
                        self.guarded_line = sub.lineno
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and isinstance(sub.value, ast.Call)
                        and isinstance(sub.value.func, ast.Name)
                        and sub.value.func.id == "make_lock"
                        and sub.value.args
                        and isinstance(sub.value.args[0], ast.Constant)):
                    self.lock_ranks[tgt.attr] = sub.value.args[0].value
            if isinstance(sub, ast.Call):
                fn = sub.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if name == "Thread":
                    self.spawns_threads = True
        # second pass: Condition(self.<lock>) aliases inherit the rank
        for sub in ast.walk(self.node):
            if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                continue
            tgt = sub.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and isinstance(sub.value, ast.Call)):
                continue
            fn = sub.value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name != "Condition" or not sub.value.args:
                continue
            wrapped = sub.value.args[0]
            if (isinstance(wrapped, ast.Attribute)
                    and isinstance(wrapped.value, ast.Name)
                    and wrapped.value.id == "self"
                    and wrapped.attr in self.lock_ranks):
                self.lock_ranks[tgt.attr] = self.lock_ranks[wrapped.attr]


class GuardedByRule(Rule):
    id = "R8"
    title = "guarded-by lock coverage"

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.modules:
            if mod.path.startswith("tests/"):
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self._check_class(mod, _ClassFacts(node, mod.path),
                                      findings)
        return findings

    # ------------------------------------------------------------ per class

    def _check_class(self, mod, facts: _ClassFacts,
                     findings: List[Finding]) -> None:
        cls = facts.node
        if not facts.guarded:
            self._check_undeclared(mod, facts, findings)
            return
        for fname, rank in facts.guarded.items():
            if rank not in lockorder.RANKS:
                findings.append(Finding(
                    self.id, mod.path, facts.guarded_line,
                    f"{cls.name}.GUARDED_BY['{fname}'] names undeclared "
                    f"lock rank '{rank}' — add it to "
                    f"analysis/lockorder.py RANKS"))
        locked_uses: Dict[str, int] = {f: 0 for f in facts.guarded}
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_method(mod, facts, item, findings, locked_uses)
        for fname, n in locked_uses.items():
            if facts.guarded.get(fname) not in lockorder.RANKS:
                continue    # already reported as an undeclared rank
            if n == 0:
                findings.append(Finding(
                    self.id, mod.path, facts.guarded_line,
                    f"{cls.name}.GUARDED_BY declares '{fname}' but the "
                    f"class has no locked access to it — a stale "
                    f"declaration guards nothing (drop it or use the "
                    f"field under its lock)"))

    # ------------------------------------------------------- method scan

    def _scan_method(self, mod, facts: _ClassFacts, func, findings,
                     locked_uses: Dict[str, int]) -> None:
        if func.name == "__init__":
            # construction precedes sharing (the runtime descriptor
            # exempts it identically) — but still count nothing
            return
        if func.name.endswith("_locked"):
            base_held: Set[str] = set(facts.guarded.values())
        else:
            base_held = set()

        def rank_of(expr: ast.AST) -> Optional[str]:
            if isinstance(expr, ast.Attribute):
                if expr.attr in lockorder.BARRIER_ATTRS:
                    return "barrier"
                if isinstance(expr.value, ast.Name):
                    base = expr.value.id
                    if base == "self":
                        return facts.lock_ranks.get(expr.attr)
                    if expr.attr == "_lock":
                        return lockorder.VARIABLE_RANKS.get(base)
            return None

        def check_expr(expr: ast.AST, held: Set[str]) -> None:
            for sub in ast.walk(expr):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and sub.attr in facts.guarded):
                    rank = facts.guarded[sub.attr]
                    if rank not in lockorder.RANKS:
                        continue
                    if rank in held:
                        locked_uses[sub.attr] += 1
                    else:
                        findings.append(Finding(
                            self.id, mod.path, sub.lineno,
                            f"access to {facts.node.name}.{sub.attr} "
                            f"outside a '{rank}'-ranked lock — "
                            f"GUARDED_BY declares it guarded; wrap the "
                            f"access in `with` on the lock (or amend "
                            f"the contract)"))

        def walk(body, held: Set[str]) -> None:
            for st in body:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # nested defs run later, on unknown threads
                    self._scan_method(mod, facts, st, findings,
                                      locked_uses)
                    continue
                if isinstance(st, ast.With):
                    acquired = set(held)
                    for item in st.items:
                        r = rank_of(item.context_expr)
                        if r is not None:
                            acquired.add(r)
                        check_expr(item.context_expr, held)
                    walk(st.body, acquired)
                    continue
                # check this statement's own expressions, then descend
                # into compound bodies with the same held set
                for sub in ast.iter_child_nodes(st):
                    if isinstance(sub, ast.expr):
                        check_expr(sub, held)
                    elif isinstance(sub, ast.ExceptHandler):
                        walk(sub.body, held)
                    elif isinstance(sub, ast.stmt):
                        walk([sub], held)
        walk(func.body, set(base_held))

    # ------------------------------------------------ undeclared classes

    def _check_undeclared(self, mod, facts: _ClassFacts,
                          findings: List[Finding]) -> None:
        """Bidirectional half: a thread-spawning class sharing mutable
        state with no GUARDED_BY at all."""
        if not facts.spawns_threads:
            return
        cls = facts.node
        built: Dict[str, int] = {}
        for item in cls.body:
            if (isinstance(item, ast.FunctionDef)
                    and item.name == "__init__"):
                for sub in ast.walk(item):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        tgt, val = sub.targets[0], sub.value
                    elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                        tgt, val = sub.target, sub.value
                    else:
                        continue
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and _is_mutable_ctor(val)):
                        built[tgt.attr] = sub.lineno
        if not built:
            return
        written: Set[str] = set()
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            for sub in ast.walk(item):
                attr = None
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.ctx, (ast.Store, ast.Del))
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"):
                    attr = sub.attr
                elif (isinstance(sub, ast.Subscript)
                        and isinstance(sub.ctx, (ast.Store, ast.Del))
                        and isinstance(sub.value, ast.Attribute)
                        and isinstance(sub.value.value, ast.Name)
                        and sub.value.value.id == "self"):
                    attr = sub.value.attr
                elif (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _MUTATORS
                        and isinstance(sub.func.value, ast.Attribute)
                        and isinstance(sub.func.value.value, ast.Name)
                        and sub.func.value.value.id == "self"):
                    attr = sub.func.value.attr
                if attr in built:
                    written.add(attr)
        if written:
            fields = ", ".join(sorted(written))
            findings.append(Finding(
                self.id, mod.path, cls.lineno,
                f"thread-spawning class {cls.name} mutates shared "
                f"field(s) {fields} with no GUARDED_BY declaration — "
                f"declare the guarding rank(s) (analysis/guards.py) or "
                f"suppress with a reviewed justification"))
