"""Declared lock partial order — the single source of truth for R4.

The hot-path locking discipline used to live only in prose (PR-5's
"owner -> pump" contract in ``core/query/completion.py``, PR-6's
"fold under the shard lock, WAL record inside it", the app ingestion
barrier that everything else nests under). This module turns those
sentences into data consumed by BOTH enforcement layers:

- the static rule ``analysis/rules_locks.py`` (graftlint R4) flags a
  ``with`` acquisition that can invert the order, at review time;
- the runtime shim ``analysis/locks.py`` (``SIDDHI_TPU_SANITIZE=1``)
  asserts the order on every acquisition, at test time.

``EDGES`` are "must be acquired before" pairs: ``("owner", "pump")``
means a thread holding a *pump*-ranked lock may never acquire an
*owner*-ranked lock. Same-rank nesting is always allowed (chained
queries take owner locks down the emit cascade; re-entrant RLocks are
re-entrant by design).

Ranked locks are created through ``analysis.locks.make_lock(rank)``;
locks created bare (telemetry registries, scheduler, tables, ...) have
no rank and are transparent to both checkers.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Tuple

# rank -> owning subsystem, for error messages and docs
RANKS: Dict[str, str] = {
    "barrier": "app ingestion barrier (SiddhiAppRuntime._barrier)",
    "owner": "per-query / fused-group lock (QueryRuntime._lock, "
             "FusedFanoutRuntime._lock)",
    "pump": "CompletionPump._lock (core/query/completion.py)",
    "shard": "AggregationShard._lock (serving/sharded_aggregation.py)",
    "wal": "IngestWAL._lock (resilience/replay.py)",
    "ingest": "IngestPackPool._lock (core/stream/input/pack_pool.py)",
    "autopilot": "AutopilotController locks (siddhi_tpu/autopilot/"
                 "controller.py)",
    "adapt": "StreamJunction._adapt_lock (core/stream/junction.py)",
    "overload": "OverloadManager / FairScheduler / AppOverloadControl "
                "locks (resilience/overload.py)",
    "app_supervisor": "AppSupervisor._lock (resilience/supervisor.py)",
    # cluster fabric (siddhi_tpu/cluster/) — PR-17's bare locks, ranked
    "cluster_ingest": "ClusterRuntime._ingest_lock — global sequencing "
                      "+ checkpoint barrier (cluster/router.py)",
    "link": "_WorkerLink._lock — send vs recovery session "
            "(cluster/router.py)",
    "router": "ClusterRuntime._lock — link attach/invalidate, ids "
              "(cluster/router.py)",
    "egress": "OrderedEgress._lock/_cv (cluster/egress.py)",
    "cluster_supervisor": "WorkerSupervisor._lock "
                          "(cluster/supervisor.py)",
}

# (first, second): `first` must be acquired before `second`; acquiring
# `first` while holding `second` is an inversion.
EDGES: Tuple[Tuple[str, str], ...] = (
    ("barrier", "owner"),   # send/persist hold the barrier around dispatch
    ("owner", "pump"),      # PR-5 contract: pump lock never wraps an owner
    ("barrier", "shard"),   # checkpoint_shards runs under the app barrier
    ("shard", "wal"),       # PR-6: fold + WAL record are atomic vs rebuild
    ("barrier", "wal"),     # ingest records the WAL under the barrier
    # parallel pack runs inside delivery (barrier and owner may be held);
    # the pool's bookkeeping lock is a leaf — pool workers take NO ranked
    # locks, so nothing is ever acquired under "ingest"
    ("barrier", "ingest"),
    ("owner", "ingest"),
    # the autopilot tick is outermost: actuators take owner locks (join
    # Wp rebuild, reshard), drain the pump (flush_owner) and resize the
    # ingest pool while a controller tick is in progress — nothing in
    # the engine ever calls back INTO the controller under its locks
    ("autopilot", "barrier"),
    ("autopilot", "owner"),
    ("autopilot", "pump"),
    ("autopilot", "ingest"),
    # cluster fabric (cluster/router.py): the global-sequencing lock is
    # outermost — _ingest_frame splits + sends runs (link session) and
    # registers egress expectations under it; the checkpoint barrier
    # cuts/trims worker WALs under it
    ("cluster_ingest", "link"),
    ("cluster_ingest", "egress"),
    ("cluster_ingest", "wal"),
    # a send/recovery failure invalidates the session and notifies the
    # supervisor while holding the link session lock
    ("link", "router"),
    ("link", "egress"),      # recovery replays forget/drop under session
    ("link", "wal"),         # recovery reads the WAL suffix under session
    # the reader thread notifies the supervisor under the router lock;
    # the supervisor lock is a leaf (it never calls back into the router
    # under its own lock)
    ("router", "cluster_supervisor"),
)

# Static-rule recognizers: `NAME._lock` / `NAME` in a `with` resolves to
# a rank when the variable name is one of these (the runtime shim needs
# no heuristics — the lock object carries its rank).
VARIABLE_RANKS: Dict[str, str] = {
    "owner": "owner",
    "pump": "pump",
    "barrier": "barrier",
    "shard": "shard",
    "wal": "wal",
    "pool": "ingest",
    "link": "link",          # _WorkerLink._lock (cluster/router.py)
    "egress": "egress",      # OrderedEgress._lock (cluster/egress.py)
}

# Attribute names that denote the app barrier regardless of receiver.
BARRIER_ATTRS = ("_barrier",)


def must_precede() -> FrozenSet[Tuple[str, str]]:
    """Transitive closure of ``EDGES`` as a frozen set of
    (first, second) pairs."""
    closure = set(EDGES)
    changed = True
    while changed:
        changed = False
        for a, b in list(closure):
            for c, d in list(closure):
                if b == c and (a, d) not in closure:
                    closure.add((a, d))
                    changed = True
    return frozenset(closure)


_CLOSURE = must_precede()


def inversion(held_rank: str, acquiring_rank: str) -> bool:
    """True when acquiring ``acquiring_rank`` while holding
    ``held_rank`` inverts the declared order."""
    if held_rank == acquiring_rank:
        return False
    return (acquiring_rank, held_rank) in _CLOSURE
