"""R2 — typed-config-knob discipline.

The PR-9 regression class: every ``siddhi_tpu.*`` key used to ride a
generic ``int(v)`` loop in ``app_runtime``, so ``join_partition_grow:
'false'`` crashed with a bare ``ValueError`` and boolean/enum knobs
each grew ad-hoc spelling parsers in place. All knob reads now resolve
through the central typed parser registry
(``core/util/knobs.py``), which validates bool/int/enum spellings and
raises ``SiddhiAppValidationException`` NAMING the key and the accepted
spellings.

The rule flags:

- any ``*.get_property("siddhi_tpu....")`` call outside
  ``core/util/knobs.py`` (f-strings count — a dynamically-built key is
  still an ad-hoc read);
- any ``os.environ`` read of a ``SIDDHI_TPU_*`` variable outside the
  knob registry and the sanitizer module (env spellings deserve the
  same typed parsing as config keys);
- (the bidirectional half) any knob DECLARED in the registry that no
  production code ever reads — ``attr=None`` knobs need a
  ``read_knob(…, "key")`` literal somewhere, ``attr="x"`` knobs need
  the attribute consumed (``ctx.x`` or ``getattr(ctx, "x", …)``).
  A tunable nobody consumes is dead weight that silently does nothing
  when users set it.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from siddhi_tpu.analysis.engine import Finding, LintContext, Rule

_ALLOWED = ("core/util/knobs.py", "analysis/sanitize.py")
# SIDDHI_TPU_* env vars allowed as raw reads outside the registry
# (currently none — sanitize.py's own reads are covered by _ALLOWED)
_ENV_ALLOWED_NAMES = ()


def _literal_text(node: ast.AST) -> Optional[str]:
    """The literal portion of a Str or JoinedStr ('' for pure
    interpolation)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return "".join(v.value for v in node.values
                       if isinstance(v, ast.Constant)
                       and isinstance(v.value, str))
    return None


class ConfigKnobRule(Rule):
    id = "R2"
    title = "typed config-knob discipline"

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.modules:
            if any(mod.path.endswith(a) for a in _ALLOWED):
                continue
            in_tests = mod.path.startswith("tests/")
            if in_tests:
                continue    # tests set knobs on purpose, any spelling
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr == "get_property"):
                    for arg in node.args:
                        text = _literal_text(arg)
                        if text is not None and "siddhi_tpu." in text:
                            findings.append(Finding(
                                self.id, mod.path, node.lineno,
                                f"ad-hoc read of config key "
                                f"'{text}…' — resolve it through the "
                                f"typed parser registry in "
                                f"core/util/knobs.py (read_knob / "
                                f"apply_app_knobs) so junk spellings "
                                f"raise naming the key"))
                else:
                    self._check_env(mod, node, findings)
            for node in ast.walk(mod.tree):
                # os.environ["SIDDHI_TPU_…"] subscript reads too
                if (isinstance(node, ast.Subscript)
                        and isinstance(node.value, ast.Attribute)
                        and node.value.attr == "environ"):
                    text = _literal_text(node.slice)
                    if (text and text.startswith("SIDDHI_TPU_")
                            and text not in _ENV_ALLOWED_NAMES
                            and isinstance(node.ctx, ast.Load)):
                        findings.append(Finding(
                            self.id, mod.path, node.lineno,
                            f"ad-hoc read of env var '{text}' — give "
                            f"it a typed accessor in "
                            f"core/util/knobs.py"))
        findings.extend(self._dead_knobs(ctx))
        return findings

    # ------------------------------------------------- dead-knob parity

    def _dead_knobs(self, ctx: LintContext) -> List[Finding]:
        """The reverse direction: every ``Knob(...)`` declared in the
        registry must have a production consumer. Silent when the
        linted tree has no registry at all (targeted roots, fixtures).
        """
        knobs_mod = None
        for mod in ctx.modules:
            if mod.path.endswith("core/util/knobs.py"):
                knobs_mod = mod
                break
        if knobs_mod is None:
            return []
        declared = self._declared_knobs(knobs_mod.tree)
        if not declared:
            return []
        read_keys: set = set()
        attr_reads: set = set()
        for mod in ctx.modules:
            if mod is knobs_mod or mod.path.startswith("tests/"):
                continue
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)):
                    attr_reads.add(node.attr)
                elif isinstance(node, ast.Call):
                    fn = node.func
                    name = (fn.attr if isinstance(fn, ast.Attribute)
                            else fn.id if isinstance(fn, ast.Name)
                            else None)
                    if name == "read_knob":
                        for arg in node.args[1:]:
                            text = _literal_text(arg)
                            if text:
                                read_keys.add(text)
                    elif name == "getattr" and len(node.args) >= 2:
                        text = _literal_text(node.args[1])
                        if text:
                            attr_reads.add(text)
        findings: List[Finding] = []
        for key in sorted(declared):
            attr, lineno = declared[key]
            alive = (key in read_keys) if attr is None \
                else (attr in attr_reads)
            if not alive:
                how = (f"read_knob(…, '{key}')" if attr is None
                       else f"a read of ctx.{attr}")
                findings.append(Finding(
                    self.id, knobs_mod.path, lineno,
                    f"knob '{key}' is declared but never read by "
                    f"production code ({how} not found) — wire up a "
                    f"consumer or drop the declaration"))
        return findings

    @staticmethod
    def _declared_knobs(tree) -> dict:
        """``{key: (attr_or_None, lineno)}`` from the registry's
        ``Knob("key", ..., attr=...)`` declarations."""
        out: dict = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "Knob"
                    and node.args):
                continue
            key = _literal_text(node.args[0])
            if key is None:
                continue
            attr = None
            for kw in node.keywords:
                if kw.arg == "attr":
                    attr = _literal_text(kw.value)
            out[key] = (attr, node.lineno)
        return out

    def _check_env(self, mod, node: ast.Call, findings) -> None:
        """os.environ.get("SIDDHI_TPU_…") / os.getenv(…) outside the
        registry."""
        fn = node.func
        is_env_get = (isinstance(fn, ast.Attribute) and fn.attr == "get"
                      and isinstance(fn.value, ast.Attribute)
                      and fn.value.attr == "environ")
        is_getenv = (isinstance(fn, ast.Attribute) and fn.attr == "getenv")
        if not (is_env_get or is_getenv) or not node.args:
            return
        text = _literal_text(node.args[0])
        if (text and text.startswith("SIDDHI_TPU_")
                and text not in _ENV_ALLOWED_NAMES):
            findings.append(Finding(
                self.id, mod.path, node.lineno,
                f"ad-hoc read of env var '{text}' — give it a typed "
                f"accessor in core/util/knobs.py"))
