"""R3 — metric-registration parity.

The PR-6 regression class: per-granularity ``siddhi_aggregation_*``
gauges were registered on the sharded aggregation path but not its
unsharded twin, so /metrics silently lost families depending on a
config knob. The exposition layer (``observability/export.py``) is the
single place where telemetry names become ``siddhi_*`` Prometheus
families, and it now carries two machine-readable declarations:

- ``TELEMETRY_PREFIXES`` — every dotted telemetry-name family the tree
  may register (first segment, e.g. ``"pipeline"``). A ``.gauge()`` /
  ``.count()`` / ``.histogram()`` call whose name starts with an
  undeclared segment would fall through to the generic
  ``siddhi_gauge``/``siddhi_counter_total`` catch-all unnoticed — that
  is now a finding, as is a declared prefix with NO registration site
  left (dead declaration).
- ``PROCESS_LIFETIME_GAUGES`` — gauge-name templates that are
  intentionally never unregistered (process-lifetime probes). Every
  other gauge template must have a matching ``remove_gauge`` site
  somewhere in the tree, or a dissolved/shut-down owner pins a dead
  probe on /metrics forever.

Additionally, any literal ``siddhi_*`` family string OUTSIDE export.py
is flagged: families are declared centrally, not scattered.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from siddhi_tpu.analysis.engine import Finding, LintContext, Rule

_FAMILY = re.compile(r"^siddhi_[a-z0-9_]+$")
# a telemetry name template: word-first dotted segments ('.py' or a
# leading-dot literal is NOT one — str.count("...") must never match)
_NAMEISH = re.compile(r"^[a-z][a-z0-9_]*[a-z0-9_.{}*]*\.[a-z0-9_.{}*]+$")
_REG_METHODS = ("gauge", "count", "histogram")
# `.count(` is a common str/list method: treat it as a telemetry
# registration only on a registry-looking receiver (the repo convention)
_COUNT_RECEIVERS = ("tel", "telemetry", "_tel", "registry", "sm",
                    "stats", "statistics_manager")


def _name_template(node: ast.AST) -> Optional[str]:
    """Literal dotted-name template of a registration arg, with every
    interpolated piece normalized to ``*`` ('junction.*.queue_depth')."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _template_matches(template: str, pattern: str) -> bool:
    """fnmatch-lite where ``*`` in EITHER side matches any run."""
    rx = re.escape(pattern).replace(r"\*", ".*")
    tpl = re.escape(template).replace(r"\*", ".*")
    return bool(re.fullmatch(rx, template) or re.fullmatch(tpl, pattern))


class MetricParityRule(Rule):
    id = "R3"
    title = "metric-registration parity"

    @staticmethod
    def _countish(recv: ast.AST) -> bool:
        """Does a ``.count(...)`` receiver look like a telemetry
        registry (vs a str/list)?"""
        if isinstance(recv, ast.Name):
            return recv.id in _COUNT_RECEIVERS
        if isinstance(recv, ast.Attribute):
            return recv.attr in _COUNT_RECEIVERS
        if isinstance(recv, ast.Call):
            f = recv.func
            name = getattr(f, "attr", getattr(f, "id", ""))
            return name in ("global_registry",)
        return False

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        gauges: Dict[str, tuple] = {}       # template -> (path, line)
        removed: Set[str] = set()
        seen_prefixes: Set[str] = set()
        declared = tuple(ctx.telemetry_prefixes)
        allow = tuple(ctx.unremoved_gauge_allow)
        export_suffix = ctx.export_path.rsplit("/", 1)[-1]

        for mod in ctx.modules:
            if mod.path.startswith("tests/"):
                continue
            is_export = mod.path.endswith(export_suffix)
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and _FAMILY.match(node.value)
                        and not node.value.startswith("siddhi_tpu")
                        and not is_export):
                    findings.append(Finding(
                        self.id, mod.path, node.lineno,
                        f"metric family '{node.value}' referenced "
                        f"outside observability/export.py — families "
                        f"are declared and rendered centrally there"))
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if (isinstance(fn, ast.Name) and fn.id == "stat_count"
                        and len(node.args) >= 2):
                    # resilience counters ride the StatisticsManager via
                    # the stat_count helper — same naming discipline
                    tpl = _name_template(node.args[1])
                    if tpl and "." in tpl and _NAMEISH.match(tpl):
                        prefix = tpl.split(".", 1)[0]
                        seen_prefixes.add(prefix)
                        if declared and prefix not in declared:
                            findings.append(Finding(
                                self.id, mod.path, node.lineno,
                                f"counter '{tpl}' starts with "
                                f"undeclared prefix '{prefix}' — add "
                                f"it to TELEMETRY_PREFIXES in "
                                f"export.py"))
                    continue
                if not isinstance(fn, ast.Attribute) or not node.args:
                    continue
                if fn.attr in _REG_METHODS or fn.attr == "remove_gauge":
                    if fn.attr == "count" and not self._countish(fn.value):
                        continue    # str.count / list.count, not telemetry
                    tpl = _name_template(node.args[0])
                    if (tpl is None or "." not in tpl
                            or not _NAMEISH.match(tpl)):
                        continue    # not a telemetry name (str.count etc.)
                    if fn.attr == "remove_gauge":
                        removed.add(tpl)
                        continue
                    prefix = tpl.split(".", 1)[0]
                    if prefix == "*":
                        continue    # fully dynamic — uncheckable
                    seen_prefixes.add(prefix)
                    if declared and prefix not in declared:
                        findings.append(Finding(
                            self.id, mod.path, node.lineno,
                            f"telemetry name '{tpl}' starts with "
                            f"undeclared prefix '{prefix}' — add it to "
                            f"TELEMETRY_PREFIXES in export.py WITH a "
                            f"family mapping, or it renders as a "
                            f"generic catch-all"))
                    if fn.attr == "gauge":
                        gauges.setdefault(tpl, (mod.path, node.lineno))

        # dead declarations: a prefix with no registration site left
        exp = ctx.module(ctx.export_path) or ctx.module("export.py")
        exp_path = exp.path if exp is not None else "export.py"
        for prefix in declared:
            if prefix not in seen_prefixes:
                findings.append(Finding(
                    self.id, exp_path, 1,
                    f"TELEMETRY_PREFIXES declares '{prefix}' but no "
                    f"gauge/count/histogram registration uses it — "
                    f"remove the dead declaration"))

        # register/unregister pairing
        for tpl, (path, line) in sorted(gauges.items()):
            if any(_template_matches(tpl, r) for r in removed):
                continue
            if any(_template_matches(tpl, a) for a in allow):
                continue
            findings.append(Finding(
                self.id, path, line,
                f"gauge '{tpl}' is registered but never removed and is "
                f"not in PROCESS_LIFETIME_GAUGES (export.py) — a "
                f"dissolved owner would pin a dead probe on /metrics"))
        return findings
