"""Runtime sanitizers, gated by ``SIDDHI_TPU_SANITIZE=1``.

Four detectors for the bug classes graftlint checks statically, armed
at runtime so CI and quick checks catch what escapes the AST:

1. **Host-transfer detection.** ``jax.transfer_guard`` is set to
   ``disallow`` for implicit device->host transfers (explicit
   ``jax.device_get`` — the engine's sanctioned batched pull — stays
   allowed). On the CPU backend jax's guard is inert (arrays alias host
   memory), so a portable shim additionally patches the device array's
   scalar coercions (``float()``/``int()``/``bool()``/``.item()`` — the
   exact R5 pattern set) to raise ``HostPullError`` outside an
   ``allowed_pull()`` scope.

2. **Post-warmup recompile watchdog.** ``InstrumentedJit``
   (observability/telemetry.py) tracks the wrapped jitted callable's
   compile-cache size per call; once a key exceeds its compile budget
   (``SIDDHI_TPU_SANITIZE_MAX_COMPILES``, default 8 — pow2 padding
   means a healthy step sees a handful of shapes), or ANY cache miss
   lands after ``freeze_compiles()``, a ``RecompileError`` names the
   jit key. Compile storms (a recompile per batch) fail loudly instead
   of showing up as p99.

3. **Lock-order assertions.** ``analysis.locks.make_lock`` returns
   ``CheckedRLock``s that enforce the partial order declared in
   ``analysis/lockorder.py`` per thread, per acquisition.

4. **Lock-coverage (guarded-by) assertions.** ``analysis.guards``
   installs a data descriptor per field a class declares in its
   ``GUARDED_BY`` map (the static half is graftlint R8): every
   read/write asserts via the ``CheckedRLock`` per-thread holdings that
   a lock of the guarding rank is held, raising ``GuardViolation``
   otherwise. Plain attributes when off — zero cost.

Enable with ``SIDDHI_TPU_SANITIZE=1`` in the environment BEFORE
importing siddhi_tpu (the lock factory and jit proxies read it at
construction). ``tools/quick_all.py sanitize`` runs the quick-check
tier under it.
"""

from __future__ import annotations

import os
import threading

_ENV = "SIDDHI_TPU_SANITIZE"
_ENV_MAX_COMPILES = "SIDDHI_TPU_SANITIZE_MAX_COMPILES"


class HostPullError(RuntimeError):
    """A device value was coerced to a host scalar outside a sanctioned
    pull site (the R5 no-host-pull-in-hot-path bug class)."""


class RecompileError(RuntimeError):
    """A jitted step recompiled past its warmup budget."""


def enabled() -> bool:
    return os.environ.get(_ENV, "").strip().lower() in ("1", "true", "on",
                                                        "yes")


def max_compiles() -> int:
    # typed read: a junk spelling raises naming the variable instead of
    # silently falling back to the default (the R2 discipline)
    from siddhi_tpu.core.util.knobs import env_knob

    return env_knob(_ENV_MAX_COMPILES, "int", 8)


# ----------------------------------------------------------- pull guard

_TLS = threading.local()
_PATCHED = [False]


class allowed_pull:
    """Scope marker for sanctioned host pulls (snapshot capture, test
    assertions): scalar coercions inside do not raise."""

    def __enter__(self):
        _TLS.depth = getattr(_TLS, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _TLS.depth -= 1
        return False


def _pull_allowed() -> bool:
    return getattr(_TLS, "depth", 0) > 0


def _install_pull_guard() -> None:
    """Patch the concrete jax array type's scalar coercions to raise
    outside ``allowed_pull()``. ``np.asarray``/``jax.device_get`` (the
    sanctioned batched pulls) are untouched; on non-CPU backends the
    jax transfer guard additionally covers implicit ``np.asarray``."""
    if _PATCHED[0]:
        return
    try:
        # class import only — materializing an array here would
        # initialize the backend at siddhi_tpu import (the R1 bug class)
        from jax._src.array import ArrayImpl as cls
    except ImportError:         # pragma: no cover — jax layout change
        return
    for name in ("__float__", "__int__", "__bool__", "item"):
        orig = getattr(cls, name, None)
        if orig is None:        # pragma: no cover — jaxlib layout change
            continue

        def guard(self, *args, __orig=orig, __name=name, **kw):
            # enabled() re-checked per call: the patch is process-wide
            # and must go inert when a test unsets the env var
            if enabled() and not _pull_allowed():
                raise HostPullError(
                    f"sanitizer: host pull via {__name}() on a device "
                    f"array outside a sanctioned pull site — batch the "
                    f"transfer through jax.device_get (or wrap a cold-"
                    f"path read in analysis.sanitize.allowed_pull())")
            return __orig(self, *args, **kw)

        try:
            setattr(cls, name, guard)
        except TypeError:       # pragma: no cover — sealed type
            return
    _PATCHED[0] = True


# ------------------------------------------------------ recompile guard

_FROZEN = [False]


def freeze_compiles() -> None:
    """Declare warmup over: from now on ANY jit cache miss raises
    ``RecompileError`` naming the key (tests pin this around a planted
    recompile; long-running soaks call it after their warm phase)."""
    _FROZEN[0] = True


def thaw_compiles() -> None:
    _FROZEN[0] = False


def compiles_frozen() -> bool:
    return _FROZEN[0]


def check_recompile(key: str, compiles: int) -> None:
    """Called by ``InstrumentedJit`` when the wrapped callable's compile
    cache grew. Raises past the per-key budget or after a freeze."""
    if not enabled():
        # an InstrumentedJit built while sanitize was on caches its slow
        # path, but after disable()/env-unset the watchdog must go inert
        # like the pull guard does
        return
    if _FROZEN[0]:
        raise RecompileError(
            f"sanitizer: jit key '{key}' recompiled after warmup "
            f"(freeze_compiles() active; compile #{compiles})")
    budget = max_compiles()
    if compiles > budget:
        raise RecompileError(
            f"sanitizer: jit key '{key}' compiled {compiles} times — "
            f"past the {_ENV_MAX_COMPILES}={budget} budget; a compile "
            f"per batch means a shape or dtype is not stabilizing "
            f"(check pow2 padding and weak types)")


# --------------------------------------------------------------- enable

def enable() -> None:
    """Arm every sanitizer this process supports. Idempotent; called at
    ``siddhi_tpu`` import when ``SIDDHI_TPU_SANITIZE=1``. Only
    configures jax (no backend init)."""
    import jax

    # implicit device->host transfers raise on accelerator backends;
    # explicit jax.device_get / device_put remain allowed
    jax.config.update("jax_transfer_guard_device_to_host", "disallow")
    _install_pull_guard()


def disable() -> None:
    """Disarm the jax-config side (tests that enable() mid-process call
    this in teardown; the pull-guard patch needs no undo — it re-checks
    ``enabled()`` per call and goes inert with the env var)."""
    import jax

    jax.config.update("jax_transfer_guard_device_to_host", "allow")
    thaw_compiles()
