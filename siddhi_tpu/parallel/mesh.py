"""Key-space sharding of query state over a TPU device mesh.

The reference scales by partitioning *state* across threads in one JVM
(``partition/PartitionStreamReceiver.java:96-135``, per-key state maps in
``util/snapshot/state/PartitionStateHolder.java:43-48``). The TPU-native
equivalent: keyed state lives in dense ``[..., K, ...]`` arrays, and K is
sharded across chips over a 1-D ``Mesh`` axis (ICI). Event batches are
sharded along the batch axis; XLA inserts the all-to-all/psum collectives
needed to scatter rows into the owning shard — there is no hand-written
NCCL/MPI analog (SURVEY.md §2.13, §5.8).

Multi-host: the same code runs under ``jax.distributed`` with a mesh that
spans hosts; shardings are expressed only via ``NamedSharding``, so the
DCN/ICI split is the compiler's job.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

KEY_AXIS = "keys"


def force_host_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU platform for sharding tests.

    Env vars alone are not enough: plugin platforms (e.g. the axon TPU
    tunnel) may call ``jax.config.update("jax_platforms", ...)`` at
    interpreter start, which overrides ``JAX_PLATFORMS``. This resets the
    platform to cpu and re-initializes backends with the host-device-count
    flag applied.
    """
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses
    jax.config.update("jax_platforms", "cpu")
    from jax.extend.backend import clear_backends

    clear_backends()  # must precede the device-count update (guarded)
    jax.config.update("jax_num_cpu_devices", n)


def make_mesh(n_devices: Optional[int] = None, axis_name: str = KEY_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def key_axis_sharding(mesh: Mesh, arr_ndim: int, key_axis_index: int) -> NamedSharding:
    """Shard one array along its key axis, replicate the rest."""
    spec = [None] * arr_ndim
    spec[key_axis_index] = KEY_AXIS
    return NamedSharding(mesh, P(*spec))


def state_shardings(state, mesh: Mesh, num_keys: int, win_keys: int = 1):
    """Pytree of shardings for a query-state pytree.

    Only keyed state is sharded: selector/aggregator arrays (under the
    ``"sel"`` subtree, shape ``[slots, K]``) and partitioned window state
    (under ``"win"``: per-key rows ``[Kw]`` or flat ring buffers
    ``[Kw*W]`` — key-contiguous layout, so an even split along axis 0 is a
    split along keys) split across the mesh. Global (unkeyed, ``win_keys``
    == 1) window ring buffers and scalars are replicated — sharding a
    global ring along its ring axis would put every window write on a
    collective."""
    replicated = NamedSharding(mesh, P())
    n_dev = mesh.devices.size

    def one(path, leaf):
        if not hasattr(leaf, "shape"):
            return replicated
        top = path[0].key if path and hasattr(path[0], "key") else None
        if top == "sel":
            for i, s in enumerate(leaf.shape):
                if s == num_keys:
                    return key_axis_sharding(mesh, leaf.ndim, i)
        if (
            top == "win"
            and win_keys > 1
            and leaf.ndim >= 1
            and leaf.shape[0] % win_keys == 0
            and leaf.shape[0] % n_dev == 0
            and win_keys % n_dev == 0
        ):
            return key_axis_sharding(mesh, leaf.ndim, 0)
        if (
            top == "nfa"
            and win_keys > 1
            and leaf.ndim >= 1
            and leaf.shape[0] == win_keys
            and win_keys % n_dev == 0
        ):
            # NFA slot tensors are key-major [K, S]; per-key vectors [K]
            return key_axis_sharding(mesh, leaf.ndim, 0)
        return replicated

    return jax.tree_util.tree_map_with_path(one, state)


def batch_shardings(cols, mesh: Mesh):
    """Shard every [B, ...] column along the batch axis."""

    def one(leaf):
        return NamedSharding(mesh, P(KEY_AXIS, *([None] * (leaf.ndim - 1)))) if leaf.ndim else NamedSharding(mesh, P())

    return jax.tree_util.tree_map(one, cols)


def shard_query_step(runtime, mesh: Mesh, donate: bool = True):
    """Jit a QueryRuntime's step with its keyed state sharded over ``mesh``.

    Returns ``(jitted_step, sharded_state)``. The batch stays replicated in
    this wrapper (scatter-heavy segment reductions into K-sharded state are
    the collective-bound part; replicating the small event batch keeps the
    all-to-all off the hot path). For B-sharded ingestion use
    ``batch_shardings`` explicitly.
    """
    num_keys = runtime.selector_plan.num_keys
    if runtime._state is None:
        runtime._state = runtime._init_state()
    step = runtime.build_step_fn()
    st_sh = state_shardings(runtime._state, mesh, num_keys,
                            win_keys=getattr(runtime, "_win_keys", 1))
    state = jax.device_put(runtime._state, st_sh)
    out_sh = _out_shardings(mesh, st_sh)
    jitted = jax.jit(
        step,
        in_shardings=(st_sh, None, None),
        out_shardings=out_sh,
        donate_argnums=(0,) if donate else (),
    )
    # hand the runtime the sharded timeline so junction-fed batches
    # (QueryRuntime.process_batch) and direct jitted() callers share state;
    # remember the mesh so capacity growth re-establishes the sharding
    # (QueryRuntime._ensure_capacity re-invokes this function)
    runtime._state = state
    runtime._step = jitted
    runtime._shard_mesh = mesh
    if hasattr(runtime, "_steps"):
        # NFA runtimes jit one step per input stream (plus a TIMER sweep);
        # clear them so they re-jit with the sharded in_shardings
        runtime._steps.clear()
        runtime._timer_step = None
    return jitted, state


def _out_shardings(mesh: Mesh, st_sh):
    """(state', out) output shardings for a sharded query step: state keeps
    its key-axis sharding; the OUT batch is forced replicated. On one host
    this is what the host pull does anyway; on a multi-process mesh it is
    required — ``jax.device_get`` can only read fully-addressable arrays,
    so a partially-sharded output would strand rows on the other host.
    ``None`` (let XLA choose) when the mesh is single-process: forcing a
    replicate there costs a gather with no benefit."""
    if all(d.process_index == jax.process_index() for d in mesh.devices.flat):
        return None
    return (st_sh, NamedSharding(mesh, P()))


def sharded_jit_for(runtime, fn, n_state_args: int = 1, n_plain_args: int = 2):
    """Jit ``fn(state, *plain)`` with the runtime's recorded mesh shardings
    (used by NFAQueryRuntime for per-stream and timer steps)."""
    mesh = runtime._shard_mesh
    st_sh = state_shardings(runtime._state, mesh, runtime.selector_plan.num_keys,
                            win_keys=getattr(runtime, "_win_keys", 1))
    return jax.jit(
        fn,
        in_shardings=(st_sh,) + (None,) * n_plain_args,
        out_shardings=_out_shardings(mesh, st_sh),
        donate_argnums=(0,),
    )
