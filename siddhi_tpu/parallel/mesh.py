"""Key-space sharding of query state over a TPU device mesh.

The reference scales by partitioning *state* across threads in one JVM
(``partition/PartitionStreamReceiver.java:96-135``, per-key state maps in
``util/snapshot/state/PartitionStateHolder.java:43-48``). The TPU-native
equivalent: keyed state lives in dense ``[..., K, ...]`` arrays, and K is
sharded across chips over a 1-D ``Mesh`` axis (ICI). Event batches are
sharded along the batch axis; XLA inserts the all-to-all/psum collectives
needed to scatter rows into the owning shard — there is no hand-written
NCCL/MPI analog (SURVEY.md §2.13, §5.8).

Multi-host: the same code runs under ``jax.distributed`` with a mesh that
spans hosts; shardings are expressed only via ``NamedSharding``, so the
DCN/ICI split is the compiler's job.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

KEY_AXIS = "keys"


def force_host_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU platform for sharding tests.

    Env vars alone are not enough: plugin platforms (e.g. the axon TPU
    tunnel) may call ``jax.config.update("jax_platforms", ...)`` at
    interpreter start, which overrides ``JAX_PLATFORMS``. This resets the
    platform to cpu and re-initializes backends with the host-device-count
    flag applied.
    """
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses
    jax.config.update("jax_platforms", "cpu")
    from jax.extend.backend import clear_backends

    clear_backends()  # must precede the device-count update (guarded)
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # older jax: the host-device count is an XLA flag consumed at
        # backend init — scrub any previous value, set the new one, and
        # re-clear so the next backend lookup picks it up
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        clear_backends()


def make_mesh(n_devices: Optional[int] = None, axis_name: str = KEY_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def key_axis_sharding(mesh: Mesh, arr_ndim: int, key_axis_index: int) -> NamedSharding:
    """Shard one array along its key axis, replicate the rest."""
    spec = [None] * arr_ndim
    spec[key_axis_index] = KEY_AXIS
    return NamedSharding(mesh, P(*spec))


def _key_axis_of(path, leaf, num_keys: int, win_keys: int) -> int:
    """Key-axis index of a query-state leaf, or -1 if unkeyed.

    Keyed state: selector/aggregator arrays (under the ``"sel"`` subtree,
    shape ``[slots, K]``), partitioned window state (under ``"win"``:
    per-key rows ``[Kw]`` or flat ring buffers ``[Kw*W]`` — key-contiguous
    layout, so an even split along axis 0 is a split along keys), and NFA
    slot tensors (``"nfa"``: key-major ``[K, S]`` / per-key ``[K]``)."""
    if not hasattr(leaf, "shape") or leaf.ndim == 0:
        return -1
    top = path[0].key if path and hasattr(path[0], "key") else None
    if top == "sel":
        for i, s in enumerate(leaf.shape):
            if s == num_keys:
                return i
    if top == "win" and win_keys > 1 and leaf.shape[0] % win_keys == 0:
        return 0
    if top == "nfa" and win_keys > 1 and leaf.shape[0] == win_keys:
        return 0
    return -1


def state_shardings(state, mesh: Mesh, num_keys: int, win_keys: int = 1):
    """Pytree of shardings for a query-state pytree.

    Only keyed state is sharded (see ``_key_axis_of``). Global (unkeyed,
    ``win_keys`` == 1) window ring buffers and scalars are replicated —
    sharding a global ring along its ring axis would put every window
    write on a collective."""
    replicated = NamedSharding(mesh, P())
    n_dev = mesh.devices.size

    def one(path, leaf):
        ax = _key_axis_of(path, leaf, num_keys, win_keys)
        if ax < 0:
            return replicated
        top = path[0].key if path and hasattr(path[0], "key") else None
        if top in ("win", "nfa") and (
            leaf.shape[0] % n_dev != 0 or win_keys % n_dev != 0
        ):
            return replicated
        return key_axis_sharding(mesh, leaf.ndim, ax)

    return jax.tree_util.tree_map_with_path(one, state)


def batch_shardings(cols, mesh: Mesh):
    """Shard every [B, ...] column along the batch axis."""

    def one(leaf):
        return NamedSharding(mesh, P(KEY_AXIS, *([None] * (leaf.ndim - 1)))) if leaf.ndim else NamedSharding(mesh, P())

    return jax.tree_util.tree_map(one, cols)


def _release_from_fanout(runtime):
    """A sharded step owns the runtime's dispatch: a fused fan-out group
    (core/query/fused_fanout.py) would keep stepping the member through
    its pre-sharding fused computation, so hand the member back its own
    junction subscription before wiring the sharded jit."""
    group = getattr(runtime, "_fanout_group", None)
    if group is not None:
        group.release(runtime)


def shard_query_step(runtime, mesh: Mesh, donate: bool = True):
    """Jit a QueryRuntime's step with its keyed state sharded over ``mesh``.

    Returns ``(jitted_step, sharded_state)``. The batch stays replicated in
    this wrapper (scatter-heavy segment reductions into K-sharded state are
    the collective-bound part; replicating the small event batch keeps the
    all-to-all off the hot path). For B-sharded ingestion use
    ``batch_shardings`` explicitly.
    """
    _release_from_fanout(runtime)
    num_keys = runtime.selector_plan.num_keys
    if runtime._state is None:
        runtime._state = runtime._init_state()
    step = runtime.build_step_fn()
    st_sh = state_shardings(runtime._state, mesh, num_keys,
                            win_keys=getattr(runtime, "_win_keys", 1))
    state = jax.device_put(runtime._state, st_sh)
    out_sh = _out_shardings(mesh, st_sh)
    jitted = jax.jit(
        step,
        in_shardings=(st_sh, None, None),
        out_shardings=out_sh,
        donate_argnums=(0,) if donate else (),
    )
    # telemetry: a sharded (re-)jit is a compile event — capacity growth
    # re-invokes this function, and those recompiles must be visible on
    # /metrics (siddhi_jit_compiles_total) before they show up as p99
    tel = getattr(runtime.app_context, "telemetry", None)
    if tel is not None:
        jitted = tel.instrument_jit(
            jitted, f"query.{runtime.name}.sharded_step")
    # hand the runtime the sharded timeline so junction-fed batches
    # (QueryRuntime.process_batch) and direct jitted() callers share state;
    # remember the mesh so capacity growth re-establishes the sharding
    # (QueryRuntime._ensure_capacity re-invokes this function)
    runtime._state = state
    runtime._step = jitted
    runtime._shard_mesh = mesh
    if hasattr(runtime, "_steps"):
        # NFA runtimes jit one step per input stream (plus a TIMER sweep);
        # clear them so they re-jit with the sharded in_shardings
        runtime._steps.clear()
        runtime._timer_step = None
    return jitted, state


def _out_shardings(mesh: Mesh, st_sh):
    """(state', out) output shardings for a sharded query step: state keeps
    its key-axis sharding; the OUT batch is forced replicated. On one host
    this is what the host pull does anyway; on a multi-process mesh it is
    required — ``jax.device_get`` can only read fully-addressable arrays,
    so a partially-sharded output would strand rows on the other host.
    ``None`` (let XLA choose) when the mesh is single-process: forcing a
    replicate there costs a gather with no benefit."""
    if all(d.process_index == jax.process_index() for d in mesh.devices.flat):
        return None
    return (st_sh, NamedSharding(mesh, P()))


def route_batch_to_shards(cols, n_shards: int, rows_per_shard: int):
    """Host-side all-to-all: scatter batch rows to their owning key shard.

    The owner of dense key ``k`` is ``k % n_shards`` and its local id is
    ``k // n_shards`` — round-robin keeps the keyer's dense ids
    load-balanced across shards. Returns a routed column dict of shape
    ``[n_shards * rows_per_shard]`` where segment ``d`` holds shard ``d``'s
    rows (original order preserved within the shard) padded with invalid
    rows, and the PK/GK columns rewritten to LOCAL ids. Pair with
    ``shard_keyed_query_step``: the router replaces the device-side
    all-to-all the reference's partition fan-out does with per-key junction
    dispatch (``PartitionStreamReceiver.java:96-135``)."""
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import PK_KEY, VALID_KEY

    key_col = PK_KEY if PK_KEY in cols else GK_KEY
    if GK_KEY in cols and PK_KEY in cols and not np.array_equal(
            np.asarray(cols[GK_KEY]), np.asarray(cols[PK_KEY])):
        # a group-by key distinct from the partition key lives in its own
        # dense-id space; rewriting it to partition-local ids would corrupt
        # the selector's group state (runtime.py:531-534 — GK == PK only
        # for partitioned queries without an explicit group-by)
        raise ValueError(
            "route_batch_to_shards requires GK == PK (partitioned query "
            "without a distinct group-by key)")
    valid = np.asarray(cols[VALID_KEY])
    keep = np.nonzero(valid)[0]  # capacity padding never competes for rows
    pk = np.asarray(cols[key_col]).astype(np.int64)[keep]
    owner = pk % n_shards
    local = pk // n_shards
    order = np.argsort(owner, kind="stable")
    counts = np.bincount(owner, minlength=n_shards)
    if int(counts.max(initial=0)) > rows_per_shard:
        raise ValueError(
            f"shard overflow: {int(counts.max())} rows for one shard > "
            f"rows_per_shard={rows_per_shard}; raise the pad or split the batch")
    starts = np.zeros(n_shards, np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    owner_sorted = owner[order]
    pos = np.arange(keep.shape[0], dtype=np.int64) - starts[owner_sorted]
    dest = owner_sorted * rows_per_shard + pos
    src = keep[order]

    N = n_shards * rows_per_shard
    routed = {}
    for k, v in cols.items():
        v = np.asarray(v)
        if k in (PK_KEY, GK_KEY):
            buf = np.zeros(N, v.dtype)
            buf[dest] = local[order].astype(v.dtype)
        else:
            buf = np.zeros((N,) + v.shape[1:], v.dtype)
            buf[dest] = v[src]
        routed[k] = buf
    return routed  # padding rows keep VALID=False (zero-fill)


def shard_keyed_query_step(runtime, mesh: Mesh, rows_per_shard: int):
    """Jit a keyed (partitioned) query step as a ``shard_map`` over ``mesh``
    — zero-collective data parallelism over the key space.

    Contract with ``route_batch_to_shards``: the runtime is sized to its
    PER-SHARD key capacity (``selector_plan.num_keys`` / ``_win_keys`` are
    local values), and batches arrive routed (``[n * rows_per_shard]`` rows
    carrying local key ids). Each device then steps its own
    ``[slots, K_local]`` / ``[K_local * W]`` state over only its own rows;
    the compiled HLO contains NO collective ops (verified by
    ``tools/hlo_audit.py``) — the host router IS the all-to-all, and the
    ICI carries nothing per step. Global-window queries cannot take this
    path (their ring semantics need every row in order); use
    ``shard_query_step`` for those.

    Returns ``(jitted_step, global_state)``. Out rows come back
    shard-segmented (leaf axis 0 = ``n * R_local``); ``"__meta__"`` is
    ``[n, 3]`` — one (overflow, notify, count) row per shard."""
    from jax.experimental.shard_map import shard_map

    _release_from_fanout(runtime)
    n = mesh.devices.size
    localK = runtime.selector_plan.num_keys
    local_win = getattr(runtime, "_win_keys", 1)
    if runtime._state is None:
        runtime._state = runtime._init_state()
    local_state = runtime._state
    step = runtime.build_step_fn()

    axes = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _key_axis_of(path, leaf, localK, local_win),
        local_state)

    def stack_global(leaf, ax):
        arr = np.asarray(leaf)
        if ax < 0:
            # unkeyed leaf: leading device axis — every shard keeps its own
            # independently-evolving copy (squeezed back inside the map)
            return np.stack([arr] * n, axis=0)
        return np.concatenate([arr] * n, axis=ax)

    global_state = jax.tree_util.tree_map(stack_global, local_state, axes)
    st_specs = jax.tree_util.tree_map(
        lambda ax: P(KEY_AXIS) if ax <= 0 else P(*([None] * ax), KEY_AXIS),
        axes)

    def wrapped(state, cols, now):
        state = jax.tree_util.tree_map(
            lambda leaf, ax: leaf[0] if ax < 0 else leaf, state, axes)
        st, out = step(state, cols, now)
        st = jax.tree_util.tree_map(
            lambda leaf, ax: jnp.asarray(leaf)[None] if ax < 0 else leaf,
            st, axes)
        out = {
            k: jnp.asarray(v)[None] if (k == "__meta__" or jnp.ndim(v) == 0)
            else v
            for k, v in out.items()
        }
        return st, out

    sharded = shard_map(
        wrapped, mesh=mesh,
        in_specs=(st_specs, P(KEY_AXIS), P()),
        out_specs=(st_specs, P(KEY_AXIS)),
        check_rep=False,
    )
    jitted = jax.jit(sharded, donate_argnums=(0,))
    state = jax.device_put(global_state, jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), st_specs))
    return jitted, state


def sharded_jit_for(runtime, fn, n_state_args: int = 1, n_plain_args: int = 2):
    """Jit ``fn(state, *plain)`` with the runtime's recorded mesh shardings
    (used by NFAQueryRuntime for per-stream and timer steps)."""
    mesh = runtime._shard_mesh
    st_sh = state_shardings(runtime._state, mesh, runtime.selector_plan.num_keys,
                            win_keys=getattr(runtime, "_win_keys", 1))
    return jax.jit(
        fn,
        in_shardings=(st_sh,) + (None,) * n_plain_args,
        out_shardings=_out_shardings(mesh, st_sh),
        donate_argnums=(0,),
    )
