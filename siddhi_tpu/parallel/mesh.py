"""Key-space sharding of query state over a TPU device mesh.

The reference scales by partitioning *state* across threads in one JVM
(``partition/PartitionStreamReceiver.java:96-135``, per-key state maps in
``util/snapshot/state/PartitionStateHolder.java:43-48``). The TPU-native
equivalent: keyed state lives in dense ``[..., K, ...]`` arrays, and K is
sharded across chips over a 1-D ``Mesh`` axis (ICI). Event batches are
sharded along the batch axis; XLA inserts the all-to-all/psum collectives
needed to scatter rows into the owning shard — there is no hand-written
NCCL/MPI analog (SURVEY.md §2.13, §5.8).

Multi-host: the same code runs under ``jax.distributed`` with a mesh that
spans hosts; shardings are expressed only via ``NamedSharding``, so the
DCN/ICI split is the compiler's job.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

KEY_AXIS = "keys"


def force_host_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU platform for sharding tests.

    Env vars alone are not enough: plugin platforms (e.g. the axon TPU
    tunnel) may call ``jax.config.update("jax_platforms", ...)`` at
    interpreter start, which overrides ``JAX_PLATFORMS``. This resets the
    platform to cpu and re-initializes backends with the host-device-count
    flag applied.
    """
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses
    jax.config.update("jax_platforms", "cpu")
    from jax.extend.backend import clear_backends

    clear_backends()  # must precede the device-count update (guarded)
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # older jax: the host-device count is an XLA flag consumed at
        # backend init — scrub any previous value, set the new one, and
        # re-clear so the next backend lookup picks it up
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        clear_backends()


def make_mesh(n_devices: Optional[int] = None, axis_name: str = KEY_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def key_axis_sharding(mesh: Mesh, arr_ndim: int, key_axis_index: int) -> NamedSharding:
    """Shard one array along its key axis, replicate the rest."""
    spec = [None] * arr_ndim
    spec[key_axis_index] = KEY_AXIS
    return NamedSharding(mesh, P(*spec))


def _key_axis_of(path, leaf, num_keys: int, win_keys: int) -> int:
    """Key-axis index of a query-state leaf, or -1 if unkeyed.

    Keyed state: selector/aggregator arrays (under the ``"sel"`` subtree,
    shape ``[slots, K]``), partitioned window state (under ``"win"``:
    per-key rows ``[Kw]`` or flat ring buffers ``[Kw*W]`` — key-contiguous
    layout, so an even split along axis 0 is a split along keys), and NFA
    slot tensors (``"nfa"``: key-major ``[K, S]`` / per-key ``[K]``)."""
    if not hasattr(leaf, "shape") or leaf.ndim == 0:
        return -1
    top = path[0].key if path and hasattr(path[0], "key") else None
    if top == "sel":
        for i, s in enumerate(leaf.shape):
            if s == num_keys:
                return i
    if (top in ("win", "lwin", "rwin") and win_keys > 1
            and leaf.shape[0] % win_keys == 0):
        # "lwin"/"rwin": a partitioned join's per-side keyed rings share
        # the single-stream keyed-window layout (key-contiguous flat)
        return 0
    if top == "nfa" and win_keys > 1 and leaf.shape[0] == win_keys:
        return 0
    return -1


def state_shardings(state, mesh: Mesh, num_keys: int, win_keys: int = 1):
    """Pytree of shardings for a query-state pytree.

    Only keyed state is sharded (see ``_key_axis_of``). Global (unkeyed,
    ``win_keys`` == 1) window ring buffers and scalars are replicated —
    sharding a global ring along its ring axis would put every window
    write on a collective."""
    replicated = NamedSharding(mesh, P())
    n_dev = mesh.devices.size

    def one(path, leaf):
        ax = _key_axis_of(path, leaf, num_keys, win_keys)
        if ax < 0:
            return replicated
        top = path[0].key if path and hasattr(path[0], "key") else None
        if top in ("win", "nfa") and (
            leaf.shape[0] % n_dev != 0 or win_keys % n_dev != 0
        ):
            return replicated
        return key_axis_sharding(mesh, leaf.ndim, ax)

    return jax.tree_util.tree_map_with_path(one, state)


def batch_shardings(cols, mesh: Mesh):
    """Shard every [B, ...] column along the batch axis."""

    def one(leaf):
        return NamedSharding(mesh, P(KEY_AXIS, *([None] * (leaf.ndim - 1)))) if leaf.ndim else NamedSharding(mesh, P())

    return jax.tree_util.tree_map(one, cols)


def _release_from_fanout(runtime):
    """A sharded step owns the runtime's dispatch: a fused fan-out group
    (core/query/fused_fanout.py) would keep stepping the member through
    its pre-sharding fused computation, so hand the member back its own
    junction subscription before wiring the sharded jit."""
    group = getattr(runtime, "_fanout_group", None)
    if group is not None:
        group.release(runtime)


def shard_query_step(runtime, mesh: Mesh, donate: bool = True):
    """Jit a QueryRuntime's step with its keyed state sharded over ``mesh``.

    Returns ``(jitted_step, sharded_state)``. The batch stays replicated in
    this wrapper (scatter-heavy segment reductions into K-sharded state are
    the collective-bound part; replicating the small event batch keeps the
    all-to-all off the hot path). For B-sharded ingestion use
    ``batch_shardings`` explicitly.
    """
    _release_from_fanout(runtime)
    num_keys = runtime.selector_plan.num_keys
    if runtime._state is None:
        runtime._state = runtime._init_state()
    step = runtime.build_step_fn()
    st_sh = state_shardings(runtime._state, mesh, num_keys,
                            win_keys=getattr(runtime, "_win_keys", 1))
    state = jax.device_put(runtime._state, st_sh)
    out_sh = _out_shardings(mesh, st_sh)
    jitted = jax.jit(
        step,
        in_shardings=(st_sh, None, None),
        out_shardings=out_sh,
        donate_argnums=(0,) if donate else (),
    )
    # telemetry: a sharded (re-)jit is a compile event — capacity growth
    # re-invokes this function, and those recompiles must be visible on
    # /metrics (siddhi_jit_compiles_total) before they show up as p99
    tel = getattr(runtime.app_context, "telemetry", None)
    if tel is not None:
        # cache_extra: in_shardings/out_shardings live on the jit
        # wrapper, invisible in the traced program — the mesh string is
        # the witness that keeps distinct layouts from aliasing
        jitted = tel.instrument_jit(
            jitted, f"query.{runtime.name}.sharded_step",
            family="gspmd_replicated_batch", cache_extra=str(mesh))
    # hand the runtime the sharded timeline so junction-fed batches
    # (QueryRuntime.process_batch) and direct jitted() callers share state;
    # remember the mesh so capacity growth re-establishes the sharding
    # (QueryRuntime._ensure_capacity re-invokes this function)
    runtime._state = state
    runtime._step = jitted
    runtime._shard_mesh = mesh
    if hasattr(runtime, "_steps"):
        # NFA runtimes jit one step per input stream (plus a TIMER sweep);
        # clear them so they re-jit with the sharded in_shardings
        runtime._steps.clear()
        runtime._timer_step = None
    return jitted, state


def _out_shardings(mesh: Mesh, st_sh):
    """(state', out) output shardings for a sharded query step: state keeps
    its key-axis sharding; the OUT batch is forced replicated. On one host
    this is what the host pull does anyway; on a multi-process mesh it is
    required — ``jax.device_get`` can only read fully-addressable arrays,
    so a partially-sharded output would strand rows on the other host.
    ``None`` (let XLA choose) when the mesh is single-process: forcing a
    replicate there costs a gather with no benefit."""
    if all(d.process_index == jax.process_index() for d in mesh.devices.flat):
        return None
    return (st_sh, NamedSharding(mesh, P()))


def route_batch_to_shards(cols, n_shards: int, rows_per_shard: int):
    """Host-side all-to-all: scatter batch rows to their owning key shard.

    DEPRECATED — a compatibility shim kept for the legacy
    ``shard_keyed_query_step`` callers. The host router costs ~75% of
    single-shard throughput (BENCH_r05) and requires GK == PK; new code
    should use :func:`device_route_query_step`, which routes rows INSIDE
    the jitted step (dense ``all_to_all`` under ``shard_map``), supports a
    group-by key distinct from the partition key, and re-merges emitted
    rows into the exact unsharded order.

    The owner of dense key ``k`` is ``k % n_shards`` and its local id is
    ``k // n_shards`` — round-robin keeps the keyer's dense ids
    load-balanced across shards. Returns a routed column dict of shape
    ``[n_shards * rows_per_shard]`` where segment ``d`` holds shard ``d``'s
    rows (original order preserved within the shard) padded with invalid
    rows, and the PK/GK columns rewritten to LOCAL ids."""
    import time
    import warnings

    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.core.stream.junction import FatalQueryError
    from siddhi_tpu.ops.expressions import PK_KEY, VALID_KEY

    warnings.warn(
        "route_batch_to_shards is deprecated: use device_route_query_step "
        "(on-device repartitioning; lifts the GK == PK restriction)",
        DeprecationWarning, stacklevel=2)
    t0 = time.perf_counter()
    key_col = PK_KEY if PK_KEY in cols else GK_KEY
    if GK_KEY in cols and PK_KEY in cols and not np.array_equal(
            np.asarray(cols[GK_KEY]), np.asarray(cols[PK_KEY])):
        # a group-by key distinct from the partition key lives in its own
        # dense-id space; rewriting it to partition-local ids would corrupt
        # the selector's group state. The DEVICE router carries the two id
        # spaces separately — use device_route_query_step for distinct GKs.
        raise FatalQueryError(
            "route_batch_to_shards requires GK == PK (partitioned query "
            "without a distinct group-by key) — device_route_query_step "
            "lifts this restriction")
    valid = np.asarray(cols[VALID_KEY])
    keep = np.nonzero(valid)[0]  # capacity padding never competes for rows
    pk = np.asarray(cols[key_col]).astype(np.int64)[keep]
    owner = pk % n_shards
    local = pk // n_shards
    order = np.argsort(owner, kind="stable")
    counts = np.bincount(owner, minlength=n_shards)
    if int(counts.max(initial=0)) > rows_per_shard:
        raise FatalQueryError(
            f"shard overflow: {int(counts.max())} rows for one shard > "
            f"rows_per_shard={rows_per_shard} — raise rows_per_shard or "
            f"split the batch")
    starts = np.zeros(n_shards, np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    owner_sorted = owner[order]
    pos = np.arange(keep.shape[0], dtype=np.int64) - starts[owner_sorted]
    dest = owner_sorted * rows_per_shard + pos
    src = keep[order]

    N = n_shards * rows_per_shard
    routed = {}
    for k, v in cols.items():
        v = np.asarray(v)
        if k in (PK_KEY, GK_KEY):
            buf = np.zeros(N, v.dtype)
            buf[dest] = local[order].astype(v.dtype)
        else:
            buf = np.zeros((N,) + v.shape[1:], v.dtype)
            buf[dest] = v[src]
        routed[k] = buf
    _record_route_telemetry(None, "host", counts,
                            (time.perf_counter() - t0) * 1000.0)
    return routed  # padding rows keep VALID=False (zero-fill)


def shard_keyed_query_step(runtime, mesh: Mesh, rows_per_shard: int):
    """Jit a keyed (partitioned) query step as a ``shard_map`` over ``mesh``
    — zero-collective data parallelism over the key space.

    Contract with ``route_batch_to_shards``: the runtime is sized to its
    PER-SHARD key capacity (``selector_plan.num_keys`` / ``_win_keys`` are
    local values), and batches arrive routed (``[n * rows_per_shard]`` rows
    carrying local key ids). Each device then steps its own
    ``[slots, K_local]`` / ``[K_local * W]`` state over only its own rows;
    the compiled HLO contains NO collective ops (verified by
    ``tools/hlo_audit.py``) — the host router IS the all-to-all, and the
    ICI carries nothing per step. Global-window queries cannot take this
    path (their ring semantics need every row in order); use
    ``shard_query_step`` for those.

    Returns ``(jitted_step, global_state)``. Out rows come back
    shard-segmented (leaf axis 0 = ``n * R_local``); ``"__meta__"`` is
    ``[n, 3]`` — one (overflow, notify, count) row per shard."""
    from jax.experimental.shard_map import shard_map

    _release_from_fanout(runtime)
    n = mesh.devices.size
    localK = runtime.selector_plan.num_keys
    local_win = getattr(runtime, "_win_keys", 1)
    if runtime._state is None:
        runtime._state = runtime._init_state()
    local_state = runtime._state
    step = runtime.build_step_fn()

    axes = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _key_axis_of(path, leaf, localK, local_win),
        local_state)

    def stack_global(leaf, ax):
        arr = np.asarray(leaf)
        if ax < 0:
            # unkeyed leaf: leading device axis — every shard keeps its own
            # independently-evolving copy (squeezed back inside the map)
            return np.stack([arr] * n, axis=0)
        return np.concatenate([arr] * n, axis=ax)

    global_state = jax.tree_util.tree_map(stack_global, local_state, axes)
    st_specs = jax.tree_util.tree_map(
        lambda ax: P(KEY_AXIS) if ax <= 0 else P(*([None] * ax), KEY_AXIS),
        axes)

    def wrapped(state, cols, now):
        state = jax.tree_util.tree_map(
            lambda leaf, ax: leaf[0] if ax < 0 else leaf, state, axes)
        st, out = step(state, cols, now)
        st = jax.tree_util.tree_map(
            lambda leaf, ax: jnp.asarray(leaf)[None] if ax < 0 else leaf,
            st, axes)
        out = {
            k: jnp.asarray(v)[None] if (k == "__meta__" or jnp.ndim(v) == 0)
            else v
            for k, v in out.items()
        }
        return st, out

    sharded = shard_map(
        wrapped, mesh=mesh,
        in_specs=(st_specs, P(KEY_AXIS), P()),
        out_specs=(st_specs, P(KEY_AXIS)),
        check_rep=False,
    )
    jitted = jax.jit(sharded, donate_argnums=(0,))
    tel = getattr(runtime.app_context, "telemetry", None)
    if tel is not None:
        jitted = tel.instrument_jit(
            jitted, f"query.{runtime.name}.shard_map_step",
            family="shard_map_routed", cache_extra=str(mesh))
    state = jax.device_put(global_state, jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), st_specs))
    return jitted, state


def sharded_jit_for(runtime, fn, n_state_args: int = 1, n_plain_args: int = 2):
    """Jit ``fn(state, *plain)`` with the runtime's recorded mesh shardings
    (used by NFAQueryRuntime for per-stream and timer steps)."""
    mesh = runtime._shard_mesh
    st_sh = state_shardings(runtime._state, mesh, runtime.selector_plan.num_keys,
                            win_keys=getattr(runtime, "_win_keys", 1))
    return jax.jit(
        fn,
        in_shardings=(st_sh,) + (None,) * n_plain_args,
        out_shardings=_out_shardings(mesh, st_sh),
        donate_argnums=(0,),
    )


# ---------------------------------------------------------------------------
# Device-side repartitioning (round 6): the host router above moved every
# batch row through numpy before dispatch and hard-required GK == PK. The
# device router below does the same scatter INSIDE the jitted step — the
# unrouted batch enters B-sharded, each shard computes owners on device,
# rows exchange shard-to-shard with one dense all_to_all (or a Pallas TPU
# ring kernel, config-selected), and emitted rows re-merge into the exact
# unsharded emission order on the way out ("Scaling Ordered Stream
# Processing on Shared-Memory Multicores": ordered re-merge over
# out-of-order parallel execution). Two dense id spaces ride each row —
# the partition key (owner = pk % n, local = pk // n) and the group-by key
# (owned by its pk's shard, local ids assigned per shard in allocation
# order via a host-maintained LUT) — which is what lifts GK == PK.
# ---------------------------------------------------------------------------

# plain numpy scalar: a module-level jnp constant would initialize the
# jax backend AT IMPORT TIME and silently break force_host_devices
_ROUTE_BIG = np.int64(2 ** 62)
# registry -> {scope: np[n] last routed rows}. Weak keys: a dead app's
# registry must not pin its arrays forever, and a NEW registry allocated
# at a recycled address must not inherit the old one's "already
# registered" state (id()-keyed caching would do exactly that)
import weakref as _weakref

_ROUTE_ROWS: "_weakref.WeakKeyDictionary" = _weakref.WeakKeyDictionary()


def _record_route_telemetry(telemetry, scope: str, rows, exchange_ms):
    """siddhi_shard_rows{shard} gauges + siddhi_shard_exchange_ms histogram
    — registered on BOTH the legacy host-routed path (process-global
    registry, scope "host") and the device-routed path (app registry,
    scope = query name) so key skew is visible either way."""
    if telemetry is None:
        from siddhi_tpu.observability.telemetry import global_registry

        telemetry = global_registry()
    if exchange_ms is not None:
        telemetry.histogram(f"shard.exchange_ms.{scope}").record(exchange_ms)
    store = _ROUTE_ROWS.setdefault(telemetry, {})
    prev = store.get(scope)
    known = 0 if prev is None else prev.shape[0]
    store[scope] = np.asarray(rows, np.int64)
    # register gauges for any shard indices not seen before — a
    # re-install onto a LARGER mesh must grow the gauge set, not keep
    # reporting only the original shards' skew
    for i in range(known, len(store[scope])):
        telemetry.gauge(
            f"shard.rows.{scope}.{i}",
            lambda s=scope, j=i, st=store: (
                float(st[s][j]) if j < st[s].shape[0] else 0.0))


class RouteLayout:
    """Host-side bookkeeping of one device-routed query: shard count,
    receive capacity, and the group-key local-id LUT that carries a
    distinct GK through the exchange. ``localK``/``local_win`` mirror the
    runtime's (now per-shard) capacity fields; ``n * localK`` is the
    global dense-id capacity the keyer allocates into."""

    def __init__(self, mesh: Mesh, rows_per_shard: int, exchange: str,
                 partitioned: bool, use_lut: bool):
        self.mesh = mesh
        self.n = int(mesh.devices.size)
        self.rows_per_shard = int(rows_per_shard)
        self.quota = max(1, self.rows_per_shard // self.n)
        self.exchange = exchange
        self.partitioned = partitioned
        self.use_lut = use_lut
        self.localK = 1
        self.local_win = 1
        # group-key space: global gk id -> (owner shard, per-shard local id)
        self.gk_owner = np.full(0, -1, np.int32)
        self.gk_local = np.full(0, -1, np.int32)
        self.gk_counts = np.zeros(self.n, np.int64)
        self.gk_known = 0
        self._lut_dev = None      # (lut [Kg], inv [n, localK]) device pair
        self._lut_dirty = True

    # ------------------------------------------------------------- lut sync

    def _resize_gk(self, cap: int):
        if self.gk_owner.shape[0] >= cap:
            return
        grown_o = np.full(cap, -1, np.int32)
        grown_l = np.full(cap, -1, np.int32)
        grown_o[: self.gk_owner.shape[0]] = self.gk_owner
        grown_l[: self.gk_local.shape[0]] = self.gk_local
        self.gk_owner, self.gk_local = grown_o, grown_l

    def sync_gk(self, keyer) -> bool:
        """Assign per-shard local ids to group keys allocated since the
        last sync (allocation order per shard — deterministic given the
        keyer map). Returns True while every shard still fits localK;
        False means a shard overflowed and capacity must grow."""
        if not self.use_lut or keyer is None:
            return True
        total = len(keyer)
        if total <= self.gk_known and not self._lut_dirty:
            return int(self.gk_counts.max(initial=0)) <= self.localK
        self._resize_gk(max(total, self.n * self.localK))
        if total > self.gk_known:
            fresh = sorted(
                ((gid, key) for key, gid in keyer._map.items()
                 if gid >= self.gk_known))
            for gid, key in fresh:
                owner = int(key[0]) % self.n   # composite keys lead with pk
                self.gk_owner[gid] = owner
                self.gk_local[gid] = self.gk_counts[owner]
                self.gk_counts[owner] += 1
            self.gk_known = total
            self._lut_dirty = True
        return int(self.gk_counts.max(initial=0)) <= self.localK

    def rebuild_gk(self, keyer):
        """Full LUT rebuild (restore / capacity growth): local ids are a
        pure function of the keyer map, so rebuilding is always safe."""
        self.gk_owner = np.full(0, -1, np.int32)
        self.gk_local = np.full(0, -1, np.int32)
        self.gk_counts = np.zeros(self.n, np.int64)
        self.gk_known = 0
        self._lut_dirty = True
        return self.sync_gk(keyer)

    def device_luts(self):
        """(lut, inv) device pair, replicated over the mesh; refreshed
        only when the host LUT changed (steady state: zero transfers)."""
        if self._lut_dev is not None and not self._lut_dirty:
            return self._lut_dev
        Kg = self.n * self.localK
        if self.use_lut:
            self._resize_gk(Kg)
            lut = np.where(self.gk_local[:Kg] >= 0,
                           self.gk_local[:Kg], 0).astype(np.int32)
            inv = np.zeros((self.n, self.localK), np.int32)
            alloc = np.nonzero(self.gk_local[:Kg] >= 0)[0]
            inv[self.gk_owner[alloc], self.gk_local[alloc]] = alloc
        else:
            lut = np.zeros(1, np.int32)
            inv = np.zeros((self.n, 1), np.int32)
        rep = NamedSharding(self.mesh, P())
        self._lut_dev = (jax.device_put(lut, rep), jax.device_put(inv, rep))
        self._lut_dirty = False
        return self._lut_dev

    # --------------------------------------------------------- permutations

    def pk_positions(self, local: int) -> np.ndarray:
        """Routed row of global pk id g in a [n * local] key space."""
        g = np.arange(self.n * local, dtype=np.int64)
        return (g % self.n) * local + g // self.n

    def gk_positions(self) -> np.ndarray:
        """Routed row of global gk id g (bijective over [n * localK]):
        allocated ids sit at (owner, local); unallocated ids — and ids
        whose per-shard local slot exceeds localK (allocated this batch,
        about to trigger growth; they never owned a state row yet) — fill
        the remaining all-init rows in order."""
        Kg = self.n * self.localK
        if not self.use_lut:
            return self.pk_positions(self.localK)
        self._resize_gk(Kg)
        pos = np.full(Kg, -1, np.int64)
        placed = np.nonzero(
            (self.gk_local[:Kg] >= 0) & (self.gk_local[:Kg] < self.localK))[0]
        pos[placed] = (self.gk_owner[placed].astype(np.int64) * self.localK
                       + self.gk_local[placed])
        free = np.setdiff1d(np.arange(Kg), pos[placed], assume_unique=False)
        pos[pos < 0] = free
        return pos

    def gk_inverse_values(self) -> np.ndarray:
        """[n, localK] local gk id -> global gk id (0 where unallocated;
        ids allocated past localK — pending growth, no state row yet —
        are simply not placed)."""
        inv = np.zeros((self.n, self.localK), np.int64)
        Kg = self.n * self.localK
        self._resize_gk(Kg)
        placed = np.nonzero(
            (self.gk_local[:Kg] >= 0) & (self.gk_local[:Kg] < self.localK))[0]
        inv[self.gk_owner[placed], self.gk_local[placed]] = placed
        return inv


def route_ineligibility(runtime) -> Optional[str]:
    """Why this runtime cannot take the device-routed path (None = it
    can, else a ``core.eligibility.Reason`` — free text with a stable
    machine-readable ``.code``). v1 scope: single-stream partitioned
    queries over device keyed length windows (or no window at all), and
    non-partitioned grouped aggregations without a window. Time-driven
    windows keep the legacy paths until their emission-order keys are
    made global-aware."""
    from siddhi_tpu.core.eligibility import ReasonCode as RC
    from siddhi_tpu.core.eligibility import reason
    from siddhi_tpu.ops.keyed_windows import KeyedLengthWindowStage

    if getattr(runtime, "sides", None) is not None:
        return _join_route_ineligibility(runtime)
    if hasattr(runtime, "_steps"):
        return reason(RC.NFA_QUERY, "pattern/sequence (NFA) queries")
    if runtime.host_window is not None:
        return reason(RC.HOST_WINDOW, "host-mode windows")
    sp = runtime.selector_plan
    if sp.order_by or sp.limit is not None or sp.offset is not None:
        return reason(RC.ORDER_LIMIT,
                      "order by / limit (batch-global ordering)")
    win = runtime.window_stage
    if win is not None and not isinstance(win, KeyedLengthWindowStage):
        return reason(RC.WINDOW_NOT_GLOBAL_AWARE,
                      f"window stage {type(win).__name__} (emission-order "
                      f"keys not global-aware yet)")
    if win is not None and runtime.partition_ctx is None:
        return reason(RC.GLOBAL_WINDOW, "global (non-partitioned) windows")
    if runtime.partition_ctx is None and runtime.keyer is None:
        return reason(RC.UNKEYED, "unkeyed queries (nothing to route by)")
    if runtime.carried_pk:
        return reason(RC.INNER_PARTITION_STREAM,
                      "inner partition '#stream' inputs")
    return None


def _join_route_ineligibility(runtime) -> Optional[str]:
    """Why a JOIN runtime cannot take the device-routed path (None = it
    can). v1 scope: partitioned keyed-length-window stream-stream joins —
    both sides' keyed rings route by the partition key through the same
    exchange, probes stay partition-local by construction (a key's whole
    ring lives on its owner shard), and the join step's emission-order
    keys (trigger okey stridden by the probe width) re-merge exactly."""
    from siddhi_tpu.core.eligibility import ReasonCode as RC
    from siddhi_tpu.core.eligibility import reason
    from siddhi_tpu.ops.keyed_windows import KeyedLengthWindowStage

    if runtime.partition_ctx is None:
        return reason(RC.JOIN_UNPARTITIONED,
                      "non-partitioned joins (nothing to route by)")
    if runtime.keyer is not None:
        return reason(RC.GROUPED_SELECT,
                      "grouped join selectors (host keyed select between "
                      "stages)")
    sp = runtime.selector_plan
    if sp.order_by or sp.limit is not None or sp.offset is not None:
        return reason(RC.ORDER_LIMIT,
                      "join order by / limit (batch-global ordering)")
    if runtime.index_probe is not None:
        return reason(RC.INDEXED_PROBE, "indexed join probes")
    for side in runtime.sides.values():
        if side.store is not None or side.host_window is not None:
            return reason(RC.STORE_SIDE,
                          f"shared-store/host-window join side "
                          f"'{side.stream_id}'")
        if side.global_side:
            return reason(RC.GLOBAL_SIDE,
                          "global (non-partitioned) join sides")
        if not isinstance(side.window_stage, KeyedLengthWindowStage):
            return reason(RC.WINDOW_NOT_GLOBAL_AWARE,
                          f"join window stage "
                          f"{type(side.window_stage).__name__} "
                          f"(emission-order keys not global-aware yet)")
    return None


def device_route_query_step(runtime, mesh: Mesh, rows_per_shard: int = 4096,
                            exchange: Optional[str] = None):
    """Install on-device repartitioning for a keyed query: the runtime's
    step becomes a ``shard_map`` whose body (1) computes each row's owner
    shard from its key on device, (2) exchanges rows shard-to-shard with a
    dense ``jax.lax.all_to_all`` (``exchange="pallas_ring"`` selects the
    TPU ring kernel; inert on CPU fallback), (3) rewrites the partition-
    and group-key columns into their per-shard local id spaces (distinct
    spaces — GK == PK is no longer required), (4) steps the shard's local
    state, and (5) re-merges emitted rows across shards by their global
    emission-order keys, so sharded output is bit-identical to unsharded.

    ``rows_per_shard`` bounds each shard's per-batch receive capacity;
    the host pre-checks per-pair quotas and SPLITS oversized batches
    (``prepare_routed_batches``) instead of dying, and the device-side
    overflow flag (rows beyond quota) surfaces as ``FatalQueryError``
    naming ``rows_per_shard``.

    Returns ``(step3, state)`` where ``step3(state, cols, now)`` is also
    installed as ``runtime._step`` so junction-fed batches take the
    routed path (CompletionPump-eligible: the merged meta keeps the
    ``[overflow, notify, count]`` prefix)."""
    from siddhi_tpu.ops.expressions import CompileError

    why = route_ineligibility(runtime)
    if why is not None:
        raise CompileError(
            f"query '{runtime.name}': device routing does not support "
            f"{why} — use shard_query_step for those")
    _release_from_fanout(runtime)
    n = int(mesh.devices.size)
    if exchange is None:
        exchange = getattr(runtime.app_context, "shard_exchange",
                           "all_to_all")
    if exchange == "pallas_ring" and not _tpu_backend():
        exchange = "all_to_all"   # Pallas ring is TPU-only; inert on CPU
    partitioned = runtime.partition_ctx is not None
    use_lut = partitioned and runtime.keyer is not None

    # current (global/canonical) capacities and state
    if runtime._route_layout is not None:
        canonical = canonical_route_state(runtime)
        old = runtime._route_layout
        Kg = old.n * old.localK
        Wg = old.n * old.local_win if old.local_win > 1 else runtime._win_keys
    else:
        Kg = runtime.selector_plan.num_keys
        Wg = runtime._win_keys
        canonical = None
        if runtime._state is not None:
            canonical = jax.tree_util.tree_map(
                np.asarray, jax.device_get(runtime._state))

    layout = RouteLayout(mesh, rows_per_shard, exchange, partitioned, use_lut)
    _install_routed(runtime, layout, canonical, Kg, Wg)
    return runtime._step, runtime._state


def _tpu_backend() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no backend yet
        return False


def _install_routed(runtime, layout: RouteLayout, canonical, Kg: int, Wg: int):
    """Shared tail of install / capacity growth / snapshot adoption: size
    the per-shard capacities, (re)build the GK LUT, lay the canonical
    state out shard-major, and jit the routed step."""
    n = layout.n
    Kg = max(int(Kg), n)
    # floor 16 (the engine's minimum key capacity): a tiny localK would
    # collide with aggregator slot counts in _key_axis_of's size-match
    # heuristic ([slots, K] with slots == K is ambiguous)
    layout.localK = max(16, _pow2_div(Kg, n))
    if layout.partitioned:
        Wg = max(int(Wg), n)
        layout.local_win = max(16, _pow2_div(Wg, n))
    else:
        layout.local_win = 1
    # per-shard GK pressure can exceed localK under key skew even when the
    # global count fits — grow until the worst shard fits
    layout.rebuild_gk(runtime.keyer)
    while int(layout.gk_counts.max(initial=0)) > layout.localK:
        layout.localK *= 2
        layout._lut_dirty = True
    runtime.selector_plan.num_keys = layout.localK
    runtime._win_keys = layout.local_win
    runtime._route_layout = layout
    runtime._shard_mesh = layout.mesh
    # meta layout changed: drop the cached drain-side instrument spec
    runtime._instr_spec = None

    state = _canonical_to_routed(runtime, layout, canonical)
    if n > 1:
        axes = _routed_axes(runtime, layout, state)
        st_specs = jax.tree_util.tree_map(
            lambda ax: P(KEY_AXIS) if ax <= 0 else P(*([None] * ax), KEY_AXIS),
            axes)
        state = jax.device_put(state, jax.tree_util.tree_map(
            lambda spec: NamedSharding(layout.mesh, spec), st_specs))
    else:
        state = jax.device_put(state)
    runtime._state = state
    if getattr(runtime, "sides", None) is not None:
        # joins jit one routed step PER SIDE, lazily — the side steps are
        # rebuilt on demand by process_side_batch (routed_step_for with
        # side_key); a stale _steps cache would run the old capacities
        runtime._step = None
        runtime._steps.clear()
    else:
        runtime._step = routed_step_for(runtime)


def _pow2_div(total: int, n: int) -> int:
    """total/n rounded up to the next power of two (total, n both pow2 in
    practice; stays exact then)."""
    k = 1
    need = (total + n - 1) // n
    while k < need:
        k *= 2
    return k


def _routed_axes(runtime, layout: RouteLayout, state):
    """Key-axis index per leaf of the GLOBAL routed state (shard-major
    layout, leaf sizes n*localK / n*local_win*W); -1 = unkeyed (stacked
    with a leading device axis)."""
    Kg = layout.n * layout.localK
    Wgk = layout.n * layout.local_win if layout.local_win > 1 else 1
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _key_axis_of(path, leaf, Kg, Wgk), state)


# -------------------------------------------------------- state relayout

def _leaf_space(path) -> str:
    top = path[0].key if path and hasattr(path[0], "key") else None
    return "gk" if top == "sel" else "pk"


def _buffered_id_col(path) -> Optional[str]:
    """'__gk__'/'__pk__' when this window-buffer leaf stores key ids whose
    VALUES must translate between local and global spaces."""
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import PK_KEY

    top = path[0].key if path and hasattr(path[0], "key") else None
    tail = path[-1].key if path and hasattr(path[-1], "key") else None
    if top in ("win", "lwin", "rwin") and tail in (GK_KEY, PK_KEY):
        return "gk" if tail == GK_KEY else "pk"
    return None


def canonical_route_state(runtime):
    """Routed (shard-major) state -> canonical unsharded layout, host-side
    numpy. Snapshots store THIS, so revisions cross-restore between any
    routed layouts (2/4/8 shards) and the unsharded runtime."""
    layout = runtime._route_layout
    state = jax.tree_util.tree_map(np.asarray, jax.device_get(runtime._state))
    n, Kl, Wl = layout.n, layout.localK, layout.local_win
    pos_gk = layout.gk_positions()
    inv_gk_vals = layout.gk_inverse_values() if layout.use_lut else None

    def one(path, leaf):
        axes = _key_axis_of(path, leaf, n * Kl, n * Wl if Wl > 1 else 1)
        if axes < 0:
            return leaf[0] if leaf.ndim else leaf   # stacked unkeyed copy
        leaf = np.asarray(leaf)
        idcol = _buffered_id_col(path)
        if idcol is not None:
            # translate buffered LOCAL key ids to global before the rows
            # move: ring rows of shard s live in block s of the flat ring.
            # Without a LUT (no distinct group-by) the gk space IS the pk
            # space, so both translate by the round-robin formula.
            per_shard = leaf.shape[0] // n
            out = leaf.copy()
            for s in range(n):
                blk = out[s * per_shard:(s + 1) * per_shard]
                if idcol == "pk" or inv_gk_vals is None:
                    out[s * per_shard:(s + 1) * per_shard] = blk * n + s
                else:
                    safe = np.clip(blk.astype(np.int64), 0, Kl - 1)
                    out[s * per_shard:(s + 1) * per_shard] = (
                        inv_gk_vals[s][safe].astype(leaf.dtype))
            leaf = out
        if _leaf_space(path) == "gk":
            return np.take(leaf, pos_gk, axis=axes)
        keys = n * Wl
        W = leaf.shape[0] // keys
        pos = layout.pk_positions(Wl)
        rows = (pos[:, None] * W + np.arange(W)[None, :]).reshape(-1)
        return leaf[rows]

    return jax.tree_util.tree_map_with_path(one, state)


def _canonical_to_routed(runtime, layout: RouteLayout, canonical):
    """Canonical state (possibly smaller-capacity) -> routed shard-major
    layout at the layout's capacities; missing key rows come from init."""
    n, Kl, Wl = layout.n, layout.localK, layout.local_win
    # routed init: per-shard local inits concatenated shard-major
    local_init = jax.tree_util.tree_map(np.asarray, runtime._init_state())
    axes_local = jax.tree_util.tree_map_with_path(
        lambda path, leaf: _key_axis_of(path, leaf, Kl,
                                        Wl if Wl > 1 else 1), local_init)

    def stack(leaf, ax):
        arr = np.asarray(leaf)
        if ax < 0:
            return np.stack([arr] * n, axis=0)
        return np.concatenate([arr] * n, axis=ax)

    routed = jax.tree_util.tree_map(stack, local_init, axes_local)
    if canonical is None:
        return routed
    pos_gk = layout.gk_positions()
    if layout.use_lut:
        layout._resize_gk(n * Kl)

    def one(path, routed_leaf, canon_leaf):
        ax = _key_axis_of(path, routed_leaf, n * Kl, n * Wl if Wl > 1 else 1)
        if ax < 0:
            base = np.asarray(canon_leaf)
            return np.stack([base] * n, axis=0)
        canon_leaf = np.asarray(canon_leaf)
        out = np.asarray(routed_leaf).copy()
        if _leaf_space(path) == "gk":
            # source capacity comes from the canonical leaf itself (it may
            # be a smaller snapshot/pre-growth layout)
            g = np.arange(min(canon_leaf.shape[ax], n * Kl))
            if layout.use_lut:
                # only groups ALIVE in the (rebuilt-from-keyer) LUT carry
                # their canonical rows over. Purged gids are absent from
                # the keyer map, so the rebuild compacts local ids — and
                # the freed slots are exactly what new groups allocate
                # next; copying a purged group's stale aggregates there
                # would seed new groups with dead state (verified bug).
                # Dropped rows fall back to init, like the unsharded
                # engine's "purged rows become unreachable" rule.
                g = g[layout.gk_local[g] >= 0]
            sl_dst = [slice(None)] * out.ndim
            sl_src = [slice(None)] * out.ndim
            sl_dst[ax] = pos_gk[g]
            sl_src[ax] = g
            out[tuple(sl_dst)] = canon_leaf[tuple(sl_src)]
            return out
        keys = n * Wl
        W = out.shape[0] // keys
        pos = layout.pk_positions(Wl)
        g = np.arange(min(canon_leaf.shape[0] // max(W, 1), keys))
        rows_dst = (pos[g][:, None] * W + np.arange(W)[None, :]).reshape(-1)
        rows_src = (g[:, None] * W + np.arange(W)[None, :]).reshape(-1)
        out[rows_dst] = canon_leaf[rows_src]
        idcol = _buffered_id_col(path)
        if idcol is not None:
            # translate buffered GLOBAL key ids to this layout's locals
            # (without a LUT the gk space IS the pk space — formula)
            per_shard = out.shape[0] // n
            for s in range(n):
                blk = out[s * per_shard:(s + 1) * per_shard]
                if idcol == "pk" or not layout.use_lut:
                    out[s * per_shard:(s + 1) * per_shard] = (
                        blk.astype(np.int64) // n).astype(out.dtype)
                else:
                    lut_g = np.where(
                        layout.gk_local[: n * Kl] >= 0,
                        layout.gk_local[: n * Kl], 0).astype(np.int64)
                    safe = np.clip(blk.astype(np.int64), 0, len(lut_g) - 1)
                    out[s * per_shard:(s + 1) * per_shard] = (
                        lut_g[safe].astype(out.dtype))
        return out

    return jax.tree_util.tree_map_with_path(one, routed, canonical)


# ----------------------------------------------------------- routed step

def routed_step_for(runtime, side_key: Optional[str] = None):
    """Build (and return) the device-routed ``step3(state, cols, now)``
    for a runtime whose ``_route_layout`` is installed. ``side_key``
    selects one side of a JOIN runtime (the side's fused insert+probe
    step routes like any keyed step: both sides' rings are sharded by the
    partition key, so a routed row's probe surface — the other side's
    ring rows of ITS OWN key — is already local to its owner shard).
    The heavy lifting happens in one jitted ``shard_map``:

    ingress   rows enter B-sharded; each shard computes ``owner = key % n``
              for its slice, buckets rows per destination (per-pair quota
              ``rows_per_shard // n``; over-quota rows are counted, not
              silently dropped), and one dense ``all_to_all`` moves every
              bucket to its owner. Received rows arrive source-major, i.e.
              in original batch order.
    local     PK/GK columns are rewritten to per-shard local ids (PK by
              ``// n``; GK through the replicated LUT — distinct id
              spaces, so GK != PK is fine) and the shard steps its local
              ``[.., K/n]`` state.
    egress    the window/selector's emission-order key (``__okey__``,
              derived from the pre-exchange global row index) rides out;
              shards ``all_gather`` their emitted rows and sort once by
              okey — the ordered re-merge that makes sharded output
              bit-identical to the unsharded run. The packed meta becomes
              ``[overflow, notify, count, route_overflow, rows_0..n-1]``
              (prefix-compatible with the unsharded ``[3]`` contract)."""
    from jax.experimental.shard_map import shard_map

    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import (
        OKEY_KEY, PK_KEY, RIDX_KEY, VALID_KEY)

    layout = runtime._route_layout
    n, Q = layout.n, layout.quota
    localK = layout.localK
    partitioned, use_lut = layout.partitioned, layout.use_lut
    # device instruments (observability/instruments.py): the inner step
    # appends its own slot lanes; the route wrapper adds the exchange
    # residual and aggregates the inner lanes across shards per each
    # slot's declared reduction. Captured at BUILD so the compiled meta
    # layout matches runtime.instrument_slots() exactly.
    ins_on = runtime._instruments_on()
    inner_slots = runtime._step_instrument_slots()
    if side_key is not None:
        side_step = runtime.build_side_step_fn(side_key)
        _ph = jnp.zeros((1,), bool)

        def step(state, cols, now):
            # probe placeholders are inert: both probe surfaces live
            # inside the sharded state (keyed rings)
            return side_step(state, {}, _ph, cols, now)
    else:
        step = runtime.build_step_fn()
    key_name = PK_KEY if partitioned else GK_KEY

    if n == 1:
        def one_dev(state, cols, luts, now):
            cols = dict(cols)
            B = cols[VALID_KEY].shape[0]
            cols[RIDX_KEY] = jnp.arange(B, dtype=jnp.int64)
            rows = jnp.sum(cols[VALID_KEY], dtype=jnp.int64)
            st, out = step(state, cols, now)
            out = dict(out)
            meta = out.pop("__meta__")
            out.pop(OKEY_KEY, None)   # single shard: already in order
            parts = [meta[:3], jnp.zeros(1, jnp.int64), rows[None]]
            if ins_on:
                parts.append(jnp.full((1,), n * Q, jnp.int64) - rows[None])
            parts.append(meta[3:])    # inner step's instrument lanes
            out["__meta__"] = jnp.concatenate(parts)
            return st, out

        jitted = jax.jit(one_dev, donate_argnums=(0,))
        return _finish_routed_install(runtime, layout, jitted, side_key)

    axes = _routed_axes(runtime, layout, runtime._state)
    st_specs = jax.tree_util.tree_map(
        lambda ax: P(KEY_AXIS) if ax <= 0 else P(*([None] * ax), KEY_AXIS),
        axes)
    if layout.exchange == "pallas_ring":
        exchange = lambda buf: _pallas_ring_exchange(buf, n)  # noqa: E731
    else:
        exchange = lambda buf: jax.lax.all_to_all(  # noqa: E731
            buf, KEY_AXIS, split_axis=0, concat_axis=0, tiled=True)

    def wrapped(state, cols, luts, now):
        state = jax.tree_util.tree_map(
            lambda leaf, ax: leaf[0] if ax < 0 else leaf, state, axes)
        me = jax.lax.axis_index(KEY_AXIS)
        valid = cols[VALID_KEY]
        Bl = valid.shape[0]
        ridx = me.astype(jnp.int64) * Bl + jnp.arange(Bl, dtype=jnp.int64)
        # owner shard per local row (invalid rows route nowhere)
        owner = jnp.where(valid, cols[key_name].astype(jnp.int64) % n,
                          jnp.int64(n))
        dest = jnp.arange(n, dtype=jnp.int64)[:, None]
        maskd = owner[None, :] == dest                        # [n, Bl]
        pos = jnp.cumsum(maskd.astype(jnp.int64), axis=1) - 1
        # per-ROW slot: each row has exactly one destination, so every
        # column scatters once at [Bl] cost (an [n*Bl] broadcast-scatter
        # here would n-fold the hot loop's scatter bandwidth)
        owner_c = jnp.clip(owner, 0, n - 1).astype(jnp.int32)
        pos_row = jnp.take_along_axis(pos, owner_c[None, :], axis=0)[0]
        sendable = owner < n                                  # valid rows
        sent_row = sendable & (pos_row < Q)
        route_ov = jnp.sum((sendable & ~sent_row).astype(jnp.int64))
        slot_row = jnp.where(sent_row, owner * Q + pos_row, jnp.int64(n * Q))

        def exch(col):
            buf = jnp.zeros((n * Q,) + col.shape[1:], col.dtype)
            buf = buf.at[slot_row].set(col, mode="drop")
            return exchange(buf)

        rcols = {k: exch(v) for k, v in cols.items()}
        rcols[RIDX_KEY] = exch(ridx)
        rows_here = jnp.sum(rcols[VALID_KEY], dtype=jnp.int64)
        # global -> per-shard local ids (two separate dense spaces)
        if partitioned:
            pk = rcols[PK_KEY]
            rcols[PK_KEY] = (pk.astype(jnp.int64) // n).astype(pk.dtype)
        gk = rcols[GK_KEY]
        if use_lut:
            lut = luts[0]
            gl = lut[jnp.clip(gk.astype(jnp.int64), 0, lut.shape[0] - 1)]
            gl = jnp.clip(gl, 0, localK - 1)
        else:
            gl = gk.astype(jnp.int64) // n
        rcols[GK_KEY] = gl.astype(gk.dtype)

        st, out = step(state, rcols, now)
        out = dict(out)
        meta = out.pop("__meta__")
        okey = jnp.asarray(out.pop(OKEY_KEY), jnp.int64)
        valid_o = out[VALID_KEY]
        okey = jnp.where(valid_o, okey, _ROUTE_BIG)
        # local -> global ids on the emitted rows
        if partitioned and PK_KEY in out:
            pko = out[PK_KEY]
            out[PK_KEY] = (pko.astype(jnp.int64) * n
                           + me.astype(jnp.int64)).astype(pko.dtype)
        if GK_KEY in out:
            gko = out[GK_KEY]
            if use_lut:
                inv = luts[1]
                gg = inv[me, jnp.clip(gko.astype(jnp.int64), 0, localK - 1)]
            else:
                gg = gko.astype(jnp.int64) * n + me.astype(jnp.int64)
            out[GK_KEY] = gg.astype(gko.dtype)
        # ordered re-merge: gather every shard's emitted rows and sort
        # once by the global emission-order key (invalid rows sort last,
        # exactly like _order_emit does within one step)
        okg = jax.lax.all_gather(okey, KEY_AXIS, axis=0, tiled=True)
        order = jnp.argsort(okg, stable=True)
        merged = {
            k: jax.lax.all_gather(v, KEY_AXIS, axis=0, tiled=True)[order]
            for k, v in out.items()
        }
        ov = jax.lax.psum(meta[0], KEY_AXIS)
        ntb = jnp.where(meta[1] < 0, _ROUTE_BIG, meta[1])
        nt = jax.lax.pmin(ntb, KEY_AXIS)
        nt = jnp.where(nt >= _ROUTE_BIG, jnp.int64(-1), nt)
        cnt = jax.lax.psum(meta[2], KEY_AXIS)
        rov = jax.lax.psum(route_ov, KEY_AXIS)
        rows = jax.lax.all_gather(rows_here, KEY_AXIS)
        parts = [jnp.stack([ov, nt, cnt, rov]), rows.astype(jnp.int64)]
        if ins_on:
            # exchange residual: receive capacity left on the FULLEST
            # shard this batch (0 = one more skewed batch overflows)
            parts.append(jnp.full((1,), n * Q, jnp.int64)
                         - jax.lax.pmax(rows_here, KEY_AXIS)[None])
        # inner step's instrument lanes, aggregated per declared reduce
        # (sum for shard-owned counts, max for fill levels)
        lane = 3
        for slot in inner_slots:
            v = meta[lane:lane + slot.width]
            lane += slot.width
            parts.append(jax.lax.pmax(v, KEY_AXIS) if slot.reduce == "max"
                         else jax.lax.psum(v, KEY_AXIS))
        merged["__meta__"] = jnp.concatenate(parts)
        st = jax.tree_util.tree_map(
            lambda leaf, ax: jnp.asarray(leaf)[None] if ax < 0 else leaf,
            st, axes)
        return st, merged

    sharded = shard_map(
        wrapped, mesh=layout.mesh,
        in_specs=(st_specs, P(KEY_AXIS), P(), P()),
        out_specs=(st_specs, P()),
        check_rep=False,
    )
    jitted = jax.jit(sharded, donate_argnums=(0,))
    return _finish_routed_install(runtime, layout, jitted, side_key)


def _finish_routed_install(runtime, layout: RouteLayout, jitted,
                           side_key: Optional[str] = None):
    key = f"query.{runtime.name}.routed_step" + (
        f".{side_key}" if side_key else "")
    tel = getattr(runtime.app_context, "telemetry", None)
    if tel is not None:
        jitted = tel.instrument_jit(
            jitted, key,
            family="device_routed" + (f".{side_key}" if side_key else ""),
            cache_extra=str(layout.mesh))

    def step3(state, cols, now):
        return jitted(state, cols, layout.device_luts(), now)

    step3._key = key
    step3._routed_raw = jitted    # hlo_audit lowers through this
    step3._layout = layout
    return step3


def prepare_routed_batches(runtime, cols):
    """Host side of the device-routed dispatch: pad the batch to a
    multiple of the shard count, pre-check the per-(src, dst) exchange
    quotas, and SPLIT oversized batches in half until every piece fits —
    feasible splitting replaces the old router's hard ``shard overflow``
    death. Also records the shard-skew gauges and the (now tiny)
    host-side exchange-prep histogram. Returns a list of column dicts to
    dispatch in order."""
    import time as _time

    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import PK_KEY, VALID_KEY

    layout = runtime._route_layout
    t0 = _time.perf_counter()
    n, quota = layout.n, layout.quota
    cols = {k: np.asarray(v) for k, v in dict(cols).items()}
    key_name = PK_KEY if layout.partitioned else GK_KEY

    def pad_to_mult(c):
        B = c[VALID_KEY].shape[0]
        if B % n == 0:
            return c
        pad = n - B % n
        return {k: np.concatenate(
            [v, np.zeros((pad,) + v.shape[1:], v.dtype)]) for k, v in c.items()}

    pieces = []

    def emit(c):
        c = pad_to_mult(c)
        B = c[VALID_KEY].shape[0]
        Bl = B // n
        valid = c[VALID_KEY].astype(bool)
        key = c[key_name].astype(np.int64)
        src = np.arange(B) // Bl
        pair = (src * n + key % n)[valid]
        counts = np.bincount(pair, minlength=n * n)
        if int(counts.max(initial=0)) <= quota or B <= n:
            pieces.append(c)
            return
        half = max((B // 2 // n) * n, n)
        emit({k: v[:half] for k, v in c.items()})
        emit({k: v[half:] for k, v in c.items()})

    emit(cols)
    dest_rows = np.bincount(
        (np.asarray(cols[key_name], np.int64) % n)[
            np.asarray(cols[VALID_KEY], bool)], minlength=n)
    _record_route_telemetry(
        getattr(runtime.app_context, "telemetry", None), runtime.name,
        dest_rows, (_time.perf_counter() - t0) * 1000.0)
    return pieces


def ensure_routed_capacity(runtime) -> None:
    """Routed analog of ``QueryRuntime._ensure_capacity``: grow per-shard
    capacities when the GLOBAL key population outgrows ``n * localK`` /
    ``n * local_win`` — or when key skew overfills one shard's slice of
    the group-key space — re-laying the live state out via its canonical
    form."""
    layout = runtime._route_layout
    n = layout.n
    needed_sel = runtime._needed_sel_keys()
    needed_win = (runtime.partition_ctx.num_keys()
                  if runtime.partition_ctx is not None else 1)
    fits = layout.sync_gk(runtime.keyer)
    grow_sel = needed_sel > n * layout.localK or not fits
    grow_win = layout.partitioned and needed_win > n * layout.local_win
    if not (grow_sel or grow_win):
        return
    canonical = (canonical_route_state(runtime)
                 if runtime._state is not None else None)
    Kg = n * layout.localK
    while needed_sel > Kg:
        Kg *= 2
    Wg = n * layout.local_win if layout.partitioned else 1
    while layout.partitioned and needed_win > Wg:
        Wg *= 2
    overloaded = getattr(runtime.app_context, "overload", None) is not None
    if overloaded and canonical is not None:
        # device-memory budget gate (resilience/overload.py): routed
        # growth re-lays the whole state out at the grown global
        # capacity — deny BEFORE allocating n shards' worth of it
        from siddhi_tpu.core.util.statistics import pytree_nbytes
        from siddhi_tpu.resilience.overload import ensure_memory_budget

        ratio = max(Kg / max(n * layout.localK, 1),
                    (Wg / max(n * layout.local_win, 1)
                     if layout.partitioned else 1.0))
        ensure_memory_budget(
            runtime.app_context, f"query.{runtime.name}",
            int(pytree_nbytes(canonical) * ratio),
            what=f"query '{runtime.name}' routed key-capacity growth "
                 f"({n * layout.localK}->{Kg} global keys)")
    _install_routed(runtime, layout, canonical, Kg, Wg)
    if overloaded:
        from siddhi_tpu.core.util.statistics import pytree_nbytes
        from siddhi_tpu.resilience.overload import charge_memory

        charge_memory(runtime.app_context, f"query.{runtime.name}",
                      pytree_nbytes(runtime._state))


def adopt_canonical(runtime, sel_keys_g: int, win_keys_g: int) -> None:
    """Snapshot-restore hook: ``runtime._state`` currently holds CANONICAL
    state at the snapshot's global capacities (snapshots of routed
    runtimes are captured canonical — see ``canonical_route_state``);
    re-derive this runtime's shard-major layout from it. Works for any
    source layout: unsharded, or routed at a different shard count."""
    layout = runtime._route_layout
    canonical = None
    if runtime._state is not None:
        canonical = jax.tree_util.tree_map(
            np.asarray, jax.device_get(runtime._state))
    _install_routed(runtime, layout, canonical, sel_keys_g, win_keys_g)


# ------------------------------------------------- Pallas TPU ring kernel

def _pallas_ring_exchange(buf, n: int):
    """All-to-all of ``buf`` ([n * Q, ...]: segment d goes to shard d) via
    direct async remote copies (SNIPPETS.md [2] pattern:
    ``pltpu.make_async_remote_copy`` under ``shard_map``). TPU-only —
    selected by ``shard_exchange = "pallas_ring"`` and silently replaced
    by ``lax.all_to_all`` on CPU fallback (``device_route_query_step``).
    Each shard pushes segment d straight to shard d's receive buffer at
    segment ``me`` (received rows stay source-major, matching the dense
    all_to_all layout); ``wait()`` on every descriptor covers both the
    local sends and the n-1 expected arrivals, whose semaphore slots line
    up because transfer sizes are uniform."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_dtype = buf.dtype
    if orig_dtype == jnp.bool_:
        buf = buf.astype(jnp.int8)   # DMA-friendly lane type

    def kernel(x_ref, out_ref, send_sems, recv_sems):
        me = jax.lax.axis_index(KEY_AXIS)
        Q = x_ref.shape[0] // n
        out_ref[pl.ds(me * Q, Q)] = x_ref[pl.ds(me * Q, Q)]
        descs = []
        for hop in range(1, n):
            dst = jax.lax.rem(me + hop, n)
            d = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[pl.ds(dst * Q, Q)],
                dst_ref=out_ref.at[pl.ds(me * Q, Q)],
                send_sem=send_sems.at[hop - 1],
                recv_sem=recv_sems.at[hop - 1],
                device_id=(dst,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            d.start()
            descs.append(d)
        for d in descs:
            d.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
                        pltpu.SemaphoreType.DMA((max(n - 1, 1),))],
    )
    out = pl.pallas_call(
        functools.partial(kernel),
        out_shape=jax.ShapeDtypeStruct(buf.shape, buf.dtype),
        grid_spec=grid_spec,
    )(buf)
    if orig_dtype == jnp.bool_:
        out = out.astype(jnp.bool_)
    return out
