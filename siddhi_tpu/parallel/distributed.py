"""Multi-host initialization: the DCN-facing half of the comm backend.

The reference scales out with NCCL/MPI-style transports; the TPU-native
equivalent is ``jax.distributed``: every host runs the same program,
``initialize_cluster`` joins them into one JAX process group, and
``global_mesh`` spans EVERY host's devices in one 1-D key mesh. The same
``NamedSharding``s used single-host (``parallel/mesh.py``) then shard key
state across hosts — XLA routes collectives over ICI within a slice and
DCN across slices; nothing else in the framework changes.

Usage (identical program on each host)::

    from siddhi_tpu.parallel.distributed import initialize_cluster, global_mesh
    initialize_cluster(coordinator_address="host0:8476",
                       num_processes=4, process_id=HOST_RANK)
    mesh = global_mesh()
    shard_query_step(runtime, mesh)
"""

from __future__ import annotations

from typing import Optional

from siddhi_tpu.parallel.mesh import KEY_AXIS


def initialize_cluster(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       max_missing_heartbeats: Optional[int] = None) -> None:
    """Join this process into the cluster (``jax.distributed.initialize``);
    with no arguments, cluster-environment auto-detection applies.

    ``max_missing_heartbeats`` (default: jax's 10 x 10 s) bounds how long
    the coordination service waits before declaring a silent peer dead —
    at which point it propagates an error that TERMINATES every healthy
    task. A supervised deployment (``resilience/supervisor.py``) that
    wants to recover in place rather than be torn down should raise it;
    the supervisor's own peer monitor and the bounded device pull provide
    the (much faster) failure detection instead."""
    import jax

    if max_missing_heartbeats is None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return
    from jax._src import distributed as _dist

    # the public wrapper does not expose the heartbeat knobs; the state
    # object underneath it does
    _dist.global_state.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        service_max_missing_heartbeats=max_missing_heartbeats,
        client_max_missing_heartbeats=max_missing_heartbeats,
    )


def global_mesh(axis_name: str = KEY_AXIS):
    """1-D mesh over every device of every process (DCN+ICI spanning)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis_name,))


def process_info() -> dict:
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


class ClusterPeerError(RuntimeError):
    """A multi-process device pull did not complete within the configured
    timeout — a peer process is presumed dead or unreachable.

    The reference surfaces transport failures through source retry /
    OnError hooks (``stream/input/source/Source.java:155-185``); the
    TPU-native failure mode is different: a peer dying mid-collective
    leaves every other host BLOCKED inside XLA, so the detection has to
    be a bounded wait around the device pull. Raised inside the
    junction's delivery path, this error rides the same ``@OnError`` /
    fault-stream machinery as any other processing failure.

    TERMINAL for the runtime: the timed-out pull leaves a leaked thread
    parked on the device stream, so retrying (or stepping the runtime
    again) only stacks more leaked threads — ``guarded_pull`` counts
    them (``cluster.outstanding_pulls`` gauge) and fails fast at its
    cap. Recovery story: tear the runtime down, restart the cluster with
    the surviving hosts (new ``jax.distributed`` incarnation), and
    ``restore_last_revision()`` from the persistence store — snapshots
    are host-side and replicated, so any surviving host can restore."""


def local_survivor_mesh(axis_name: str = KEY_AXIS):
    """1-D mesh over THIS process's devices only — the shape a survivor
    rebuilds on after a peer death, when re-forming the full cluster is
    not (yet) possible. State restored from the replicated snapshot store
    re-shards onto it transparently (same NamedSharding specs, smaller
    device set)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.local_devices()), (axis_name,))


# Fault-injection slot (resilience/faults.py): when set, every
# guarded_pull consults it BEFORE waiting — ``FaultInjector.drop_peer``
# installs a hook that raises ClusterPeerError immediately, simulating a
# dead peer without waiting out the pull timeout. Never set in production.
_fault_hook = None

# Leaked-pull accounting: every timeout abandons a daemon thread parked
# in an un-cancellable XLA host wait. The count of still-outstanding
# pulls is exported as a process gauge (``cluster.outstanding_pulls`` on
# GET /metrics), and bounded by ``_MAX_OUTSTANDING_PULLS`` — reaching
# the cap means the caller kept stepping a runtime that ClusterPeerError
# already declared dead (see guarded_pull's docstring: the error is
# TERMINAL), and further pulls fail fast instead of stacking threads.
_MAX_OUTSTANDING_PULLS = 32
_outstanding_pulls = 0
_pull_lock = None    # created lazily (threading import stays function-local)


def outstanding_pulls() -> int:
    """Device pulls currently in flight or abandoned-but-parked (leaked
    native waits from timed-out guarded_pull calls)."""
    return _outstanding_pulls


def _register_pull_gauge():
    from siddhi_tpu.observability.telemetry import global_registry

    global_registry().gauge("cluster.outstanding_pulls", outstanding_pulls)


_register_pull_gauge()


def guarded_pull(value, timeout_s: float, what: str = "cluster step"):
    """``np.asarray(value)`` bounded by ``timeout_s``.

    The wait runs in a daemon thread; on timeout the caller gets a
    labeled ``ClusterPeerError`` immediately (the stuck native wait stays
    parked in the abandoned thread — XLA host calls are not cancellable,
    but the PROGRAM regains control, which is the part that matters for
    failure detection).

    ``ClusterPeerError`` is TERMINAL for the runtime that raised it: the
    abandoned thread still owns the device stream, so retrying the pull
    (or stepping the same runtime again) can only stack more leaked
    threads behind a dead collective. The supported recovery is the
    supervisor's peer protocol — abandon the runtime, rebuild on
    ``local_survivor_mesh()``, restore the last revision, replay the WAL
    (``resilience/supervisor.py``). Outstanding pulls are counted on the
    ``cluster.outstanding_pulls`` gauge and capped at
    ``_MAX_OUTSTANDING_PULLS``; at the cap, guarded_pull fails fast."""
    import threading

    import numpy as np

    global _pull_lock, _outstanding_pulls
    if _pull_lock is None:
        _pull_lock = threading.Lock()

    if _fault_hook is not None:
        _fault_hook(what)

    with _pull_lock:
        if _outstanding_pulls >= _MAX_OUTSTANDING_PULLS:
            raise ClusterPeerError(
                f"{what}: {_outstanding_pulls} device pulls already "
                f"outstanding (cap {_MAX_OUTSTANDING_PULLS}) — earlier "
                f"ClusterPeerErrors were terminal; abandon this runtime "
                f"and run the peer-recovery protocol instead of retrying")
        _outstanding_pulls += 1

    box = {}
    done = threading.Event()

    def wait():
        global _outstanding_pulls
        try:
            # explicit device_get: guarded_pull is a sanctioned pull
            # point (the sanitizer transfer guard allows explicit only)
            import jax

            box["v"] = np.asarray(jax.device_get(value))
        except Exception as ex:  # surfaced to the caller below
            box["e"] = ex
        finally:
            with _pull_lock:
                _outstanding_pulls -= 1
            done.set()

    t = threading.Thread(target=wait, daemon=True,
                         name="siddhi-cluster-pull")
    t.start()
    if not done.wait(timeout_s):
        raise ClusterPeerError(
            f"{what} did not complete within {timeout_s:.1f}s — a cluster "
            f"peer process is presumed dead; this error is terminal for "
            f"the runtime: abandon it, restart the cluster and restore "
            f"from the last snapshot revision")
    if "e" in box:
        raise box["e"]
    return box["v"]
