from siddhi_tpu.parallel.mesh import (
    batch_shardings,
    device_route_query_step,
    force_host_devices,
    key_axis_sharding,
    make_mesh,
    shard_query_step,
    state_shardings,
)

__all__ = [
    "batch_shardings",
    "device_route_query_step",
    "force_host_devices",
    "key_axis_sharding",
    "make_mesh",
    "shard_query_step",
    "state_shardings",
]
