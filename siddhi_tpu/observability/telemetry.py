"""Telemetry registry: gauges, counters, and jit-compile events.

Complements ``core/util/statistics.StatisticsManager`` (throughput +
latency trackers behind ``@app:statistics``) with the operational
signals a production deployment scrapes continuously:

- **gauges** — sampled callables: @Async junction queue depth and
  in-flight batches (``core/stream/junction.py``), ingest-WAL size
  (``resilience/replay.py``), outstanding bounded cluster pulls
  (``parallel/distributed.py``). Registered once at wiring time, read
  at scrape time — a dead probe reports NaN instead of failing the
  scrape.
- **counters** — monotone event counts outside the statistics levels:
  backpressure stalls (producer blocked on a full @Async queue).
- **jit events** — per-key compile count, compile wall-ms, and cache
  hits, hooked where the runtimes build/cache jitted steps
  (``QueryRuntime._make_step``, the join/NFA ``_steps`` caches,
  ``parallel/mesh.py`` sharded jits, ``snapshot.py``'s replicate-jit
  cache). Compile storms and cache-miss regressions — recompiles on
  every capacity growth — show up here before they show up as p99.

One registry per app (``SiddhiAppContext.telemetry``, always present so
call sites need no None checks) plus one process-global registry
(``global_registry()``) for sites with no app context; ``export.py``
merges both into every scrape.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict


class InstrumentedJit:
    """First-call compile-timing proxy around a jitted callable.

    ``jax.jit`` returns instantly; tracing + XLA compilation happen at
    the first invocation. This proxy times that first call, records it
    as a jit-compile event (and a ``span("jit", key=...)``), then
    degrades to a single attribute check per call.

    Under ``SIDDHI_TPU_SANITIZE=1`` it additionally watches the wrapped
    callable's compile cache on EVERY call: a cache miss past the
    per-key budget — or any miss after
    ``analysis.sanitize.freeze_compiles()`` — raises ``RecompileError``
    naming the jit key, so a recompile-per-batch shape instability
    fails a test instead of melting p99 in production.

    When the call site declares a step ``family``, the first call also
    consults the process-global compiled-program cache
    (``core/util/program_cache.py``): an equal program already compiled
    by ANY app swaps in as this wrapper's callable — recorded as a
    cache HIT, not a compile — while a miss registers this wrapper's
    jit as the shared executable. ``family=None`` (the default) opts a
    wrapper out: sharding on the jit wrapper is invisible in the traced
    program, so only call sites that declare their construction family
    may share."""

    __slots__ = ("_fn", "_key", "_telemetry", "_compiled", "_sanitize",
                 "_cache_size", "_compiles", "_family", "_cache_extra",
                 "_shared")

    def __init__(self, fn: Callable, key: str, telemetry: "TelemetryRegistry",
                 family: str = None, cache_extra: str = ""):
        from siddhi_tpu.analysis import sanitize

        self._fn = fn
        self._key = key
        self._telemetry = telemetry
        self._compiled = False
        self._sanitize = sanitize.enabled()
        self._cache_size = 0
        self._compiles = 0
        self._family = family
        self._cache_extra = cache_extra
        self._shared = False    # dispatching through a shared executable

    def __call__(self, *args):
        if self._compiled and not self._sanitize:
            return self._fn(*args)
        from siddhi_tpu.observability.tracing import span

        hit = False
        if not self._compiled:
            from siddhi_tpu.observability import costmodel

            traced = None
            if self._family is not None:
                from siddhi_tpu.core.util import program_cache

                ctx = getattr(self._telemetry, "app_context", None)
                if program_cache.enabled_for(ctx):
                    fn, traced, hit = program_cache.cache().attach(
                        self._key, self._family, self._fn, args,
                        owner=self._telemetry, extra=self._cache_extra,
                        max_entries=program_cache.max_entries_for(ctx))
                    if hit:
                        self._fn = fn
                        self._shared = True
                        # recompile-watchdog baseline: the shared
                        # wrapper already holds its sharers' compiled
                        # shapes — only growth from HERE is a compile
                        # chargeable to this key
                        try:
                            self._cache_size = int(self._fn._cache_size())
                        except Exception:  # noqa: BLE001 — introspection
                            pass
            if costmodel.enabled():
                # cost-registry capture (fingerprint + cost/memory
                # analysis) runs BEFORE the first call: the step jits
                # donate their state argument, and tracing after the
                # call would read deleted buffers. The program-cache
                # trace is reused, and a shared hit reuses the donor's
                # analysis instead of a second AOT compile.
                costmodel.registry().capture(self._key, self._fn, args,
                                             traced=traced, shared=hit)
        t0 = time.perf_counter()
        with span("jit", key=self._key):
            out = self._fn(*args)
        first = not self._compiled
        self._compiled = True
        if first:
            if hit:
                # shared executable, no compile happened for this key
                self._telemetry.record_jit(self._key, hit=True)
            else:
                self._telemetry.record_jit(
                    self._key, wall_ms=(time.perf_counter() - t0) * 1000.0)
        if self._sanitize:
            self._watch_recompiles(first,
                                   (time.perf_counter() - t0) * 1000.0)
        return out

    def _watch_recompiles(self, first_call: bool, wall_ms: float) -> None:
        from siddhi_tpu.analysis import sanitize

        cache_size_fn = getattr(self._fn, "_cache_size", None)
        if cache_size_fn is None:
            return      # not a jax.jit callable — nothing to watch
        try:
            size = int(cache_size_fn())
        except Exception:   # noqa: BLE001 — jaxlib introspection only
            return
        if size > self._cache_size:
            self._compiles += size - self._cache_size
            self._cache_size = size
            if not first_call:
                # a LATE compile: record it (the off-mode proxy only
                # times the first call) and let the watchdog judge it.
                # wall_ms is the whole call (compile + execute), same
                # approximation as the first-call timing.
                self._telemetry.record_jit(self._key, wall_ms=wall_ms)
            if not first_call or sanitize.compiles_frozen():
                # freeze_compiles() means ANY cache miss raises — even a
                # cold proxy's very first compile (a late-created
                # runtime compiling mid-soak IS the storm being hunted)
                sanitize.check_recompile(self._key, self._compiles)

    def __getattr__(self, name):
        # transparent proxy: .lower()/.trace()/aot inspection go to the
        # wrapped jitted callable (hlo_audit lowers the sharded step)
        return getattr(self._fn, name)


class TelemetryRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._gauges: Dict[str, Callable[[], float]] = {}
        self.counters: Dict[str, int] = {}
        # key -> {"compiles": int, "compile_ms": float, "hits": int}
        self.jit: Dict[str, dict] = {}
        # name -> observability.histogram.Histogram (ms by convention):
        # always-on percentile series outside the @app:statistics levels —
        # aggregation flush latency, serving-tier fan-out/merge/query time
        self.histograms: Dict[str, object] = {}

    # ------------------------------------------------------------- gauges

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        """Register (or replace) a sampled gauge."""
        with self._lock:
            self._gauges[name] = fn

    def remove_gauge(self, name: str) -> None:
        with self._lock:
            self._gauges.pop(name, None)

    def read_gauges(self) -> Dict[str, float]:
        with self._lock:
            items = list(self._gauges.items())
        out = {}
        for name, fn in items:
            try:
                out[name] = float(fn())
            except Exception:  # noqa: BLE001 — a dead probe must not
                out[name] = math.nan  # fail the scrape
        return out

    # ----------------------------------------------------------- counters

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    # ---------------------------------------------------------- histograms

    def histogram(self, name: str):
        """Get-or-create a named log-bucket latency histogram
        (``observability/histogram.py``) — O(1) record, p50/p95/p99 on
        every scrape. Idempotent: call sites keep the returned object and
        record on it directly."""
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                from siddhi_tpu.observability.histogram import Histogram

                h = self.histograms[name] = Histogram()
            return h

    # --------------------------------------------------------- jit events

    def record_jit(self, key: str, wall_ms: float = 0.0,
                   hit: bool = False) -> None:
        with self._lock:
            rec = self.jit.get(key)
            if rec is None:
                rec = self.jit[key] = {"compiles": 0, "compile_ms": 0.0,
                                       "hits": 0}
            if hit:
                rec["hits"] += 1
            else:
                rec["compiles"] += 1
                rec["compile_ms"] += float(wall_ms)

    def instrument_jit(self, fn: Callable, key: str,
                       family: str = None,
                       cache_extra: str = "") -> InstrumentedJit:
        """Wrap a freshly-built jitted callable so its first call is
        recorded as a compile event. ``family`` (a step-builder tag,
        e.g. ``"query_step"``) opts the wrapper into the process-global
        compiled-program cache; ``cache_extra`` carries any
        sharding/mesh witness the traced program cannot see."""
        return InstrumentedJit(fn, key, self, family=family,
                               cache_extra=cache_extra)

    # ------------------------------------------------------------ reading

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
            jit = {k: dict(v) for k, v in self.jit.items()}
            hists = {k: h.snapshot() for k, h in self.histograms.items()}
        out = {"gauges": self.read_gauges(), "counters": counters,
               "jit": jit}
        if hists:
            out["histograms"] = hists
        return out

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.jit.clear()
            for h in self.histograms.values():
                h.reset()


_GLOBAL = TelemetryRegistry()


def global_registry() -> TelemetryRegistry:
    """Process-wide registry for sites with no app context (the snapshot
    replicate-jit cache, the bounded cluster-pull gauge)."""
    return _GLOBAL
