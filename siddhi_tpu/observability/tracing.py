"""Structured tracing spans with Chrome-trace export.

A span covers one host-side stage of the pipeline (compile, plan, jit,
junction dispatch, query step, sink publish, persist) at batch
granularity — the host-side complement of the XLA profiler trace
(``SiddhiAppRuntime.start_trace``), which sees device ops but not the
host pipeline between them.

Design constraints, in priority order:

1. **Near-zero cost when disabled.** ``span(...)`` checks one module
   flag and returns a shared no-op context manager — no allocation
   beyond the kwargs dict, no locks. The hot path (junction dispatch,
   query step) runs it per *batch*, not per event.
2. **Thread-safe when enabled.** Spans finish in LIFO order per thread
   (context managers), so nesting is correct by construction; the ring
   buffer is a ``deque(maxlen=...)`` whose appends are atomic under the
   GIL. When full, the OLDEST span falls off (``dropped`` counts them) —
   tracing never grows without bound and never blocks.
3. **Standard output.** ``to_chrome_trace()`` emits the Trace Event
   Format (complete events, ``ph: "X"`` with pid/tid/ts/dur/name/args)
   that ``chrome://tracing`` and Perfetto load directly.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

_DEFAULT_CAPACITY = 65_536


class _FinishedSpan:
    __slots__ = ("name", "tid", "ts_us", "dur_us", "args")

    def __init__(self, name, tid, ts_us, dur_us, args):
        self.name = name
        self.tid = tid
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.args = args


class _NoopSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        self._tracer._record(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """Ring-buffered span collector (one per process — see ``TRACER``)."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self.enabled = False
        self.capacity = int(capacity)
        self._buf: deque = deque(maxlen=self.capacity)
        # guards buffer swaps and export snapshots against concurrent
        # producer appends ("deque mutated during iteration"); producers
        # hold it only for one append, so contention is one span long
        self._lock = threading.Lock()
        self.dropped = 0
        self._epoch_ns = time.perf_counter_ns()

    # ------------------------------------------------------------ control

    def start(self, capacity: Optional[int] = None) -> None:
        """Enable collection into a fresh ring buffer."""
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
            self._buf = deque(maxlen=self.capacity)
            self.dropped = 0
            self._epoch_ns = time.perf_counter_ns()
            self.enabled = True

    def stop(self) -> dict:
        """Disable collection and return the Chrome-trace JSON object."""
        self.enabled = False
        return self.to_chrome_trace()

    def clear(self) -> None:
        with self._lock:
            self._buf = deque(maxlen=self.capacity)
            self.dropped = 0

    def __len__(self) -> int:
        return len(self._buf)

    # ---------------------------------------------------------- recording

    def span(self, name: str, **args):
        if not self.enabled:
            return _NOOP
        return _Span(self, name, args)

    def _record(self, name: str, t0_ns: int, t1_ns: int, args: dict):
        if not self.enabled:
            return     # stopped while the span was open
        span_rec = _FinishedSpan(
            name, threading.get_ident(),
            (t0_ns - self._epoch_ns) / 1000.0,
            max(t1_ns - t0_ns, 1) / 1000.0,
            args)
        with self._lock:
            if not self.enabled:
                return   # a racing stop() export must not see new appends
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1     # deque evicts the oldest on append
            self._buf.append(span_rec)

    # ------------------------------------------------------------- export

    def to_chrome_trace(self) -> dict:
        """Trace Event Format: complete events sorted by (tid, ts) so
        parents precede children, plus process/thread metadata."""
        pid = os.getpid()
        with self._lock:   # snapshot against concurrent producer appends
            buf = list(self._buf)
            dropped = self.dropped
        spans = sorted(buf, key=lambda s: (s.tid, s.ts_us))
        events = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "siddhi_tpu"},
        }]
        for s in spans:
            ev = {
                "name": s.name,
                "cat": "siddhi",
                "ph": "X",
                "pid": pid,
                "tid": s.tid,
                "ts": round(s.ts_us, 3),
                "dur": round(s.dur_us, 3),
            }
            if s.args:
                ev["args"] = {k: _jsonable(v) for k, v in s.args.items()}
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": dropped},
        }


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# process-global tracer: spans from every app/runtime in this process
# land in one timeline (pid/tid separate them), controlled by
# POST /trace/start|stop on the REST service or Tracer.start()/stop()
TRACER = Tracer()


def span(name: str, **args):
    """``with span("jit", query="q1"): ...`` — records a structured span
    on the global tracer; a shared no-op when tracing is off."""
    if not TRACER.enabled:
        return _NOOP
    return _Span(TRACER, name, args)
