"""Batch-journey tracing + critical-path attribution.

The spans of ``tracing.py`` time components in isolation; once the
dispatch pipeline overlaps stages (``core/query/completion.py``,
depth >= 2) they cannot say where a batch's END-TO-END latency actually
goes — the dispatch slice of a pipelined batch returns instantly and
the device time hides inside the ride. This module follows each batch
through the pipeline with host-side monotonic timestamps only (zero
changes inside jitted step code — sanitizers and ``hlo_audit`` stay
quiet) and attributes wall-clock per stage the way "Scaling Ordered
Stream Processing on Shared-Memory Multicores" (PAPERS.md) prescribes:
service time vs queueing time, per stage, with overlapped stages
attributed by MAX, not sum.

Stage glossary (exported as ``siddhi_stage_ms{query,stage}`` service
histograms and ``siddhi_stage_queue_ms{query,stage}`` queueing
histograms on ``GET /metrics``):

- ``pack``     — host event->columnar encode (``HostBatch.from_events``
                 / ``from_columns``), stamped where the batch is born.
- ``queue``    — residence in the @Async junction queue (enqueue ->
                 dequeue); a queue-only stage: its signal is queueing
                 time, service is the worker's re-batching (~0).
- ``dispatch`` — host work inside ``process_batch``: key computation,
                 capacity checks, routing prep, jitted-step dispatch.
- ``device``   — observed device busy time. A pipelined batch rides in
                 flight; at drain the existing ``jax.Array.is_ready``
                 machinery tells which side was waiting: output NOT
                 ready => the device worked the whole ride (service =
                 ride + meta pull), output ready => the device finished
                 mid-ride and only the pull is service — the ride was
                 the output parked waiting for the host (recorded as
                 ``device`` queueing/slack, NOT service). This is the
                 max-not-sum rule: when the host is the bottleneck the
                 ride must not ALSO count as device service.
- ``emit``     — output decode + downstream publish (sink/junction).

Cost model: near-zero when off — every instrumented site checks one
module flag and does nothing else. When on, a batch carries one small
``Journey`` object (a handful of floats); finished journeys land in
per-(query, stage) telemetry histograms plus a bounded ring buffer of
recent per-batch records (tracing never grows without bound).

The analyzer (:func:`critical_path_report`) aggregates the histograms
into a report naming the bottleneck stage per query: the stage with the
largest mean service time per batch — except a ``queue``-stage residence
dominating every service mean names the queue itself (the consumer is
stalled OUTSIDE its measured service, e.g. a wedged/throttled worker).
Utilization = stage busy time / observed wall. Rendered by
``tools/critical_path.py``; served at ``GET /profile/critical_path``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

STAGES = ("pack", "queue", "dispatch", "device", "emit")

_DEFAULT_RING = 4096

# module flag: the ONE check every instrumented hot-path site pays when
# journey tracing is off (HostBatch.from_events runs per batch, not per
# event — same discipline as tracing.span)
_ENABLED = False
_enable_count = 0
_lock = threading.RLock()

# ring of recently finished journeys (dicts; see Journey.finish)
_RING: deque = deque(maxlen=_DEFAULT_RING)

# (app, query) -> [first_seen, last_seen] perf_counter span: the
# observed wall the analyzer divides stage busy time by
_WALL: Dict[Tuple[str, str], List[float]] = {}

# fault injection (tests / tools): stage -> seconds of planted service
# delay, consulted only by instrumented sites and only when enabled —
# FaultInjector.delay_stage is the public face (resilience/faults.py)
_DELAYS: Dict[str, float] = {}

# per-delivery-thread context: the @Async worker stamps the queue wait
# of the unit it is about to deliver; every receiving query's journey
# picks it up (one delivery fans out to N receivers)
_TLS = threading.local()


def enabled() -> bool:
    return _ENABLED


def enable(ring_capacity: Optional[int] = None) -> None:
    """Turn journey tracing on (refcounted: one ``disable()`` per
    ``enable()``; the first enable resets the ring and wall tracking)."""
    global _ENABLED, _enable_count, _RING
    with _lock:
        _enable_count += 1
        if not _ENABLED:
            _RING = deque(maxlen=int(ring_capacity or _DEFAULT_RING))
            _WALL.clear()
            _ENABLED = True
        elif ring_capacity is not None and ring_capacity != _RING.maxlen:
            _RING = deque(_RING, maxlen=int(ring_capacity))


def disable(force: bool = False) -> None:
    global _ENABLED, _enable_count
    with _lock:
        _enable_count = 0 if force else max(0, _enable_count - 1)
        if _enable_count == 0:
            _ENABLED = False


def forget_app(app_name: str) -> None:
    """Drop an app's wall-tracking entries (called at runtime shutdown):
    a redeployed same-named app must not inherit a dead app's
    first-seen timestamp — its utilization would read ~0% across the
    gap — and app churn must not grow the map without bound."""
    with _lock:
        for key in [k for k in _WALL if k[0] == app_name]:
            del _WALL[key]


def inject_delay(stage: str, seconds: float) -> None:
    """Plant a service delay inside an instrumented stage (the
    critical-path tests' known bottleneck). Only ``pack`` is a direct
    injection point today; queue bottlenecks are planted with
    ``FaultInjector.delay_worker`` (the consumer side)."""
    if stage not in STAGES:
        raise ValueError(f"unknown journey stage '{stage}' — one of {STAGES}")
    _DELAYS[stage] = float(seconds)


def clear_delays() -> None:
    _DELAYS.clear()


def maybe_delay(stage: str) -> None:
    d = _DELAYS.get(stage)
    if d:
        time.sleep(d)


def ring() -> list:
    """Snapshot of the recent-journeys ring (newest last)."""
    with _lock:
        return list(_RING)


def ready_of(ref) -> bool:
    """``jax.Array.is_ready`` verdict of a device ref (True for numpy /
    unknown / deleted — also aliased as ``completion._is_ready``, the
    pump's stall probe)."""
    is_ready = getattr(ref, "is_ready", None)
    if is_ready is None:
        return True
    try:
        return bool(is_ready())
    except Exception:   # noqa: BLE001 — deleted/donated buffers etc.
        return True


# ------------------------------------------------------- delivery context

def push_delivery_queue_wait(enq_t: Optional[float]):
    """Open one junction delivery's scope on this thread: receivers of
    THIS delivery read the unit's queue residence (None = not from an
    @Async queue). Returns the previous value for the paired
    :func:`pop_delivery_queue_wait` — a nested delivery (a receiver's
    synchronous emit cascading into a downstream junction) masks the
    outer wait instead of charging the upstream queue residence to
    queries that never sat in that queue."""
    prev = getattr(_TLS, "queue_ms", None)
    _TLS.queue_ms = (None if enq_t is None
                     else (time.perf_counter() - enq_t) * 1000.0)
    return prev


def pop_delivery_queue_wait(prev) -> None:
    _TLS.queue_ms = prev


def _delivery_queue_ms() -> Optional[float]:
    return getattr(_TLS, "queue_ms", None)


# ---------------------------------------------------------------- journey

class Journey:
    """Per-batch trace context: stamped at pack, carried on the
    ``HostBatch`` through junction delivery, forked per receiving query,
    riding the batch's ``QueryCompletion``/``FusedCompletion`` through
    the pump, finished after emit. All timestamps host-monotonic."""

    __slots__ = ("pack_ms", "queue_ms", "_t_disp0", "dispatch_ms",
                 "_t_disp1", "_t_drain0", "ready", "pull_ms", "emit_ms")

    def __init__(self, pack_ms: Optional[float] = None):
        self.pack_ms = pack_ms
        self.queue_ms: Optional[float] = None
        self._t_disp0: Optional[float] = None
        self.dispatch_ms = 0.0
        self._t_disp1: Optional[float] = None
        self._t_drain0: Optional[float] = None
        self.ready: Optional[bool] = None
        self.pull_ms = 0.0
        self.emit_ms = 0.0

    # one journey object is stamped on the batch at pack time; each
    # receiving query forks its own (stage times are per query)
    def fork(self) -> "Journey":
        return Journey(pack_ms=self.pack_ms)

    def begin_dispatch(self) -> None:
        self.queue_ms = _delivery_queue_ms()
        self._t_disp0 = time.perf_counter()

    def end_dispatch(self) -> None:
        if self._t_disp0 is not None and self._t_disp1 is None:
            self._t_disp1 = time.perf_counter()
            self.dispatch_ms = (self._t_disp1 - self._t_disp0) * 1000.0

    def pre_drain(self, ready: bool) -> None:
        """Stamped immediately BEFORE the meta pull, with the output's
        ``is_ready`` verdict — the pivot of the device attribution."""
        self._t_drain0 = time.perf_counter()
        self.ready = bool(ready)

    def drained(self, pull_ms: float) -> None:
        self.pull_ms = float(pull_ms)

    def device_times(self) -> Tuple[float, float]:
        """(service_ms, queue_ms) of the device stage — see the module
        docstring's max-not-sum rule."""
        ride = 0.0
        if self._t_drain0 is not None and self._t_disp1 is not None:
            ride = max(0.0, (self._t_drain0 - self._t_disp1) * 1000.0)
        if self.ready is False:
            return ride + self.pull_ms, 0.0
        # ready (or never observed): only the pull is known device work;
        # the ride was the finished output parked waiting for the host
        return self.pull_ms, ride

    def finish(self, app_context, names) -> None:
        """Record this journey's stage times into the app's telemetry
        histograms (one set per query name — a fused group records the
        shared batch under every member) and the recent-journeys ring."""
        if not _ENABLED:
            return
        tel = getattr(app_context, "telemetry", None)
        if tel is None:
            return
        app = getattr(app_context, "name", "")
        dev_service, dev_queue = self.device_times()
        now = time.perf_counter()
        for name in names:
            if self.pack_ms is not None:
                tel.histogram(
                    f"stage.{name}.pack.service_ms").record(self.pack_ms)
            if self.queue_ms is not None:
                tel.histogram(
                    f"stage.{name}.queue.queue_ms").record(self.queue_ms)
            tel.histogram(
                f"stage.{name}.dispatch.service_ms").record(self.dispatch_ms)
            tel.histogram(
                f"stage.{name}.device.service_ms").record(dev_service)
            tel.histogram(
                f"stage.{name}.device.queue_ms").record(dev_queue)
            tel.histogram(f"stage.{name}.emit.service_ms").record(self.emit_ms)
        with _lock:
            # under the lock: forget_app's clear must not interleave
            # with this read-modify-write (a last in-flight finish
            # re-inserting a dead app's first-seen timestamp)
            for name in names:
                wall = _WALL.get((app, name))
                if wall is None:
                    t0 = self._t_disp0 if self._t_disp0 is not None else now
                    _WALL[(app, name)] = [t0, now]
                else:
                    wall[1] = now
            _RING.append({
                "app": app, "queries": list(names),
                "pack_ms": self.pack_ms, "queue_ms": self.queue_ms,
                "dispatch_ms": self.dispatch_ms,
                "device_service_ms": dev_service,
                "device_queue_ms": dev_queue,
                "emit_ms": self.emit_ms, "t": now,
            })


def stamp_pack(batch, t0: float) -> None:
    """Attach a fresh journey (pack service = now - t0) to a batch just
    built by ``HostBatch.from_events``/``from_columns``. Caller already
    checked :func:`enabled` — this is the pack-stage stamp the rest of
    the pipeline carries forward."""
    batch.journey = Journey(pack_ms=(time.perf_counter() - t0) * 1000.0)


def stamp_pack_ms(batch, pack_ms: float) -> None:
    """Pack stamp with a caller-computed service time — the parallel
    ingest pack path (``core/event._parallel_from_events``) attributes
    max-over-sub-batches plus the serial merge, per the max-not-sum rule
    (concurrent packer time must not count once per worker)."""
    batch.journey = Journey(pack_ms=float(pack_ms))


def begin(batch) -> Journey:
    """Per-receiver journey for a delivered batch: forks the batch's
    pack stamp (N receivers must not share mutable stage state) and
    opens the dispatch stage."""
    src = getattr(batch, "journey", None)
    jr = src.fork() if src is not None else Journey()
    jr.begin_dispatch()
    return jr


# --------------------------------------------------------------- analyzer

# residence in the queue stage must dominate every service mean by this
# factor before the analyzer blames the queue itself: queueing time is
# a symptom, and a modest wait in front of a genuinely busy stage should
# name the busy stage, not the line in front of it
_QUEUE_DOMINANCE = 2.0

_STAGE_KINDS = ("service", "queue")


def _parse_stage_hists(hist_snapshot: dict) -> Dict[str, dict]:
    """``stage.<query>.<stage>.<kind>_ms`` histogram snapshots grouped
    as {query: {stage: {kind: snap}}} (query names may contain dots —
    the stage/kind tail is fixed, so parse from the right)."""
    out: Dict[str, dict] = {}
    for name, snap in hist_snapshot.items():
        if not name.startswith("stage."):
            continue
        rest = name[len("stage."):]
        parts = rest.rsplit(".", 2)
        if len(parts) != 3:
            continue
        query, stage, kind_ms = parts
        if not kind_ms.endswith("_ms"):
            continue
        kind = kind_ms[:-3]
        if stage not in STAGES or kind not in _STAGE_KINDS:
            continue
        out.setdefault(query, {}).setdefault(stage, {})[kind] = snap
    return out


def _parse_device_signals(hist_snapshot: dict,
                          gauge_snapshot: dict) -> Dict[str, dict]:
    """``device.<query>.<slot>`` instrument histograms paired with their
    ``.capacity`` gauges, grouped per query (slot names come from the
    DEVICE_SLOTS declaration in export.py — the graftlint-R6-checked
    tuple, so a newly declared slot is visible here by construction;
    query names may contain dots, so parse from the right against the
    known slot set)."""
    from siddhi_tpu.observability.export import DEVICE_SLOTS

    slots = sorted(DEVICE_SLOTS, key=len, reverse=True)
    out: Dict[str, dict] = {}
    for name, snap in hist_snapshot.items():
        if not name.startswith("device."):
            continue
        rest = name[len("device."):]
        for slot in slots:
            if rest.endswith("." + slot):
                query = rest[: -len(slot) - 1]
                cap = gauge_snapshot.get(f"device.{query}.{slot}.capacity")
                out.setdefault(query, {})[slot] = {
                    "snap": snap, "capacity": cap}
                break
    return out


def _device_structure(device_slots: Optional[dict]) -> Optional[dict]:
    """The most saturated device structure of one query, from its
    drained instrument histograms: max p99/capacity ratio across slots
    with a known capacity — the thing to name when the device stage is
    the bottleneck ('join right side partition fill p99 = 0.97 of
    Wp')."""
    from siddhi_tpu.observability.instruments import (
        RESIDUAL_SLOTS, SLOT_CAP_NAMES, SLOT_LABELS)

    best = None
    for slot, rec in (device_slots or {}).items():
        cap = rec.get("capacity")
        if not cap or cap != cap:      # missing or NaN denominator
            continue
        label = SLOT_LABELS.get(slot, slot)
        cap_name = SLOT_CAP_NAMES.get(slot, "capacity")
        if slot in RESIDUAL_SLOTS:
            # a residual saturates toward ZERO: the worst case over the
            # window is the MINIMUM residual seen, not a high quantile
            # (p99 would be the healthiest batch)
            quoted = float(rec["snap"].get("min", 0.0))
            ratio = max(0.0, 1.0 - quoted / float(cap))
            text = (f"{label} min = {quoted:.0f} of {cap_name} "
                    f"({ratio:.2f} saturated)")
        else:
            quoted = float(rec["snap"].get("p99", 0.0))
            ratio = quoted / float(cap)
            text = f"{label} p99 = {ratio:.2f} of {cap_name}"
        if best is None or ratio > best["ratio"]:
            best = {
                "slot": slot,
                "label": label,
                # the quoted statistic: p99 for fill-style slots, MIN
                # for residuals (the field name must not lie about it)
                "stat": "min" if slot in RESIDUAL_SLOTS else "p99",
                "value": round(quoted, 3),
                "capacity": float(cap),
                "ratio": round(ratio, 4),
                "text": text,
            }
    return best


def _query_report(app: str, query: str, stages: Dict[str, dict],
                  device_slots: Optional[dict] = None) -> dict:
    per_stage = {}
    for stage in STAGES:
        kinds = stages.get(stage)
        if not kinds:
            continue
        service = kinds.get("service") or {}
        queue = kinds.get("queue") or {}
        per_stage[stage] = {
            "batches": int(service.get("count") or queue.get("count") or 0),
            "service_ms": service,
            "queue_ms": queue,
            "busy_ms": round(float(service.get("sum", 0.0)), 3),
            "mean_service_ms": round(
                float(service.get("sum", 0.0))
                / max(1, int(service.get("count", 0))), 4),
            "mean_queue_ms": round(
                float(queue.get("sum", 0.0))
                / max(1, int(queue.get("count", 0))), 4),
        }
    wall = _WALL.get((app, query))
    wall_ms = (wall[1] - wall[0]) * 1000.0 if wall else 0.0

    # bottleneck: largest mean service per batch; a queue-stage
    # residence dominating every service mean names the queue itself
    best_stage, best_mean = None, -1.0
    for stage, rec in per_stage.items():
        if stage == "queue":
            continue
        if rec["mean_service_ms"] > best_mean:
            best_stage, best_mean = stage, rec["mean_service_ms"]
    queue_rec = per_stage.get("queue")
    if queue_rec is not None:
        q_mean = queue_rec["mean_queue_ms"]
        if q_mean > 0 and q_mean >= _QUEUE_DOMINANCE * max(best_mean, 0.0):
            best_stage, best_mean = "queue", q_mean
    structure = _device_structure(device_slots)
    bottleneck = None
    if best_stage is not None:
        rec = per_stage[best_stage]
        busy = (float(rec["queue_ms"].get("sum", 0.0))
                if best_stage == "queue" else rec["busy_ms"])
        bottleneck = {
            "stage": best_stage,
            "kind": "queueing" if best_stage == "queue" else "service",
            "mean_ms": round(best_mean, 4),
            "utilization": round(min(1.0, busy / wall_ms), 4)
            if wall_ms > 0 else None,
        }
        if best_stage == "device" and structure is not None:
            # the device is the bottleneck AND its instruments say which
            # structure is saturated — name it right in the verdict
            bottleneck["structure"] = structure["text"]
    report = {"stages": per_stage, "wall_ms": round(wall_ms, 3),
              "bottleneck": bottleneck}
    if structure is not None:
        report["device_structure"] = structure
    return report


def critical_path_report(manager, app_name: Optional[str] = None) -> dict:
    """Aggregate the per-stage histograms into the critical-path report
    (per app, per query): stage service/queue quantiles, busy time,
    observed wall, and the named bottleneck stage with its utilization.
    Correct under pipelining: overlapped stages were attributed by max
    at record time (see ``Journey.device_times``), so a host-bound
    pipeline never shows the device as busy for the full wall."""
    runtimes = manager.app_runtimes
    if app_name is not None:
        rt = runtimes.get(app_name)
        if rt is None:
            raise KeyError(f"app '{app_name}' is not deployed")
        runtimes = {app_name: rt}
    apps = {}
    for name in sorted(runtimes):
        rt = runtimes[name]
        tel = rt.app_context.telemetry
        snap = tel.snapshot()
        hists = snap.get("histograms", {})
        # device instruments (on by default, independent of journey
        # tracing): when the device stage is the bottleneck, the report
        # names the saturated structure behind it
        device = _parse_device_signals(hists, snap.get("gauges", {}))
        queries = {
            q: _query_report(name, q, stages, device_slots=device.get(q))
            for q, stages in sorted(_parse_stage_hists(hists).items())
        }
        apps[name] = {"queries": queries}
    return {
        "enabled": enabled(),
        "stage_glossary": list(STAGES),
        "recent_journeys": len(_RING),
        "apps": apps,
    }
