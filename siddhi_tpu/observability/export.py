"""Metrics exposition: Prometheus text format + JSON snapshot.

Renders every deployed app's ``StatisticsManager`` (throughput, latency
with p50/p95/p99, named counters, DETAIL memory/buffer probes) and
``TelemetryRegistry`` (gauges, counters, jit-compile events), merged
with the process-global registry, as:

- Prometheus text exposition (v0.0.4) for ``GET /metrics`` — the
  scrapeable surface a production deployment points its collector at;
- a JSON snapshot (``?format=json`` / ``Accept: application/json``) for
  humans and tests.

Naming: structured label sets, not dotted metric names — per-query
latency is ``siddhi_latency_ms{app=...,name=...,quantile=...}``, @Async
depth is ``siddhi_junction_queue_depth{app=...,stream=...}``, and named
counters keep their dotted names as a LABEL VALUE
(``siddhi_counter_total{name="resilience.wal_replayed_batches"}``)
where dots are legal. The well-known ``resilience.*`` counters are
always emitted (0 until the event happens) so dashboards and alerts can
be written before the first failure."""

from __future__ import annotations

import math
import re
import time
from typing import Dict, List, Tuple

from siddhi_tpu.observability.telemetry import global_registry

# --- graftlint R3 declarations (metric-registration parity) ----------
# Every dotted telemetry name registered anywhere in the tree
# (.gauge/.count/.histogram/stat_count) must start with one of these
# prefixes; each prefix maps to a dedicated family below or renders as
# the labeled generic siddhi_counter_total/siddhi_gauge ON PURPOSE.
# A registration with an undeclared prefix, and a declared prefix with
# no remaining registration site, are both lint findings — the PR-6
# "gauges registered on one code path but not its twin" class.
TELEMETRY_PREFIXES = (
    "junction",      # @Async queue depth / stalls / sheds / timeouts
    "fanout",        # fused fan-out group size + dispatch counters
    "pipeline",      # CompletionPump depth + metas/pulls/stalls
    "aggregation",   # rollup buckets, shards, shard WALs, flush_ms
    "shard",         # routed-row skew gauges + exchange_ms
    "join",          # device-join partition occupancy, probe/insert_ms
    "serving",       # admission pool, scatter-gather latency
    "quota",         # overload quota-utilization gauges
    "overload",      # always-on overload counters (generic family)
    "wal",           # ingest-WAL size gauges
    "cluster",       # bounded-pull probe (process registry) + the
                     # multi-process cluster fabric: workers-live /
                     # per-worker acked-seq + WAL gauges, ingest / run /
                     # egress / checkpoint counters, per-worker respawn
                     # and replay counters (siddhi_tpu/cluster/ ->
                     # siddhi_cluster_*)
    "resilience",    # StatisticsManager recovery counters (stat_count)
    "stage",         # batch-journey per-stage service/queue histograms
                     # (observability/journey.py -> siddhi_stage_*)
    "jitcost",       # compiled-program cost gauges
                     # (observability/costmodel.py -> siddhi_jit_cost_*)
    "program_cache", # process-global compiled-program cache: hit/miss/
                     # eviction counters + live-entry size gauge
                     # (core/util/program_cache.py ->
                     # siddhi_program_cache_*; the size gauge is removed
                     # at cache drain)
    "scrape",        # /metrics self-timing (siddhi_scrape_ms)
    "device",        # device-instrument slots riding the meta vector
                     # (observability/instruments.py -> siddhi_device_*)
    "ingest",        # multicore ingest front door: pack-pool gauges,
                     # pack/merge histograms, wire-frame counters
                     # (core/stream/input/pack_pool.py + wire.py ->
                     # siddhi_ingest_*)
    "eligibility",   # build-time strategy-eligibility census counters
                     # (core/eligibility.py register_census ->
                     # siddhi_eligibility_total{surface,code,query})
    "autopilot",     # closed-loop controller: mode gauge, tick/freeze
                     # counters, per-(knob,direction,reason) decision
                     # counters (siddhi_tpu/autopilot/ ->
                     # siddhi_autopilot_*)
)

# --- graftlint R6 declarations (device-instrument parity) ------------
# Every DATA slot name a step builder may declare in its
# instrument_slots() spec (observability/instruments.Slot). The
# exposition regexes below are BUILT from this tuple, and R6 checks the
# declared set against the Slot(...) construction sites and the
# _consume_check_slot consumers bidirectionally — a slot computed on
# device but never decoded (or declared but never computed) is a lint
# finding, not a silent telemetry hole.
DEVICE_SLOTS = (
    "win_fill",        # window ring live rows (keyed: hottest key)
    "groups",          # distinct group keys touched by the batch
    "nfa_runs",        # live NFA partial-match slots
    "shard_rows",      # per-shard routed rows (device-routed exchange)
    "route_residual",  # receive capacity left on the fullest shard
    "fill.left",       # join build directory fill per partition
    "fill.right",
)
# Structural (kind='check') slots: consumed by a runtime's
# _consume_check_slot hook at drain, never rendered as telemetry.
DEVICE_CHECK_SLOTS = (
    "route_overflow",  # exchange overflow -> FatalQueryError
    "seq",             # join cross-stream sequence verification
)
# Gauge templates that live exactly as long as their registry does —
# per-app gauges die with the app's TelemetryRegistry at shutdown, the
# process-registry entries below are deliberate process-lifetime
# probes. Everything else must have a remove_gauge site or it pins a
# dead probe on /metrics (the lint names this list on violation).
PROCESS_LIFETIME_GAUGES = (
    "junction.*",           # app registry — junctions live with the app
    "pipeline.*.inflight",  # app registry; label-keyed, survives rebuilds
    "wal.*",                # app registry — registered at WAL attach
    "aggregation.*",        # app registry — both rollup paths register
    "quota.*",              # app registry — overload registration
    "join.partition_rows.*",  # app registry — device-join attach
    "shard.rows.*",         # app + process registry (legacy host-router
                            # scope "host" is a deprecated shim)
    "cluster.outstanding_pulls",  # process registry, process-lifetime
    "jitcost.*",            # process registry — a compiled program's
                            # cost record outlives any single app
    "device.*",             # app registry — device-instrument last-value
                            # and capacity gauges die with the app
)
# ---------------------------------------------------------------------

# operationally load-bearing counters, pre-declared at 0 per app
RESILIENCE_COUNTERS = (
    "resilience.worker_restarts",
    "resilience.wal_replayed_batches",
    "resilience.wal_dropped_batches",
    "resilience.source_retries",
    "resilience.sink_retries",
    "resilience.peer_failures",
    "resilience.peer_recoveries",
    # serving tier (siddhi_tpu/serving/)
    "resilience.query_sheds",
    "resilience.shard_rebuilds",
    "resilience.shard_replay_skips",
    "resilience.shard_replay_gaps",
    # overload armor (siddhi_tpu/resilience/overload.py)
    "resilience.shed_events",
    "resilience.quota_denials",
    "resilience.enqueue_timeouts",
)

_JUNCTION_GAUGE = re.compile(r"^junction\.(?P<stream>.+)\.(?P<kind>"
                             r"queue_depth|inflight_batches)$")
_JUNCTION_STALLS = re.compile(r"^junction\.(?P<stream>.+)"
                              r"\.backpressure_stalls$")
# overload armor (resilience/overload.py): per-stream shed / escalation
# counters + per-app quota-utilization gauges
_JUNCTION_SHEDS = re.compile(r"^junction\.(?P<stream>.+)\.shed_events$")
_JUNCTION_TIMEOUTS = re.compile(r"^junction\.(?P<stream>.+)"
                                r"\.enqueue_timeouts$")
_QUOTA_GAUGE = re.compile(r"^quota\.(?P<resource>queue|pipeline|memory)"
                          r"_utilization(?:\.(?P<stream>.+))?$")
_FANOUT_GAUGE = re.compile(r"^fanout\.(?P<stream>.+)\.group_size$")
_FANOUT_COUNTER = re.compile(r"^fanout\.(?P<stream>.+)\.(?P<kind>"
                             r"dispatches|meta_pulls)$")
_PIPELINE_GAUGE = re.compile(r"^pipeline\.(?P<query>.+)\.inflight$")
# eligibility.<surface>.<CODE>.<query> — surface spellings are the
# core/eligibility.py SURFACES tuple, codes its ReasonCode values
_ELIGIBILITY_COUNTER = re.compile(
    r"^eligibility\.(?P<surface>[a-z_]+)\.(?P<code>[A-Z0-9_]+)"
    r"\.(?P<query>.+)$")
# multicore ingest front door (core/stream/input/): pack-pool health
# gauges, per-sub-batch pack + per-batch ordered-merge histograms, and
# wire-frame ingest counters
_INGEST_POOL_GAUGE = re.compile(r"^ingest\.pool\.(?P<kind>"
                                r"queue_depth|workers|utilization)$")
_INGEST_HIST_FAMILY = {
    "ingest.pack_ms": ("siddhi_ingest_pack_ms",
                       "ingest pack-pool sub-batch encode service time "
                       "(ms; one sample per sequence-numbered sub-batch)"),
    "ingest.merge_ms": ("siddhi_ingest_merge_ms",
                        "ordered-merge time per parallel-packed batch "
                        "(ms; serial dictionary miss resolution + column "
                        "finalize)"),
}
_INGEST_COUNTER_FAMILY = {
    "ingest.wire.frames": ("siddhi_ingest_wire_frames_total",
                           "binary wire frames accepted on "
                           "POST /ingest/{stream}"),
    "ingest.wire.bytes": ("siddhi_ingest_wire_bytes_total",
                          "wire-frame bytes accepted on "
                          "POST /ingest/{stream}"),
    "ingest.wire.events": ("siddhi_ingest_wire_events_total",
                           "events ingested through the wire-format "
                           "front door"),
    "ingest.pool.repacks": ("siddhi_ingest_repacks_total",
                            "sub-batches re-packed inline after a dead "
                            "ingest pack worker (re-packed, never lost)"),
    "ingest.pool.worker_deaths": ("siddhi_ingest_worker_deaths_total",
                                  "ingest pack-pool worker threads that "
                                  "died (respawned by pool/supervisor)"),
    "ingest.wire.decoder_evictions": (
        "siddhi_wire_decoder_evictions_total",
        "wire decoder delta-state entries evicted at the registry LRU "
        "cap (a sender whose state was evicted must WireEncoder.reset())"),
}
# pipeline.metas / pipeline.pulls: metas-per-pull batching ratio;
# pipeline.stalls: forced drains that had to wait on an unready meta
_PIPELINE_COUNTER_FAMILY = {
    "pipeline.stalls": ("siddhi_pipeline_stalls_total",
                        "pipeline drains that blocked on an unready "
                        "__meta__ (producer stalled on the device)"),
    "pipeline.metas": ("siddhi_pipeline_metas_total",
                       "batch metas drained through the dispatch "
                       "pipeline (divide by pulls for the batching "
                       "ratio)"),
    "pipeline.pulls": ("siddhi_pipeline_meta_pulls_total",
                       "device->host round trips made by pipeline "
                       "drains"),
}

# serving tier (siddhi_tpu/serving/): aggregation rollup + scatter-gather
_AGG_BUCKETS = re.compile(r"^aggregation\.(?P<agg>.+)\.(?P<dur>[a-z]+)"
                          r"\.buckets$")
_AGG_SHARDS = re.compile(r"^aggregation\.(?P<agg>.+)\.shards$")
_AGG_SHARD_WAL = re.compile(r"^aggregation\.(?P<agg>.+)\.shard"
                            r"(?P<shard>\d+)\.wal_batches$")
_AGG_FLUSH_HIST = re.compile(r"^aggregation\.(?P<agg>.+)\.flush_ms$")
_SERVING_QUERY_HIST = re.compile(r"^serving\.query\.(?P<dur>[a-z]+)_ms$")
# sharded keyed steps (parallel/mesh.py): per-shard routed-row gauges
# (key-skew visibility) + exchange/prep latency histogram — fed by BOTH
# the legacy host router (scope "host") and the device-routed path
# (scope = query name)
_SHARD_ROWS = re.compile(r"^shard\.rows\.(?P<scope>.+)\.(?P<shard>\d+)$")
_SHARD_EXCHANGE_HIST = re.compile(r"^shard\.exchange_ms\.(?P<scope>.+)$")
# device join engine (core/join/): per-partition build-side occupancy
# gauges + probe/insert host-latency histograms per join query
_JOIN_PART_ROWS = re.compile(r"^join\.partition_rows\.(?P<query>.+)"
                             r"\.(?P<side>left|right)\.(?P<part>\d+)$")
_JOIN_HIST = re.compile(r"^join\.(?P<kind>probe|insert)_ms\.(?P<query>.+)$")
# critical-path profiler (observability/journey.py): per-query per-stage
# service-time and queueing-time histograms of the batch journey
_STAGE_HIST = re.compile(r"^stage\.(?P<query>.+)\.(?P<stage>[a-z_]+)"
                         r"\.(?P<kind>service|queue)_ms$")
# device-instrument slots (observability/instruments.py): per-query
# last-drained value + capacity gauges and per-batch value histograms,
# slot names anchored to the DEVICE_SLOTS declaration above (query
# names may contain dots — the slot tail is the fixed part)
_DEVICE_SLOT_RX = "|".join(
    re.escape(s) for s in sorted(DEVICE_SLOTS, key=len, reverse=True))
_DEVICE_GAUGE = re.compile(
    r"^device\.(?P<query>.+)\.(?P<slot>" + _DEVICE_SLOT_RX +
    r")(?P<cap>\.capacity)?$")
_DEVICE_HIST = re.compile(
    r"^device\.(?P<query>.+)\.(?P<slot>" + _DEVICE_SLOT_RX + r")$")
# compiled-program cost registry (observability/costmodel.py): one gauge
# per (jit key, metric) on the process registry
_JITCOST_GAUGE = re.compile(
    r"^jitcost\.(?P<key>.+)\.(?P<metric>flops|bytes_accessed|arg_bytes|"
    r"out_bytes|temp_bytes|code_bytes|compile_ms)$")
_JITCOST_HELP = {
    "flops": ("siddhi_jit_cost_flops",
              "XLA cost analysis: floating-point ops per execution of "
              "the compiled program"),
    "bytes_accessed": ("siddhi_jit_cost_bytes_accessed",
                       "XLA cost analysis: bytes read+written per "
                       "execution"),
    "arg_bytes": ("siddhi_jit_cost_arg_bytes",
                  "compiled-program argument buffer bytes"),
    "out_bytes": ("siddhi_jit_cost_out_bytes",
                  "compiled-program output buffer bytes"),
    "temp_bytes": ("siddhi_jit_cost_temp_bytes",
                   "compiled-program temp (scratch) buffer bytes"),
    "code_bytes": ("siddhi_jit_cost_code_bytes",
                   "generated code size in bytes"),
    "compile_ms": ("siddhi_jit_cost_compile_ms",
                   "ahead-of-time capture compile wall ms"),
}
# autopilot (siddhi_tpu/autopilot/): decision counters are dotted
# autopilot.decisions.<knob>.<direction>.<rule> — knob / direction /
# rule segments are code-controlled [a-z0-9_] identifiers (never
# user-named), so the dotted split is unambiguous
_AUTOPILOT_DECISION = re.compile(
    r"^autopilot\.decisions\.(?P<knob>[a-z0-9_]+)"
    r"\.(?P<direction>up|down)\.(?P<reason>[a-z0-9_]+)$")
_AUTOPILOT_COUNTER_FAMILY = {
    "autopilot.ticks": ("siddhi_autopilot_ticks_total",
                        "autopilot observe/decide cycles run"),
    "autopilot.freezes": ("siddhi_autopilot_freezes_total",
                          "autopilot ticks skipped by compile-storm "
                          "backoff (jit compiles still climbing)"),
}
# process-global compiled-program cache (core/util/program_cache.py):
# counters on the process registry; hits are first-call executable
# shares (a hit is a compile that did NOT happen). The size family is
# public: tools/fleet_soak.py greps the exposition for it (R3 keeps the
# literal declared HERE only).
PROGRAM_CACHE_SIZE_FAMILY = "siddhi_program_cache_size"
_PROGRAM_CACHE_COUNTER_FAMILY = {
    "program_cache.hits": (
        "siddhi_program_cache_hits_total",
        "first-call program-cache hits: an equal compiled program "
        "(jaxpr + consts + output tree + backend/sharding witness) was "
        "shared instead of compiled"),
    "program_cache.misses": (
        "siddhi_program_cache_misses_total",
        "first-call program-cache misses: no equal program was live, "
        "this jit compiled and registered as the shared executable"),
    "program_cache.evictions": (
        "siddhi_program_cache_evictions_total",
        "program-cache entries evicted (refcount zero at owner "
        "release, LRU zero-ref at the program_cache_max cap, or a "
        "drain)"),
}
_SERVING_COUNTER_FAMILY = {
    "serving.queries": ("siddhi_serving_queries_total",
                        "on-demand queries admitted by the serving tier"),
    "serving.sheds": ("siddhi_serving_shed_total",
                      "on-demand queries shed at the per-endpoint "
                      "admission cap (HTTP 503)"),
    "serving.shard_rebuilds": ("siddhi_serving_shard_rebuilds_total",
                               "aggregation shards rebuilt from "
                               "checkpoint blob + WAL suffix"),
}
# cluster fabric (siddhi_tpu/cluster/): router-side gauges live exactly
# as long as the ClusterRuntime (remove_gauge in shutdown); per-worker
# names carry the worker index as a LABEL, not a metric name
_CLUSTER_WORKER_GAUGE = re.compile(
    r"^cluster\.worker\.(?P<kind>acked_seq|wal_batches)\.(?P<worker>\d+)$")
_CLUSTER_WORKER_COUNTER = re.compile(
    r"^cluster\.worker\.(?P<kind>respawns|replayed_batches|replay_gaps|"
    r"link_drops)\.(?P<worker>\d+)$")
_CLUSTER_WORKER_GAUGE_HELP = {
    "acked_seq": ("siddhi_cluster_worker_acked_seq",
                  "highest global ingest sequence the worker has acked"),
    "wal_batches": ("siddhi_cluster_worker_wal_batches",
                    "retained router-side ingest-WAL batches for the "
                    "worker (replay suffix; trimmed at checkpoint cuts)"),
}
_CLUSTER_WORKER_COUNTER_HELP = {
    "respawns": ("siddhi_cluster_worker_respawns_total",
                 "worker processes respawned after peer-death detection"),
    "replayed_batches": ("siddhi_cluster_worker_replayed_batches_total",
                         "WAL batches replayed into a recovered worker"),
    "replay_gaps": ("siddhi_cluster_worker_replay_gaps_total",
                    "runs unrecoverable at replay (WAL overflow) — "
                    "released as counted gaps, never silent hangs"),
    "link_drops": ("siddhi_cluster_worker_link_drops_total",
                   "worker link sessions dropped (EOF/error on the "
                   "router-worker socket)"),
}
_CLUSTER_COUNTER_FAMILY = {
    "cluster.ingest_batches": ("siddhi_cluster_ingest_batches_total",
                               "batches sequenced by the router ingest "
                               "front door"),
    "cluster.ingest_rows": ("siddhi_cluster_ingest_rows_total",
                            "rows sequenced by the router ingest front "
                            "door"),
    "cluster.runs_sent": ("siddhi_cluster_runs_sent_total",
                          "contiguous same-owner runs relayed to workers"),
    "cluster.runs_acked": ("siddhi_cluster_runs_acked_total",
                           "runs completed (seq-acked) by workers and "
                           "merged in global order"),
    "cluster.egress_rows": ("siddhi_cluster_egress_rows_total",
                            "output rows re-merged into exact global "
                            "order by the egress stitch"),
    "cluster.duplicate_emits_dropped": (
        "siddhi_cluster_duplicate_emits_dropped_total",
        "replayed emissions for already-merged runs dropped at the "
        "egress (the effectively-once dedup)"),
    "cluster.checkpoints": ("siddhi_cluster_checkpoints_total",
                            "cluster-wide checkpoint barriers completed"),
    "cluster.queries": ("siddhi_cluster_queries_total",
                        "scatter-gather on-demand queries served by the "
                        "cluster router"),
}
_SERVING_HIST_FAMILY = {
    "serving.fanout_ms": ("siddhi_serving_fanout_ms",
                          "scatter fan-out time across aggregation "
                          "shards (ms)"),
    "serving.merge_ms": ("siddhi_serving_merge_ms",
                         "ordered cross-shard rollup merge time (ms)"),
}


def _esc(v: str) -> str:
    """Label-VALUE escaping per the text-format spec: backslash, double
    quote, and line feed (stream/app/query names are user-controlled
    SiddhiQL identifiers — a hostile name must not break the sample
    grammar or inject bogus series)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _esc_help(v: str) -> str:
    """HELP-text escaping per the spec: backslash and line feed only
    (quotes are legal in HELP)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Families:
    """Accumulates samples grouped per metric family so each family's
    ``# TYPE`` header is emitted exactly once, before its samples."""

    def __init__(self):
        self._fam: Dict[str, Tuple[str, str, List[str]]] = {}

    def add(self, family: str, ftype: str, help_: str,
            labels: Dict[str, str], value, suffix: str = ""):
        rec = self._fam.get(family)
        if rec is None:
            rec = self._fam[family] = (ftype, help_, [])
        lbl = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
        lbl = "{" + lbl + "}" if lbl else ""
        rec[2].append(f"{family}{suffix}{lbl} {_fmt(value)}")

    def render(self) -> str:
        lines = []
        for family in sorted(self._fam):
            ftype, help_, samples = self._fam[family]
            lines.append(f"# HELP {family} {_esc_help(help_)}")
            lines.append(f"# TYPE {family} {ftype}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


def _add_histogram(fams: _Families, family: str, help_: str,
                   labels: Dict[str, str], snap: dict) -> None:
    """Render one telemetry histogram snapshot as a Prometheus summary
    (quantile samples + _sum/_count), matching the latency-tracker
    exposition shape."""
    for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
        fams.add(family, "summary", help_,
                 {**labels, "quantile": q}, snap.get(key, 0.0))
    fams.add(family, "summary", help_, labels, snap.get("sum", 0.0),
             suffix="_sum")
    fams.add(family, "summary", help_, labels, snap.get("count", 0),
             suffix="_count")


def app_snapshot(rt) -> dict:
    """JSON-ready metrics for one app runtime."""
    sm = rt.app_context.statistics_manager
    return {
        "app": rt.name,
        "statistics": rt.statistics() if sm is not None else {"level": "off"},
        "telemetry": rt.app_context.telemetry.snapshot(),
    }


def json_snapshot(manager) -> dict:
    t0 = time.perf_counter()
    try:
        return {
            "apps": {name: app_snapshot(rt)
                     for name, rt in sorted(manager.app_runtimes.items())},
            "process": global_registry().snapshot(),
        }
    finally:
        _record_scrape_ms(t0)


def _record_scrape_ms(t0: float) -> None:
    """Scrape self-timing (``siddhi_scrape_ms``): the duration lands in
    the process registry AFTER the snapshot is taken, so each scrape
    reports its predecessors — a scrape crossing its SLO is visible on
    the dashboard scraping it."""
    global_registry().histogram("scrape.ms").record(
        (time.perf_counter() - t0) * 1000.0)


def _add_telemetry(fams: _Families, tel_snapshot: dict, app: str):
    base = {"app": app} if app else {}
    for name, v in sorted(tel_snapshot.get("gauges", {}).items()):
        m = _JUNCTION_GAUGE.match(name)
        if m:
            fams.add(f"siddhi_junction_{m.group('kind')}", "gauge",
                     ("@Async junction queue depth"
                      if m.group("kind") == "queue_depth"
                      else "@Async junction in-flight delivery units"),
                     {**base, "stream": m.group("stream")}, v)
        else:
            m = _FANOUT_GAUGE.match(name)
            if m:
                fams.add("siddhi_fanout_group_size", "gauge",
                         "queries fused into one dispatch per stream batch",
                         {**base, "stream": m.group("stream")}, v)
            else:
                m = _PIPELINE_GAUGE.match(name)
                if m:
                    fams.add("siddhi_pipeline_depth", "gauge",
                             "device batches riding the dispatch pipeline",
                             {**base, "query": m.group("query")}, v)
                elif _AGG_SHARD_WAL.match(name):
                    m = _AGG_SHARD_WAL.match(name)
                    fams.add("siddhi_aggregation_shard_wal_batches", "gauge",
                             "retained per-shard WAL batches (rebuild "
                             "replay suffix)",
                             {**base, "name": m.group("agg"),
                              "shard": m.group("shard")}, v)
                elif _AGG_SHARDS.match(name):
                    m = _AGG_SHARDS.match(name)
                    fams.add("siddhi_aggregation_shards", "gauge",
                             "in-process key shards of the aggregation "
                             "rollup state",
                             {**base, "name": m.group("agg")}, v)
                elif _AGG_BUCKETS.match(name):
                    m = _AGG_BUCKETS.match(name)
                    fams.add("siddhi_aggregation_buckets", "gauge",
                             "live rollup buckets per granularity",
                             {**base, "name": m.group("agg"),
                              "duration": m.group("dur")}, v)
                elif _SHARD_ROWS.match(name):
                    m = _SHARD_ROWS.match(name)
                    fams.add("siddhi_shard_rows", "gauge",
                             "batch rows routed to each key shard (last "
                             "batch; skew shows as imbalance)",
                             {**base, "query": m.group("scope"),
                              "shard": m.group("shard")}, v)
                elif _JOIN_PART_ROWS.match(name):
                    m = _JOIN_PART_ROWS.match(name)
                    fams.add("siddhi_join_partition_rows", "gauge",
                             "live build-side rows per join hash "
                             "partition (skew shows as imbalance)",
                             {**base, "query": m.group("query"),
                              "side": m.group("side"),
                              "partition": m.group("part")}, v)
                elif _QUOTA_GAUGE.match(name):
                    m = _QUOTA_GAUGE.match(name)
                    labels = {**base, "resource": m.group("resource")}
                    if m.group("stream"):
                        labels["stream"] = m.group("stream")
                    fams.add("siddhi_quota_utilization", "gauge",
                             "fraction of the app's overload quota in "
                             "use (queue depth / pipeline entries / "
                             "device-memory budget)", labels, v)
                elif _DEVICE_GAUGE.match(name):
                    m = _DEVICE_GAUGE.match(name)
                    if m.group("cap"):
                        fams.add("siddhi_device_instrument_capacity",
                                 "gauge",
                                 "capacity denominator of a device "
                                 "instrument slot (ring size, Wp, "
                                 "rows_per_shard, ...)",
                                 {**base, "query": m.group("query"),
                                  "slot": m.group("slot")}, v)
                    else:
                        fams.add("siddhi_device_instrument", "gauge",
                                 "last drained device-instrument value "
                                 "(rides the per-batch meta pull — "
                                 "zero extra device transfers)",
                                 {**base, "query": m.group("query"),
                                  "slot": m.group("slot")}, v)
                elif _JITCOST_GAUGE.match(name):
                    m = _JITCOST_GAUGE.match(name)
                    family, help_ = _JITCOST_HELP[m.group("metric")]
                    fams.add(family, "gauge", help_,
                             {**base, "key": m.group("key")}, v)
                elif _INGEST_POOL_GAUGE.match(name):
                    m = _INGEST_POOL_GAUGE.match(name)
                    kind = m.group("kind")
                    fams.add(f"siddhi_ingest_pool_{kind}", "gauge",
                             {"queue_depth": "sub-batch tasks queued on "
                                             "the ingest pack pool",
                              "workers": "live ingest pack-pool worker "
                                         "threads",
                              "utilization": "fraction of ingest pack "
                                             "workers busy"}[kind],
                             base, v)
                elif name == "autopilot.mode":
                    fams.add("siddhi_autopilot_mode", "gauge",
                             "closed-loop controller mode per app "
                             "(0=off, 1=dry_run, 2=on)", base, v)
                elif name == "program_cache.size":
                    fams.add(PROGRAM_CACHE_SIZE_FAMILY, "gauge",
                             "live entries in the process-global "
                             "compiled-program cache (distinct shared "
                             "executables)", base, v)
                elif name == "cluster.workers.live":
                    fams.add("siddhi_cluster_workers_live", "gauge",
                             "worker processes with a live attached link "
                             "(out of cluster_workers)", base, v)
                elif _CLUSTER_WORKER_GAUGE.match(name):
                    m = _CLUSTER_WORKER_GAUGE.match(name)
                    family, help_ = _CLUSTER_WORKER_GAUGE_HELP[
                        m.group("kind")]
                    fams.add(family, "gauge", help_,
                             {**base, "worker": m.group("worker")}, v)
                elif name in ("serving.pool.pending", "serving.pool.active"):
                    kind = name.rsplit(".", 1)[1]
                    fams.add(f"siddhi_serving_pool_{kind}", "gauge",
                             ("on-demand queries admitted and not yet "
                              "finished" if kind == "pending"
                              else "on-demand queries currently "
                                   "executing"), base, v)
                else:
                    fams.add("siddhi_gauge", "gauge",
                             "registered telemetry gauge",
                             {**base, "name": name}, v)
    for name, v in sorted(tel_snapshot.get("counters", {}).items()):
        m = _JUNCTION_STALLS.match(name)
        if m:
            fams.add("siddhi_junction_backpressure_stalls_total", "counter",
                     "producer sends that blocked on a full @Async queue",
                     {**base, "stream": m.group("stream")}, v)
            continue
        m = _JUNCTION_SHEDS.match(name)
        if m:
            fams.add("siddhi_junction_shed_events_total", "counter",
                     "events shed by overload admission (shed_oldest / "
                     "shed_newest past the queue quota)",
                     {**base, "stream": m.group("stream")}, v)
            continue
        m = _JUNCTION_TIMEOUTS.match(name)
        if m:
            fams.add("siddhi_junction_enqueue_timeouts_total", "counter",
                     "bounded enqueue waits that timed out and escalated "
                     "to the supervisor",
                     {**base, "stream": m.group("stream")}, v)
            continue
        m = _ELIGIBILITY_COUNTER.match(name)
        if m:
            fams.add("siddhi_eligibility_total", "counter",
                     "build-time strategy-eligibility census: queries "
                     "classified per surface (route / fusion / "
                     "join_engine / join_pipeline) with stable reason "
                     "codes (core/eligibility.py; ELIGIBLE = the "
                     "strategy applies)",
                     {**base, "surface": m.group("surface"),
                      "code": m.group("code"),
                      "query": m.group("query")}, v)
            continue
        m = _FANOUT_COUNTER.match(name)
        if m:
            fams.add(f"siddhi_fanout_{m.group('kind')}_total", "counter",
                     ("fused fan-out device dispatches (one per group "
                      "per stream batch)"
                      if m.group("kind") == "dispatches"
                      else "fused fan-out combined __meta__ round trips"),
                     {**base, "stream": m.group("stream")}, v)
            continue
        m = _AUTOPILOT_DECISION.match(name)
        if m:
            fams.add("siddhi_autopilot_decisions_total", "counter",
                     "autopilot policy decisions (includes dry_run and "
                     "cooldown/damped-blocked decisions; every entry in "
                     "the GET /autopilot decision log counts here once)",
                     {**base, "knob": m.group("knob"),
                      "direction": m.group("direction"),
                      "reason": m.group("reason")}, v)
            continue
        m = _CLUSTER_WORKER_COUNTER.match(name)
        if m:
            family, help_ = _CLUSTER_WORKER_COUNTER_HELP[m.group("kind")]
            fams.add(family, "counter", help_,
                     {**base, "worker": m.group("worker")}, v)
            continue
        fam = _PIPELINE_COUNTER_FAMILY.get(name)
        if fam is None:
            fam = _SERVING_COUNTER_FAMILY.get(name)
        if fam is None:
            fam = _CLUSTER_COUNTER_FAMILY.get(name)
        if fam is None:
            fam = _INGEST_COUNTER_FAMILY.get(name)
        if fam is None:
            fam = _AUTOPILOT_COUNTER_FAMILY.get(name)
        if fam is None:
            fam = _PROGRAM_CACHE_COUNTER_FAMILY.get(name)
        if fam is not None:
            fams.add(fam[0], "counter", fam[1], base, v)
            continue
        fams.add("siddhi_counter_total", "counter",
                 "named event counter",
                 {**base, "name": name}, v)
    for name, snap in sorted(tel_snapshot.get("histograms", {}).items()):
        fam = _SERVING_HIST_FAMILY.get(name) or _INGEST_HIST_FAMILY.get(name)
        labels = dict(base)
        if fam is not None:
            family, help_ = fam
        else:
            m = _AGG_FLUSH_HIST.match(name)
            if m:
                family = "siddhi_aggregation_flush_ms"
                help_ = "aggregation ingest fold latency per batch (ms)"
                labels["name"] = m.group("agg")
            elif _SHARD_EXCHANGE_HIST.match(name):
                m = _SHARD_EXCHANGE_HIST.match(name)
                family = "siddhi_shard_exchange_ms"
                help_ = ("host time spent routing/prepping one batch for "
                         "the sharded keyed step (ms; device-routed path "
                         "pays only pad+precheck here)")
                labels["query"] = m.group("scope")
            elif _JOIN_HIST.match(name):
                m = _JOIN_HIST.match(name)
                family = f"siddhi_join_{m.group('kind')}_ms"
                help_ = (
                    "host prep+pack time per join side batch (ms)"
                    if m.group("kind") == "insert"
                    else "probe dispatch+finish time per join side "
                         "batch (ms)")
                labels["query"] = m.group("query")
            elif _STAGE_HIST.match(name):
                m = _STAGE_HIST.match(name)
                if m.group("kind") == "service":
                    family = "siddhi_stage_ms"
                    help_ = ("batch-journey per-stage service time (ms) "
                             "— see observability/journey.py stage "
                             "glossary")
                else:
                    family = "siddhi_stage_queue_ms"
                    help_ = ("batch-journey per-stage queueing/slack "
                             "time (ms)")
                labels["query"] = m.group("query")
                labels["stage"] = m.group("stage")
            elif _DEVICE_HIST.match(name):
                m = _DEVICE_HIST.match(name)
                family = "siddhi_device_instrument_value"
                help_ = ("per-batch device-instrument slot value "
                         "(observability/instruments.py slot glossary)")
                labels["query"] = m.group("query")
                labels["slot"] = m.group("slot")
            elif name == "scrape.ms":
                family = "siddhi_scrape_ms"
                help_ = "/metrics scrape self-timing (ms)"
            else:
                m = _SERVING_QUERY_HIST.match(name)
                if m:
                    family = "siddhi_serving_query_ms"
                    help_ = ("on-demand store-query latency per "
                             "granularity (ms)")
                    labels["granularity"] = m.group("dur")
                else:
                    family = "siddhi_histogram_ms"
                    help_ = "registered telemetry histogram (ms)"
                    labels["name"] = name
        _add_histogram(fams, family, help_, labels, snap)
    for key, rec in sorted(tel_snapshot.get("jit", {}).items()):
        kl = {**base, "key": key}
        fams.add("siddhi_jit_compiles_total", "counter",
                 "jitted step functions compiled", kl, rec["compiles"])
        fams.add("siddhi_jit_compile_ms_total", "counter",
                 "wall-clock ms spent in first-call jit compiles", kl,
                 rec["compile_ms"])
        fams.add("siddhi_jit_cache_hits_total", "counter",
                 "jitted step cache hits", kl, rec["hits"])


def _add_statistics(fams: _Families, rt):
    app = rt.name
    sm = rt.app_context.statistics_manager
    report = rt.statistics() if sm is not None else {"level": "off"}
    fams.add("siddhi_statistics_level", "gauge",
             "statistics level (0=off 1=basic 2=detail)",
             {"app": app},
             {"off": 0, "basic": 1, "detail": 2}.get(report.get("level"), 0))
    for name, t in sorted(report.get("throughput", {}).items()):
        fams.add("siddhi_stream_events_total", "counter",
                 "events published through the stream junction",
                 {"app": app, "stream": name}, t["events"])
        fams.add("siddhi_stream_batches_total", "counter",
                 "batches published through the stream junction",
                 {"app": app, "stream": name}, t["batches"])
    for name, lat in sorted(report.get("latency", {}).items()):
        labels = {"app": app, "name": name}
        for q in ("0.5", "0.95", "0.99"):
            key = {"0.5": "p50_ms", "0.95": "p95_ms", "0.99": "p99_ms"}[q]
            fams.add("siddhi_latency_ms", "summary",
                     "per-stage batch processing latency (ms)",
                     {**labels, "quantile": q}, lat.get(key, 0.0))
        fams.add("siddhi_latency_ms", "summary",
                 "per-stage batch processing latency (ms)",
                 labels, lat.get("total_ms", 0.0), suffix="_sum")
        fams.add("siddhi_latency_ms", "summary",
                 "per-stage batch processing latency (ms)",
                 labels, lat["batches"], suffix="_count")
        fams.add("siddhi_latency_ms_max", "gauge",
                 "max batch processing latency (ms)",
                 labels, lat.get("max_ms", 0.0))
    counters = dict(report.get("counters", {}))
    for name in RESILIENCE_COUNTERS:
        counters.setdefault(name, 0)
    for name, v in sorted(counters.items()):
        fams.add("siddhi_counter_total", "counter", "named event counter",
                 {"app": app, "name": name}, v)
    for name, v in sorted(report.get("memory_bytes", {}).items()):
        fams.add("siddhi_state_memory_bytes", "gauge",
                 "dense state footprint (bytes)",
                 {"app": app, "name": name}, v)
    for name, v in sorted(report.get("buffered_events", {}).items()):
        fams.add("siddhi_buffered_events", "gauge",
                 "pending buffered events/batches",
                 {"app": app, "name": name}, v)


def prometheus_text(manager, app_name=None) -> str:
    """Prometheus text exposition for every app (or one app) plus the
    process-global telemetry. Scrape hygiene: this function takes NO app
    barrier and makes no device pulls beyond registered gauges (which
    are themselves cached or host-side — a wedged worker or a busy app
    must never stall a scrape), and times itself into
    ``siddhi_scrape_ms``."""
    t0 = time.perf_counter()
    try:
        fams = _Families()
        runtimes = manager.app_runtimes
        if app_name is not None:
            rt = runtimes.get(app_name)
            if rt is None:
                raise KeyError(f"app '{app_name}' is not deployed")
            runtimes = {app_name: rt}
        for name in sorted(runtimes):
            rt = runtimes[name]
            _add_statistics(fams, rt)
            _add_telemetry(fams, rt.app_context.telemetry.snapshot(), name)
        _add_telemetry(fams, global_registry().snapshot(), "")
        return fams.render()
    finally:
        _record_scrape_ms(t0)
