"""Compiled-program cost registry: what did XLA actually build?

ROADMAP item 2 (a process-wide compiled-program cache across tenant
apps) needs a BEFORE picture: how many programs does a fleet compile,
how many are duplicates, and what does each cost? ROADMAP item 3's
probe daemon needs a machine-readable device-cost capture the moment
the TPU tunnel revives. This registry is both: when enabled, the first
compile of every jit key (``telemetry.InstrumentedJit``) also captures

- ``compiled.cost_analysis()``  — flops + bytes accessed per execution,
- ``compiled.memory_analysis()``— argument/output/temp/code bytes
  (the XLA buffer-assignment peak picture),
- a **jaxpr fingerprint** — sha1 over the traced jaxpr text; two keys
  with equal fingerprints are structurally identical programs, i.e.
  candidates for the semantic-overlap dedup of "On the Semantic Overlap
  of Operators in Stream Processing Engines" (PAPERS.md). The fused
  fan-out dedup (PR 3) additionally proves constants/state equal before
  sharing — the fingerprint is the cheap superset estimate, so the
  duplicate clusters here bound the cross-app cache win from above.

Exported as ``jitcost.<key>.<metric>`` process gauges (rendered as the
``siddhi_jit_cost_*{key}`` families on ``GET /metrics``) and as JSON at
``GET /programs`` with fingerprint-duplicate clusters.

Cost of capture: tracing + ONE extra ahead-of-time XLA compile per
(key, first shape) — jax's jit cache and the AOT path do not share
executables, so profiling mode roughly doubles first-call compile
time. Steady-state throughput is untouched (capture runs once, before
the first execution, never on the hot path), but the default is OFF:
enable per app with ``siddhi_tpu.profile_costs: true``, process-wide
with ``SIDDHI_TPU_PROFILE_COSTS=1`` or ``POST /profile/costs/start``.
Capture happens BEFORE the first real call on purpose: the step jits
donate their state argument, and a post-call trace would read deleted
buffers.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

_enable_count = 0
_lock = threading.RLock()


def enabled() -> bool:
    if _enable_count > 0:
        return True
    # typed env read (knob discipline: junk spellings raise naming the
    # variable); re-checked per call so tests can flip it mid-process —
    # called once per first-compile, never on the steady hot path
    from siddhi_tpu.core.util.knobs import env_knob

    return bool(env_knob("SIDDHI_TPU_PROFILE_COSTS", "bool", False))


def enable() -> None:
    """Refcounted process-wide enable (one ``disable()`` per
    ``enable()``); the env spelling is an independent override."""
    global _enable_count
    with _lock:
        _enable_count += 1


def disable(force: bool = False) -> None:
    global _enable_count
    with _lock:
        _enable_count = 0 if force else max(0, _enable_count - 1)


@dataclass
class ProgramRecord:
    """One compiled program (per jit key; re-jits on capacity growth
    overwrite their key with the fresh shape's capture)."""

    key: str
    fingerprint: str            # sha1[:16] of the traced jaxpr text
    platform: str = ""
    flops: float = 0.0
    bytes_accessed: float = 0.0
    arg_bytes: int = 0
    out_bytes: int = 0
    temp_bytes: int = 0
    code_bytes: int = 0
    compile_ms: float = 0.0     # the AOT capture compile (not the jit's)
    captures: int = 1           # how many times this key re-captured
    shared: bool = False        # analysis reused from an equal program
    error: Optional[str] = None
    extra: Dict[str, float] = field(default_factory=dict)


class CostRegistry:
    """Process-global program registry (``registry()``); the capture is
    fed by ``InstrumentedJit`` and read by ``GET /programs`` plus the
    ``siddhi_jit_cost_*`` exposition."""

    _GAUGE_METRICS = ("flops", "bytes_accessed", "arg_bytes", "out_bytes",
                      "temp_bytes", "code_bytes", "compile_ms")

    def __init__(self):
        self._lock = threading.RLock()
        self._programs: Dict[str, ProgramRecord] = {}

    # ------------------------------------------------------------ capture

    def capture(self, key: str, jitted, args, traced=None,
                shared: bool = False) -> Optional[ProgramRecord]:
        """Fingerprint + cost/memory analysis for one jitted callable
        about to run its first call. Never raises: a capture failure
        (non-jit callable, backend without analysis support) records the
        error and the engine runs on.

        ``traced`` reuses an AOT trace the program cache already made
        (one trace per first call, not two). ``shared=True`` means the
        callable is a program-cache HIT: the analysis is copied from an
        already-captured equal-fingerprint record instead of paying —
        and being double-counted as — a second AOT compile; only when
        no donor record exists (the donor app compiled with profiling
        off) does the capture fall through to a real AOT compile."""
        rec: Optional[ProgramRecord] = None
        try:
            if traced is None:
                trace = getattr(jitted, "trace", None)
                if trace is None:
                    return None     # not a jax.jit callable
                traced = trace(*args)
            fp = hashlib.sha1(
                str(traced.jaxpr).encode()).hexdigest()[:16]
            rec = ProgramRecord(key=key, fingerprint=fp)
            if shared:
                donor = self._donor(fp, key)
                if donor is not None:
                    for metric in self._GAUGE_METRICS:
                        setattr(rec, metric, getattr(donor, metric))
                    rec.platform = donor.platform
                    rec.compile_ms = 0.0    # no AOT compile happened
                    rec.shared = True
                    self._store(rec)
                    return rec
            t0 = time.perf_counter()
            compiled = traced.lower().compile()
            rec.compile_ms = (time.perf_counter() - t0) * 1000.0
            try:
                import jax

                rec.platform = jax.devices()[0].platform
            except Exception:  # noqa: BLE001 — label only
                pass
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                rec.flops = float(ca.get("flops", 0.0))
                rec.bytes_accessed = float(ca.get("bytes accessed", 0.0))
            ma = compiled.memory_analysis()
            if ma is not None:
                rec.arg_bytes = int(
                    getattr(ma, "argument_size_in_bytes", 0))
                rec.out_bytes = int(getattr(ma, "output_size_in_bytes", 0))
                rec.temp_bytes = int(getattr(ma, "temp_size_in_bytes", 0))
                rec.code_bytes = int(
                    getattr(ma, "generated_code_size_in_bytes", 0))
        except Exception as e:  # noqa: BLE001 — profiling must not break
            log.debug("cost capture failed for jit key '%s': %r", key, e)
            if rec is None:
                return None
            rec.error = repr(e)
        self._store(rec)
        return rec

    def _donor(self, fp: str, key: str) -> Optional[ProgramRecord]:
        """A clean already-captured record of the same fingerprint under
        a DIFFERENT key — the analysis source for a shared capture."""
        with self._lock:
            for rec in self._programs.values():
                if (rec.fingerprint == fp and rec.key != key
                        and rec.error is None):
                    return rec
        return None

    def _store(self, rec: ProgramRecord) -> None:
        with self._lock:
            prev = self._programs.get(rec.key)
            if prev is not None:
                rec.captures = prev.captures + 1
            self._programs[rec.key] = rec
        self._register_gauges(rec)

    def _register_gauges(self, rec: ProgramRecord) -> None:
        from siddhi_tpu.observability.telemetry import global_registry

        tel = global_registry()
        for metric in self._GAUGE_METRICS:
            # closure over the registry + key, not the record: a re-jit's
            # re-capture must be what the next scrape reads
            tel.gauge(f"jitcost.{rec.key}.{metric}",
                      lambda k=rec.key, m=metric: getattr(
                          self._programs.get(k), m, 0.0) or 0.0)

    # ------------------------------------------------------------ reading

    def programs(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._programs.values())

    def clusters(self) -> List[dict]:
        """Programs grouped by fingerprint, largest first — a cluster
        with more than one key is compiled more than once for (at least
        structurally) the same computation."""
        by_fp: Dict[str, List[str]] = {}
        for rec in self.programs():
            by_fp.setdefault(rec.fingerprint, []).append(rec.key)
        return [{"fingerprint": fp, "keys": sorted(keys),
                 "size": len(keys), "duplicates": len(keys) - 1}
                for fp, keys in sorted(by_fp.items(),
                                       key=lambda kv: (-len(kv[1]), kv[0]))]

    def snapshot(self) -> dict:
        """The ``GET /programs`` payload."""
        programs = sorted(self.programs(), key=lambda r: r.key)
        clusters = self.clusters()
        return {
            "enabled": enabled(),
            "programs": [asdict(r) for r in programs],
            "clusters": clusters,
            "unique_fingerprints": len(clusters),
            "duplicate_clusters": sum(1 for c in clusters if c["size"] > 1),
            "duplicate_programs": sum(c["duplicates"] for c in clusters),
        }

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()


_REGISTRY = CostRegistry()


def registry() -> CostRegistry:
    return _REGISTRY
