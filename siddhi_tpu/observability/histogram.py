"""Fixed-bucket log-spaced latency histograms (HDR-style).

The reference hangs metrics-core ``Histogram``s (exponentially-decaying
reservoirs) off junctions and query runtimes; the equivalent here is a
fixed array of log-spaced buckets — O(1) lock-free-under-the-GIL record,
O(buckets) quantile read, zero allocation after construction, and a
bounded, deterministic memory footprint that snapshots trivially.

Bucket ``i`` covers ``(min_value * g^(i-1), min_value * g^i]`` with
bucket 0 catching everything at or below ``min_value``; quantiles report
the geometric midpoint of the hit bucket (clamped to the observed
min/max), so the relative error is bounded by ``sqrt(g) - 1`` — ~3.5%
at the default growth of 1.07, comparable to a 2-significant-digit HDR
histogram. The default domain (1 us .. ~100 s in ms units) spans every
latency this engine produces, from a host dict probe to a cold jit
compile behind the axon tunnel.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

_DEFAULT_MIN = 1e-3     # 1 us, in ms units
_DEFAULT_GROWTH = 1.07
_DEFAULT_BUCKETS = 288  # 1e-3 * 1.07^287 ≈ 2.7e5 ms ≈ 4.5 min


class Histogram:
    """Log-bucket histogram of non-negative values (ms by convention)."""

    __slots__ = ("counts", "count", "total", "min_seen", "max_seen",
                 "min_value", "growth", "_inv_log_g", "n_buckets")

    def __init__(self, min_value: float = _DEFAULT_MIN,
                 growth: float = _DEFAULT_GROWTH,
                 n_buckets: int = _DEFAULT_BUCKETS):
        if not (growth > 1.0 and min_value > 0 and n_buckets > 1):
            raise ValueError("Histogram needs growth > 1, min_value > 0, "
                             "n_buckets > 1")
        self.min_value = float(min_value)
        self.growth = float(growth)
        self.n_buckets = int(n_buckets)
        self._inv_log_g = 1.0 / math.log(self.growth)
        self.counts: List[int] = [0] * self.n_buckets
        self.count = 0
        self.total = 0.0
        self.min_seen = math.inf
        self.max_seen = 0.0

    # ------------------------------------------------------------- record

    def record(self, value: float) -> None:
        """O(1): one log, one clamp, one increment (GIL-atomic enough for
        telemetry — a lost increment under a rare race skews a count by
        one, never corrupts the structure)."""
        v = float(value)
        if v < 0 or v != v:      # negative / NaN: clock skew artifacts
            return
        if v <= self.min_value:
            i = 0
        else:
            i = int(math.log(v / self.min_value) * self._inv_log_g) + 1
            if i >= self.n_buckets:
                i = self.n_buckets - 1
        self.counts[i] += 1
        self.count += 1
        self.total += v
        if v < self.min_seen:
            self.min_seen = v
        if v > self.max_seen:
            self.max_seen = v

    # -------------------------------------------------------------- reads

    def _bucket_mid(self, i: int) -> float:
        if i == 0:
            mid = self.min_value * 0.5
        else:
            # geometric midpoint of (min * g^(i-1), min * g^i]
            mid = self.min_value * self.growth ** (i - 0.5)
        if self.count:
            mid = min(max(mid, self.min_seen), self.max_seen)
        return mid

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]; 0.0 when empty."""
        if self.count == 0:
            return 0.0
        if q <= 0:
            return self.min_seen
        if q >= 1:
            return self.max_seen
        target = max(1, math.ceil(q * self.count))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self._bucket_mid(i)
        return self.max_seen   # pragma: no cover — counts always sum up

    def percentiles(self) -> Dict[str, float]:
        return {
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def snapshot(self) -> dict:
        out = {"count": self.count, "sum": self.total,
               "min": self.min_seen if self.count else 0.0,
               "max": self.max_seen}
        out.update(self.percentiles())
        return out

    def reset(self) -> None:
        self.counts = [0] * self.n_buckets
        self.count = 0
        self.total = 0.0
        self.min_seen = math.inf
        self.max_seen = 0.0

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram with IDENTICAL bucketing into this one
        (per-shard aggregation)."""
        if (other.n_buckets != self.n_buckets
                or other.growth != self.growth
                or other.min_value != self.min_value):
            raise ValueError("cannot merge histograms with different buckets")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        self.min_seen = min(self.min_seen, other.min_seen)
        self.max_seen = max(self.max_seen, other.max_seen)


def percentile_bounds(hist: Histogram) -> Optional[dict]:
    """Convenience for reports: None when empty, snapshot otherwise."""
    return hist.snapshot() if hist.count else None
