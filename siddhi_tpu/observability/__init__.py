"""Observability subsystem: spans, percentile histograms, telemetry, export.

The reference engine ships first-class runtime statistics (metrics-core
behind ``@app:statistics`` — ``siddhi-core/pom.xml:79``,
``SiddhiStatisticsManager``); our ``StatisticsManager`` covered counters
and *average* latencies only. Every PERF.md decision so far (the
p99-vs-batch cliff, the router eating ~75% of single-shard throughput)
hinged on tail latency and per-stage attribution, which averages cannot
show — and "Scaling Ordered Stream Processing on Shared-Memory
Multicores" (PAPERS.md) makes the same point for ordered pipelines:
diagnosis needs per-stage queue and latency instrumentation. Four parts:

- ``tracing``:   lightweight structured spans (``span("compile")``,
                 ``span("jit", key=...)``) — nested, thread-safe,
                 ring-buffered, exported as Chrome-trace JSON
                 (``chrome://tracing`` / Perfetto). Wired through
                 compile → plan → jit → junction dispatch → query step →
                 sink publish → persist.
- ``histogram``: fixed-bucket log-spaced (HDR-style) latency histograms
                 with p50/p95/p99, embedded in ``LatencyTracker`` so the
                 query/join/NFA runtimes, the @Async junction batcher,
                 and snapshot persist all gain tails for free.
- ``telemetry``: gauges (@Async queue depth, in-flight batches, WAL
                 size, outstanding cluster pulls), counters
                 (backpressure stalls), and jit-compile events (count,
                 wall-ms, cache hit/miss) — one registry per app plus a
                 process-global one for context-free sites.
- ``export``:    Prometheus text exposition + JSON snapshot, served at
                 ``GET /metrics[/{app}]`` on the REST service
                 (``service/rest.py``), with ``POST /trace/start|stop``
                 dumping a span file.

Always-on-capable: ``tools/obs_overhead.py`` holds the e2e throughput
with full instrumentation at >= 0.9x uninstrumented (PERF.md).
"""

from siddhi_tpu.observability.histogram import Histogram
from siddhi_tpu.observability.telemetry import (
    TelemetryRegistry,
    global_registry,
)
from siddhi_tpu.observability.tracing import TRACER, Tracer, span

__all__ = [
    "Histogram",
    "TRACER",
    "TelemetryRegistry",
    "Tracer",
    "global_registry",
    "span",
]
