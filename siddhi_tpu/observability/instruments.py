"""Device-instrument registry: telemetry slots that ride the meta vector.

PR 7 taught the device-routed step to append ``[route_overflow,
rows_0..n-1]`` behind the standard ``[overflow, notify, count]`` meta
prefix, and PR 9 taught the join engine to append its cross-stream
sequence number — two ad-hoc suffix layouts, each with its own
hand-written drain decoder (``runtime._routed_meta_check``,
``join_runtime._seq_check``). Meanwhile every device-resident signal the
adaptive loops need next — window ring occupancy, per-partition join
directory fill, NFA active-run counts, routed-row skew — was either
invisible or reconstructed by host mirrors, and the one device-truth
scrape surface (``JoinEngineState.partition_occupancy``) pulled device
state per scrape behind a 0.25 s cache.

This module generalizes both mechanisms into ONE declarative spec:

- a step builder declares its instrument slots
  (``QueryRuntime.instrument_slots()`` -> ordered ``[Slot]``);
- the jitted step computes each slot from state it already holds and
  appends the values behind the standard 3-lane prefix (the meta pull
  already happens per batch, so device truth costs ZERO additional host
  transfers and near-zero device work);
- the CompletionPump drain (and the synchronous tail) decodes the
  suffix by the same spec: ``check`` slots run structural consumers
  (route-overflow raise, join seq verification), data slots feed
  per-query ``device.<query>.<slot>`` telemetry histograms/gauges plus
  a host-side last-drained cache that scrape surfaces read with zero
  device pulls.

Gating: the typed knob ``siddhi_tpu.profile_device_instruments``
(default ON). Off reproduces today's meta layouts bit-for-bit — only
the structural slots (route overflow/rows, join seq) remain, in their
exact pre-existing lanes. The process-wide collector (the recent-
readings ring below) is refcounted per app runtime like
``profile_journeys``: enabled at ``start()``, released at
``shutdown()``.

graftlint R6 (``analysis/rules_instruments.py``) keeps the spec closed:
every declared slot name must map to the ``DEVICE_SLOTS`` /
``DEVICE_CHECK_SLOTS`` declarations in ``observability/export.py`` and
to a drain consumer, bidirectionally.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

# data slot name -> human-readable structure label, used by
# journey.critical_path_report to NAME the saturated device structure
# ("join right side partition fill p99 = 0.97 of Wp")
SLOT_LABELS: Dict[str, str] = {
    "win_fill": "window ring fill",
    "groups": "distinct groups touched",
    "nfa_runs": "NFA active runs",
    "shard_rows": "shard routed rows",
    "route_residual": "exchange residual capacity",
    "fill.left": "join left side partition fill",
    "fill.right": "join right side partition fill",
}

# data slot name -> the name of its capacity denominator (the knob-ish
# quantity the report quotes the saturation against)
SLOT_CAP_NAMES: Dict[str, str] = {
    "win_fill": "window capacity",
    "groups": "key capacity",
    "nfa_runs": "nfa slots",
    "shard_rows": "rows_per_shard",
    "route_residual": "rows_per_shard",
    "fill.left": "Wp",
    "fill.right": "Wp",
}

# slots where saturation means the value approaches ZERO (a residual),
# not the capacity — the report's ratio inverts for these
RESIDUAL_SLOTS = ("route_residual",)

_DEFAULT_RING = 2048


class Slot:
    """One declared instrument slot of a step's meta suffix.

    ``width`` is the number of int64 meta lanes it occupies (1 for
    scalars; n for per-shard vectors, P for per-partition fills).
    ``kind``: ``"check"`` slots are structural — consumed by the
    runtime's ``_consume_check_slot`` hook (route-overflow raise, join
    seq verification) and present regardless of the knob; data slots
    (``"gauge"``) feed ``device.<query>.<slot>`` telemetry. ``reduce``
    tells the device-routed wrapper how to aggregate an inner step's
    per-shard lane across the mesh (``sum`` for counts owned by one
    shard each, ``max`` for fill levels)."""

    __slots__ = ("name", "width", "kind", "reduce")

    def __init__(self, name: str, width: int = 1, kind: str = "gauge",
                 reduce: str = "sum"):
        self.name = name
        self.width = int(width)
        self.kind = kind
        self.reduce = reduce

    def __repr__(self):  # pragma: no cover — debugging aid
        return (f"Slot({self.name!r}, width={self.width}, "
                f"kind={self.kind!r})")


# ------------------------------------------------------- process collector

_ENABLED = False
_enable_count = 0
_lock = threading.RLock()
# recent drained readings: (app, query, slot, value, capacity) dicts —
# bounded, reset on first enable (tests/tools introspection surface)
_RING: deque = deque(maxlen=_DEFAULT_RING)


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    """Refcounted process-wide enable (one ``disable()`` per
    ``enable()``; app runtimes whose ``profile_device_instruments``
    knob is on hold one ref for their lifetime, like
    ``profile_journeys``)."""
    global _ENABLED, _enable_count
    with _lock:
        _enable_count += 1
        if not _ENABLED:
            _RING.clear()
            _ENABLED = True


def disable(force: bool = False) -> None:
    global _ENABLED, _enable_count
    with _lock:
        _enable_count = 0 if force else max(0, _enable_count - 1)
        if _enable_count == 0:
            _ENABLED = False


def ring() -> list:
    """Snapshot of recent drained instrument readings (newest last)."""
    with _lock:
        return list(_RING)


def app_instruments_on(app_context) -> bool:
    """Is the instrument suffix enabled for this app? Read at STEP BUILD
    time and at drain time — both sides see the same per-app knob, so
    the compiled layout and the decoder cannot disagree."""
    return bool(getattr(app_context, "profile_device_instruments", True))


# -------------------------------------------------------------- recording

def summary_value(vals: np.ndarray) -> float:
    """The scalar a multi-lane slot reports into its histogram/gauge:
    the MAX lane (skew/saturation is what the signal is for)."""
    return float(vals.max()) if vals.size > 1 else float(vals[0])


def record(runtime, slot: Slot, vals: np.ndarray,
           capacity: Optional[float] = None) -> None:
    """Drain-side sink of one data slot: feed the per-query
    ``device.<query>.<slot>`` histogram, lazily register the last-value
    (and capacity) gauges, and remember the raw lanes on the runtime
    (``_instr_last``) for zero-pull scrape surfaces like
    ``partition_occupancy``. Called once per drained batch per slot —
    a handful of dict writes and one O(1) histogram record."""
    tel = getattr(runtime.app_context, "telemetry", None)
    if tel is None:
        return
    q = runtime.name
    val = summary_value(vals)
    tel.histogram(f"device.{q}.{slot.name}").record(val)
    if capacity is not None:
        runtime._instr_caps[slot.name] = float(capacity)
    if slot.name not in runtime._instr_gauged:
        runtime._instr_gauged.add(slot.name)
        tel.gauge(f"device.{q}.{slot.name}",
                  lambda r=runtime, s=slot.name: _last_value(r, s))
        if capacity is not None:
            tel.gauge(f"device.{q}.{slot.name}.capacity",
                      lambda r=runtime, s=slot.name:
                      float(r._instr_caps.get(s, 0.0)))
    if _ENABLED:
        with _lock:
            _RING.append({
                "app": getattr(runtime.app_context, "name", ""),
                "query": q, "slot": slot.name,
                "value": val, "capacity": capacity,
            })


def _last_value(runtime, slot_name: str) -> float:
    vals = runtime._instr_last.get(slot_name)
    if vals is None:
        return 0.0
    return summary_value(np.asarray(vals))
