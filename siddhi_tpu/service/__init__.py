from siddhi_tpu.service.rest import SiddhiRestService  # noqa: F401
