"""REST service: HTTP surface over SiddhiManager.

Mirror of the reference runner's HTTP APIs
(``siddhi-service``/runner: deploy apps, inject events, run on-demand
queries, snapshot state, read metrics) on the standard-library HTTP
server — no framework dependency, one daemon thread.

Endpoints (JSON in/out):

- ``GET  /apps``                       — deployed app names
- ``POST /apps``                       — body = SiddhiQL app text (deploy + start)
- ``DELETE /apps/<name>``              — shutdown + undeploy
- ``POST /apps/<name>/events``         — ``{"stream": S, "data": [...] | [[...], ...], "timestamp": optional}``
- ``POST /query``                      — ``{"app": name, "query": "<on-demand query>"}`` -> rows;
  runs on a bounded executor with a per-endpoint queue cap
  (``siddhi_tpu/serving/query_tier.py``) — past the cap the request is
  SHED with ``503`` + ``Retry-After`` instead of queuing behind the app
  barrier, so a query storm never stalls ingest
- ``GET  /apps/<name>/statistics``     — metrics snapshot
- ``POST /apps/<name>/persist``        — checkpoint; -> ``{"revision": ...}``
- ``POST /apps/<name>/restore``        — ``{"revision": optional}`` (last when omitted)
- ``POST /ingest/<stream>[?app=name]`` — body = ONE binary zero-copy
  columnar wire frame (``core/stream/input/wire.py``; encoder in
  ``tools/wire_bench.py``): the production telemetry front door.
  AdmissionPool-fronted (503 + Retry-After past the per-endpoint cap);
  malformed frames answer 400 naming the defect; landed through
  ``InputHandler.send_columns`` so quotas/WAL/enforceOrder/journeys
  all apply

Observability (``siddhi_tpu/observability/``):

- ``GET  /metrics``                    — Prometheus text exposition over every
  deployed app (per-query latency p50/p95/p99, junction queue-depth gauges,
  jit-compile counters, ``resilience.*`` counters) + process telemetry;
  ``?format=json`` or ``Accept: application/json`` returns the JSON snapshot
- ``GET  /metrics/<name>``             — same, scoped to one app
- ``POST /trace/start``                — ``{"capacity": optional}``; enable the
  structured span tracer (compile/plan/jit/dispatch/step/publish/persist)
- ``POST /trace/stop``                 — ``{"file": optional relative name}``;
  disable it, dump Chrome-trace JSON under the trace base, return it inline

(The per-app ``POST /apps/<name>/trace`` endpoint remains the XLA device
profiler; ``/trace/*`` is the host-side span timeline.)

Critical-path profiler (``observability/journey.py`` + ``costmodel.py``):

- ``GET  /profile/critical_path[/{app}]`` — per-query per-stage
  service/queueing report naming the bottleneck stage (rendered by
  ``tools/critical_path.py``)
- ``GET  /programs``                   — compiled-program cost registry
  (cost/memory analysis + jaxpr-fingerprint duplicate clusters) plus the
  ``cache`` block: live program-cache entries with sharing apps,
  refcounts and hit counts (``core/util/program_cache.py``)
- ``GET  /autopilot[/{app}]``          — closed-loop controller report:
  actuator table, per-app mode/freeze state, bounded decision log
  (``siddhi_tpu/autopilot/``; 404 for apps not under autopilot control)
- ``POST /profile/journeys/start|stop``— batch-journey tracing on/off
- ``POST /profile/costs/start|stop``   — program cost capture on/off
- ``POST /profile/device/start|stop``  — process-level XLA profiler
  trace, confined under the trace base like ``/trace``
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class SiddhiRestService:
    def __init__(self, manager, host: str = "127.0.0.1", port: int = 0,
                 trace_base: Optional[str] = None,
                 query_workers: int = 8, query_queue_cap: int = 64,
                 cluster=None):
        self.manager = manager
        # optional cluster fabric (siddhi_tpu/cluster/ClusterRuntime):
        # when attached, /query scatter-gathers cluster-deployed apps
        # across the worker fleet, GET /cluster reports fabric status,
        # and the /metrics JSON snapshot carries a "cluster" block (the
        # Prometheus exposition needs no routing — the router's
        # cluster.* gauges/counters live on the process registry)
        self.cluster = cluster
        # profiler traces are confined under this directory; REST clients
        # supply a relative name, never an absolute filesystem path
        self.trace_base = trace_base or os.path.join(
            tempfile.gettempdir(), "siddhi_tpu_traces")
        # on-demand queries run on a bounded executor with a per-endpoint
        # queue cap (siddhi_tpu/serving/query_tier.py): a query storm
        # degrades to fast 503s instead of stacking handler threads behind
        # the app barrier and stalling ingest
        from siddhi_tpu.serving.query_tier import AdmissionPool

        self.admission = AdmissionPool(max_workers=query_workers,
                                       default_cap=query_queue_cap)
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):   # quiet
                pass

            def _send(self, code: int, obj):
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code: int, text: str,
                           ctype: str = "text/plain; version=0.0.4; "
                                        "charset=utf-8"):
                body = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_shed(self, e):
                """503 + Retry-After for admission sheds (/query and
                /ingest share the policy — one place to change it)."""
                self.send_response(503)
                self.send_header("Retry-After", "1")
                payload = json.dumps(
                    {"error": str(e), "shed": True}).encode("utf-8")
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b""
                ctype = self.headers.get("Content-Type", "")
                if "json" in ctype and raw:
                    return json.loads(raw)
                return raw.decode("utf-8")

            def do_GET(self):
                try:
                    service._get(self)
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": str(e)})

            def do_POST(self):
                try:
                    service._post(self)
                except Exception as e:  # noqa: BLE001
                    self._send(400, {"error": str(e)})

            def do_DELETE(self):
                try:
                    service._delete(self)
                except Exception as e:  # noqa: BLE001
                    self._send(400, {"error": str(e)})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        self._device_tracing: Optional[str] = None  # active profile dir
        # zero-copy ingest front door (core/stream/input/wire.py):
        # per-encoder dictionary-delta LUTs for POST /ingest/{stream}
        from siddhi_tpu.core.stream.input.wire import DecoderRegistry

        self._wire_decoders = DecoderRegistry()

    # ----------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="siddhi-rest")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.admission.shutdown()

    # ------------------------------------------------------------ handlers

    def _rt(self, name: str):
        rt = self.manager.get_siddhi_app_runtime(name)
        if rt is None:
            raise KeyError(f"app '{name}' is not deployed")
        return rt

    def _get(self, h):
        from urllib.parse import parse_qs, urlsplit

        split = urlsplit(h.path)
        parts = [p for p in split.path.split("/") if p]
        if parts == ["apps"]:
            h._send(200, {"apps": sorted(self.manager.app_runtimes)})
            return
        if len(parts) == 3 and parts[0] == "apps" and parts[2] == "statistics":
            h._send(200, self._rt(parts[1]).statistics())
            return
        if parts == ["programs"]:
            # compiled-program cost registry (observability/costmodel.py)
            # plus the live process-global compiled-program cache
            # (core/util/program_cache.py): which executables are shared,
            # by whom, refcounts and first-call hit totals
            from siddhi_tpu.core.util import program_cache
            from siddhi_tpu.observability import costmodel

            payload = costmodel.registry().snapshot()
            payload["cache"] = program_cache.cache().snapshot()
            h._send(200, payload)
            return
        if (len(parts) in (2, 3) and parts[0] == "profile"
                and parts[1] == "critical_path"):
            from siddhi_tpu.observability import journey

            app = parts[2] if len(parts) == 3 else None
            if app is not None and self.manager.get_siddhi_app_runtime(
                    app) is None:
                h._send(404, {"error": f"app '{app}' is not deployed"})
                return
            h._send(200, journey.critical_path_report(self.manager, app))
            return
        if parts and parts[0] == "autopilot" and len(parts) <= 2:
            from siddhi_tpu.autopilot import AutopilotController

            app = parts[1] if len(parts) == 2 else None
            if app is not None and self.manager.get_siddhi_app_runtime(
                    app) is None:
                h._send(404, {"error": f"app '{app}' is not deployed"})
                return
            try:
                h._send(200, AutopilotController.instance().report(app))
            except KeyError:
                # deployed but never registered (autopilot knob off)
                h._send(404, {"error": f"app '{app}' is not under "
                                       f"autopilot control"})
            return
        if parts == ["cluster"]:
            if self.cluster is None:
                h._send(404, {"error": "no cluster fabric is attached"})
                return
            h._send(200, self.cluster.status())
            return
        if parts and parts[0] == "metrics" and len(parts) <= 2:
            from siddhi_tpu.observability import export

            app = parts[1] if len(parts) == 2 else None
            if app is not None and self.manager.get_siddhi_app_runtime(
                    app) is None:
                h._send(404, {"error": f"app '{app}' is not deployed"})
                return
            fmt = (parse_qs(split.query).get("format", [""])[0]
                   or ("json" if "application/json"
                       in (h.headers.get("Accept") or "") else "text"))
            if fmt == "json":
                snap = export.json_snapshot(self.manager)
                if app is not None:
                    snap = {"apps": {app: snap["apps"][app]},
                            "process": snap["process"]}
                if self.cluster is not None:
                    snap["cluster"] = self.cluster.status()
                h._send(200, snap)
            else:
                h._send_text(200, export.prometheus_text(
                    self.manager, app_name=app))
            return
        h._send(404, {"error": f"unknown path {h.path}"})

    def _post(self, h):
        from urllib.parse import parse_qs, urlsplit

        split = urlsplit(h.path)
        parts = [p for p in split.path.split("/") if p]
        if len(parts) == 2 and parts[0] == "ingest":
            # binary wire frame — raw bytes, never the utf-8 _body path
            n = int(h.headers.get("Content-Length", 0))
            raw = h.rfile.read(n) if n else b""
            app = parse_qs(split.query).get("app", [None])[0]
            self._post_ingest(h, parts[1], raw, app)
            return
        body = h._body()
        if parts == ["apps"]:
            if not isinstance(body, str) or not body.strip():
                raise ValueError("POST /apps expects SiddhiQL app text")
            rt = self.manager.create_siddhi_app_runtime(body)
            rt.start()
            h._send(201, {"app": rt.name})
            return
        if parts == ["query"]:
            from siddhi_tpu.resilience import stat_count
            from siddhi_tpu.serving.query_tier import QueryShedError

            if (self.cluster is not None
                    and body["app"] in self.cluster.apps):
                # cluster-deployed app: scatter-gather across the worker
                # fleet (router re-merges with the PR-6 stitch); same
                # bounded admission as in-process queries — a storm
                # sheds 503s here instead of stacking socket fan-outs
                try:
                    fut = self.admission.try_submit(
                        "/query", self.cluster.query,
                        body["app"], body["query"])
                except QueryShedError as e:
                    h._send_shed(e)
                    return
                rows = fut.result()
                h._send(200, {"rows": [list(vals) for _ts, vals in rows]})
                return
            rt = self._rt(body["app"])
            # per-app admission (resilience/overload.py): an app with a
            # registered query_cap sheds against ITS OWN pending count
            # (endpoint '/query:<app>'), so a storm on one tenant never
            # consumes the shared '/query' cap of its siblings
            ctl = getattr(rt.app_context, "overload", None)
            endpoint, cap = "/query", None
            if ctl is not None and ctl.query_cap is not None:
                endpoint, cap = f"/query:{rt.name}", ctl.query_cap
            try:
                fut = self.admission.try_submit(
                    endpoint, rt.query, body["query"], cap=cap)
            except QueryShedError as e:
                stat_count(rt.app_context, "resilience.query_sheds")
                h._send_shed(e)
                return
            events = fut.result()
            h._send(200, {"rows": [list(e.data) for e in events]})
            return
        if len(parts) == 3 and parts[0] == "profile":
            self._post_profile(h, parts[1], parts[2], body)
            return
        if parts == ["trace", "start"]:
            from siddhi_tpu.observability.tracing import TRACER

            if TRACER.enabled:
                h._send(409, {"error": "span tracing is already running"})
                return
            cap = body.get("capacity") if isinstance(body, dict) else None
            TRACER.start(capacity=int(cap) if cap else None)
            h._send(200, {"tracing": True, "capacity": TRACER.capacity})
            return
        if parts == ["trace", "stop"]:
            from siddhi_tpu.observability.tracing import TRACER

            if not TRACER.enabled:
                h._send(409, {"error": "no span trace is running"})
                return
            # validate the target BEFORE stopping: a rejected request
            # must not kill a running trace as a side effect
            name = (body.get("file") if isinstance(body, dict) else None) \
                or "spans.trace.json"
            base = os.path.realpath(self.trace_base)
            target = os.path.realpath(os.path.join(base, name))
            # target == base is rejected too: it names the trace DIRECTORY,
            # and open() on it would 500 after killing the running trace
            if not target.startswith(base + os.sep):
                h._send(400, {"error": "trace file escapes the configured "
                                       "trace base"})
                return
            trace = TRACER.stop()
            os.makedirs(os.path.dirname(target), exist_ok=True)
            with open(target, "w", encoding="utf-8") as f:
                json.dump(trace, f)
            h._send(200, {"tracing": False, "file": target,
                          "events": len(trace["traceEvents"]),
                          "trace": trace})
            return
        if len(parts) == 3 and parts[0] == "apps":
            rt = self._rt(parts[1])
            if parts[2] == "events":
                stream = body["stream"]
                data = body["data"]
                ts = body.get("timestamp")
                rows = data if data and isinstance(data[0], list) else [data]
                handler = rt.get_input_handler(stream)
                for row in rows:
                    if ts is None:
                        handler.send(row)
                    else:
                        handler.send(int(ts), row)
                h._send(200, {"accepted": len(rows)})
                return
            if parts[2] == "persist":
                h._send(200, {"revision": rt.persist()})
                return
            if parts[2] == "trace":
                # {"action": "start", "dir": <relative name>} | {"action": "stop"}
                if not isinstance(body, dict) or body.get("action") not in (
                        "start", "stop"):
                    h._send(400, {"error": "trace expects action=start|stop"})
                    return
                if body["action"] == "start":
                    name = body.get("dir")
                    if not isinstance(name, str) or not name:
                        h._send(400, {"error": "trace start expects a "
                                               "'dir' (relative name)"})
                        return
                    base = os.path.realpath(self.trace_base)
                    target = os.path.realpath(os.path.join(base, name))
                    if target != base and not target.startswith(base + os.sep):
                        h._send(400, {"error": "trace dir escapes the "
                                               "configured trace base"})
                        return
                    try:
                        h._send(200, {"tracing": rt.start_trace(target)})
                    except RuntimeError as e:   # double-start
                        h._send(409, {"error": str(e)})
                else:
                    try:
                        rt.stop_trace()
                        h._send(200, {"tracing": None})
                    except RuntimeError as e:   # stop without start
                        h._send(409, {"error": str(e)})
                return
            if parts[2] == "restore":
                rev = body.get("revision") if isinstance(body, dict) else None
                if rev:
                    rt.restore_revision(rev)
                else:
                    rev = rt.restore_last_revision()
                h._send(200, {"revision": rev})
                return
        h._send(404, {"error": f"unknown path {h.path}"})

    def _post_ingest(self, h, stream: str, raw: bytes,
                     app: Optional[str]) -> None:
        """``POST /ingest/{stream}[?app=name]`` — the zero-copy columnar
        front door: body = one binary wire frame
        (``core/stream/input/wire.py``), landed through the stream's
        ``InputHandler.send_columns`` so quota admission, the ingest
        WAL, @app:enforceOrder, and batch-journey tracing all ride
        exactly like any other producer. AdmissionPool-fronted: past the
        per-endpoint cap the frame is SHED with 503 + Retry-After
        instead of stacking handler threads behind the app barrier."""
        from siddhi_tpu.compiler.errors import SiddhiAppValidationException
        from siddhi_tpu.core.stream.input.wire import decode_frame
        from siddhi_tpu.serving.query_tier import QueryShedError

        if app is not None:
            rt = self.manager.get_siddhi_app_runtime(app)
            if rt is None:
                # routing errors are 404s, matching the no-?app branch —
                # 400 is reserved for malformed frames
                h._send(404, {"error": f"app '{app}' is not deployed"})
                return
            if stream not in rt.junctions:
                h._send(404, {"error": f"stream '{stream}' is not "
                                       f"defined in app '{app}'"})
                return
        else:
            owners = [r for r in self.manager.app_runtimes.values()
                      if stream in r.junctions]
            if not owners:
                h._send(404, {"error": f"no deployed app defines stream "
                                       f"'{stream}'"})
                return
            if len(owners) > 1:
                h._send(409, {"error": f"stream '{stream}' is defined by "
                                       f"multiple apps "
                                       f"{sorted(r.name for r in owners)} "
                                       f"— disambiguate with ?app=<name>"})
                return
            rt = owners[0]

        def ingest():
            # scope=app name: the shared registry's LUTs hold THIS app's
            # dictionary ids — an encoder posting to two apps gets two
            # independent delta states
            data, ts = decode_frame(
                raw, rt.junctions[stream].definition,
                rt.app_context.string_dictionary, self._wire_decoders,
                scope=rt.name)
            n = len(next(iter(data.values()))) if data else 0
            handler = rt.get_input_handler(stream)
            handler.send_columns(data, timestamps=ts)
            tel = rt.app_context.telemetry
            tel.count("ingest.wire.frames")
            tel.count("ingest.wire.bytes", len(raw))
            tel.count("ingest.wire.events", n)
            return n

        try:
            fut = self.admission.try_submit(f"/ingest:{rt.name}", ingest)
        except QueryShedError as e:
            h._send_shed(e)
            return
        try:
            accepted = fut.result()
        except SiddhiAppValidationException as e:
            # malformed frame / dictionary gap: the client's fault — 400
            # with the exact reason, never a 500, never a partial batch
            h._send(400, {"error": str(e)})
            return
        h._send(200, {"accepted": accepted, "stream": stream,
                      "app": rt.name})

    def _post_profile(self, h, what: str, action: str, body):
        """``POST /profile/{journeys|costs|device}/{start|stop}`` — the
        critical-path profiler's runtime switches. ``device`` wraps the
        process-level XLA profiler (``jax.profiler.start_trace``); its
        output directory is confined under ``trace_base`` exactly like
        the ``/trace`` endpoints."""
        if action not in ("start", "stop"):
            h._send(404, {"error": f"unknown path {h.path}"})
            return
        if what == "journeys":
            from siddhi_tpu.observability import journey

            if action == "start":
                cap = body.get("capacity") if isinstance(body, dict) else None
                journey.enable(ring_capacity=int(cap) if cap else None)
            else:
                journey.disable()
            h._send(200, {"journeys": journey.enabled()})
            return
        if what == "costs":
            from siddhi_tpu.observability import costmodel

            if action == "start":
                costmodel.enable()
            else:
                costmodel.disable()
            h._send(200, {"costs": costmodel.enabled(),
                          "programs": len(costmodel.registry().programs())})
            return
        if what == "device":
            import jax

            if action == "start":
                if self._device_tracing:
                    h._send(409, {"error": "a device profile is already "
                                           "running"})
                    return
                name = (body.get("dir") if isinstance(body, dict)
                        else None) or "device_profile"
                base = os.path.realpath(self.trace_base)
                target = os.path.realpath(os.path.join(base, name))
                if target != base and not target.startswith(base + os.sep):
                    h._send(400, {"error": "profile dir escapes the "
                                           "configured trace base"})
                    return
                jax.profiler.start_trace(target)
                self._device_tracing = target
                h._send(200, {"device_profile": target})
            else:
                if not self._device_tracing:
                    h._send(409, {"error": "no device profile is running"})
                    return
                jax.profiler.stop_trace()
                target, self._device_tracing = self._device_tracing, None
                h._send(200, {"device_profile": None, "dir": target})
            return
        h._send(404, {"error": f"unknown path {h.path}"})

    def _delete(self, h):
        parts = [p for p in h.path.split("/") if p]
        if len(parts) == 2 and parts[0] == "apps":
            rt = self._rt(parts[1])
            rt.shutdown()
            del self.manager.app_runtimes[parts[1]]
            h._send(200, {"removed": parts[1]})
            return
        h._send(404, {"error": f"unknown path {h.path}"})
