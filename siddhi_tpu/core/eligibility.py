"""Machine-readable eligibility reason codes + the per-app census.

The engine runs one query under up to five strategies (legacy / fused
fan-out / pipelined / device-routed / device joins); each strategy's
planner hook reports WHY a runtime cannot take its path as a free-text
reason (``rt.engine_reason`` / ``rt.pipeline_reason`` /
``parallel.mesh.route_ineligibility`` / ``fanout_plan
.fusion_ineligibility``). Free text is fine for humans but useless for
tooling: the semantic fuzzer (``siddhi_tpu/fuzz/``) must assert
"this generated shape SHOULD be route-eligible — did the engine agree,
and if not, for a reason I know about?" so silent strategy fallbacks
become detected coverage gaps instead of quietly-passing diffs.

This module is the single source of truth: every reason the engine can
emit is a :class:`Reason` — a ``str`` subclass (all existing substring
asserts and f-string interpolations keep working unchanged) carrying a
stable :class:`ReasonCode` enum member. ``code_of`` normalizes any
surface value (None = eligible, Reason, legacy bare str) to a code;
a bare str maps to ``UNKNOWN``, which the fuzzer treats as an
UNEXPLAINED fallback — adding a new ineligibility branch without
declaring its code here is a detected gap, not a silent one.

``register_census`` walks a freshly-built app's query runtimes, records
each query's classification on every surface into
``app_runtime.eligibility_census`` and counts it on the app's telemetry
registry as ``eligibility.<surface>.<code>.<query>`` (exported as the
``siddhi_eligibility_total{surface,code,query}`` family by
``observability/export.py``), so a production dashboard can watch the
eligible/ineligible population per strategy the same way the fuzzer
does.
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional, Tuple

# census surfaces (the strategy axes a query is classified on)
SURFACE_ROUTE = "route"                  # device-routed mesh sharding
SURFACE_FUSION = "fusion"                # fan-out fusion membership
SURFACE_JOIN_ENGINE = "join_engine"      # device join engine
SURFACE_JOIN_PIPELINE = "join_pipeline"  # join CompletionPump ride
SURFACES = (SURFACE_ROUTE, SURFACE_FUSION, SURFACE_JOIN_ENGINE,
            SURFACE_JOIN_PIPELINE)


class ReasonCode(str, Enum):
    """Stable machine-readable eligibility codes. Values are the wire
    spelling (census counters, fuzz reports, /metrics labels) — never
    renumbered, only appended."""

    # pseudo-codes
    ELIGIBLE = "ELIGIBLE"            # reason is None — the strategy applies
    UNKNOWN = "UNKNOWN"              # legacy bare-str reason (a coverage gap)

    # shared across surfaces
    HOST_WINDOW = "HOST_WINDOW"              # host-mode window stage
    ORDER_LIMIT = "ORDER_LIMIT"              # order by / limit / offset
    GROUPED_SELECT = "GROUPED_SELECT"        # host keyed select between stages
    INDEXED_PROBE = "INDEXED_PROBE"          # indexed table probe
    STORE_SIDE = "STORE_SIDE"                # shared-store probe side
    SCHEDULER_WINDOW = "SCHEDULER_WINDOW"    # timer-driven window
    DISABLED = "DISABLED"                    # config opt-out (legacy mode)

    # device routing (parallel/mesh.route_ineligibility)
    NFA_QUERY = "NFA_QUERY"                  # pattern/sequence state machine
    WINDOW_NOT_GLOBAL_AWARE = "WINDOW_NOT_GLOBAL_AWARE"
    GLOBAL_WINDOW = "GLOBAL_WINDOW"          # non-partitioned window
    UNKEYED = "UNKEYED"                      # nothing to route by
    INNER_PARTITION_STREAM = "INNER_PARTITION_STREAM"  # '#stream' input
    JOIN_UNPARTITIONED = "JOIN_UNPARTITIONED"
    GLOBAL_SIDE = "GLOBAL_SIDE"              # global join side in a partition

    # device join engine (core/join/engine.py)
    PARTITIONED = "PARTITIONED"              # keyed rings partition-local
    WINDOW_KIND = "WINDOW_KIND"              # side window has no adapter
    NOT_ATTACHED = "NOT_ATTACHED"            # pre-classification default
    NO_WINDOW = "NO_WINDOW"                  # side without a window stage

    # fan-out fusion (core/plan/fanout_plan.py + JoinSideProxy)
    NOT_PLAIN_RUNTIME = "NOT_PLAIN_RUNTIME"  # join/pattern runtime classes
    HOST_TRANSFORM = "HOST_TRANSFORM"        # host-side transform chain
    LOG_TAPS = "LOG_TAPS"                    # #log() host taps
    SHARDED = "SHARDED"                      # already sharded over a mesh
    NO_DEVICE_ENGINE = "NO_DEVICE_ENGINE"    # join side w/o device engine
    SELF_JOIN = "SELF_JOIN"                  # both sides on one junction


class Reason(str):
    """A free-text ineligibility reason carrying its stable code.

    ``str`` subclass on purpose: every existing consumer — substring
    asserts in tests, ``f"...({rt.engine_reason})"`` interpolations,
    ``reason is not None`` eligibility checks — sees the exact text it
    always did; tooling reads ``.code``."""

    __slots__ = ("code",)

    def __new__(cls, code: ReasonCode, detail: str) -> "Reason":
        r = super().__new__(cls, detail)
        r.code = code
        return r

    def __reduce__(self):  # keep .code across pickling (snapshots, IPC)
        return (Reason, (self.code, str(self)))


def reason(code: ReasonCode, detail: str) -> Reason:
    """The one constructor every eligibility surface uses."""
    return Reason(code, detail)


def code_of(value: Optional[str]) -> ReasonCode:
    """Normalize a surface value to its code: ``None`` is ELIGIBLE, a
    :class:`Reason` carries its own code, and a legacy bare string is
    UNKNOWN — the fuzzer's definition of an unexplained fallback."""
    if value is None:
        return ReasonCode.ELIGIBLE
    if isinstance(value, Reason):
        return value.code
    return ReasonCode.UNKNOWN


# --------------------------------------------------------------- census

def census_of(app_runtime) -> Dict[str, List[Tuple[str, ReasonCode, str]]]:
    """Classify every query runtime on every surface it participates in.

    Returns ``{query_name: [(surface, code, detail), ...]}``. Pure read:
    consults the same functions the planners do, mutates nothing."""
    from siddhi_tpu.core.plan.fanout_plan import fusion_ineligibility
    from siddhi_tpu.parallel.mesh import route_ineligibility

    out: Dict[str, List[Tuple[str, ReasonCode, str]]] = {}
    for name, q in app_runtime.query_runtimes.items():
        rows: List[Tuple[str, ReasonCode, str]] = []
        r = route_ineligibility(q)
        rows.append((SURFACE_ROUTE, code_of(r), str(r or "")))
        if getattr(q, "sides", None) is not None:
            # join: the fusion decision is made per side PROXY (the
            # junction receivers), not on the JoinQueryRuntime itself
            proxies = getattr(q, "_proxies", None)
            if proxies:
                for key, proxy in sorted(proxies.items()):
                    fr = proxy.fusion_ineligibility()
                    rows.append((SURFACE_FUSION, code_of(fr), str(fr or "")))
            else:
                fr = fusion_ineligibility(q)
                rows.append((SURFACE_FUSION, code_of(fr), str(fr or "")))
            er = getattr(q, "engine_reason", None)
            pr = getattr(q, "pipeline_reason", None)
            rows.append((SURFACE_JOIN_ENGINE, code_of(er), str(er or "")))
            rows.append((SURFACE_JOIN_PIPELINE, code_of(pr), str(pr or "")))
        else:
            fr = fusion_ineligibility(q)
            rows.append((SURFACE_FUSION, code_of(fr), str(fr or "")))
        out[name] = rows
    return out


def register_census(app_runtime) -> None:
    """Record the build-time classification census: stash it on
    ``app_runtime.eligibility_census`` for direct reads (the fuzzer) and
    count each (surface, code, query) on the app's telemetry registry
    for the /metrics family. Called once per app build, right after
    fan-out planning."""
    census = census_of(app_runtime)
    app_runtime.eligibility_census = census
    tel = getattr(app_runtime.app_context, "telemetry", None)
    if tel is None:
        return
    for qname, rows in census.items():
        for surface, code, _detail in rows:
            tel.count(f"eligibility.{surface}.{code.value}.{qname}")
