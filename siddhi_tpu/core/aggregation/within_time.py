"""Date-string parsing for aggregation ``within`` clauses.

The reference resolves ``within`` bounds of incremental-aggregation reads
with ``incrementalAggregator:startTimeEndTime()``
(executor/incremental/IncrementalStartTimeEndTimeFunctionExecutor.java:139-200):
a single string may wildcard trailing calendar fields with ``**`` and means
the whole calendar unit it names ([start, start + unit)); a pair of bounds
may each be a unix-ms long or a fully-specified date string
(IncrementalUnixTimeFunctionExecutor). GMT strings are 19 chars; a
``±HH:MM`` ISO-8601 offset suffix makes 26. Months/years roll
calendar-aware (IncrementalTimeConverterUtil.getNextEmitTime)."""

from __future__ import annotations

import re
from datetime import datetime, timedelta, timezone
from typing import Tuple

_FULL = re.compile(r"^\d{4}-\d{2}-\d{2}\s\d{2}:\d{2}:\d{2}$")
_MIN = re.compile(r"^\d{4}-\d{2}-\d{2}\s\d{2}:\d{2}:\*\*$")
_HOUR = re.compile(r"^\d{4}-\d{2}-\d{2}\s\d{2}:\*\*:\*\*$")
_DAY = re.compile(r"^\d{4}-\d{2}-\d{2}\s\*\*:\*\*:\*\*$")
_MONTH = re.compile(r"^\d{4}-\d{2}-\*\*\s\*\*:\*\*:\*\*$")
_YEAR = re.compile(r"^\d{4}-\*\*-\*\*\s\*\*:\*\*:\*\*$")
_OFFSET = re.compile(r"^(.*)\s([+-])(\d{2}):(\d{2})$")


class WithinFormatError(ValueError):
    pass


def _split_offset(s: str) -> Tuple[str, timezone]:
    """Split an optional trailing ``±HH:MM`` offset; GMT without one."""
    s = s.strip()
    m = _OFFSET.match(s)
    if m and len(s) == 26:
        sign = 1 if m.group(2) == "+" else -1
        delta = timedelta(hours=int(m.group(3)), minutes=int(m.group(4)))
        return m.group(1), timezone(sign * delta)
    if len(s) != 19:
        raise WithinFormatError(
            f"within date '{s}' must be 'yyyy-MM-dd HH:mm:ss' (19 chars, GMT) "
            f"or with a ' ±HH:MM' offset (26 chars); wildcard trailing fields "
            f"with '**'")
    return s, timezone.utc


def unix_ms(s: str) -> int:
    """Epoch ms of a fully-specified ``yyyy-MM-dd HH:mm:ss [±HH:MM]``
    string (IncrementalUnixTimeFunctionExecutor.getUnixTimeStamp)."""
    body, tz = _split_offset(s)
    try:
        dt = datetime.strptime(body, "%Y-%m-%d %H:%M:%S").replace(tzinfo=tz)
    except ValueError as e:
        raise WithinFormatError(f"within date '{s}': {e}") from None
    return int(dt.timestamp() * 1000)


def _next_month(dt: datetime) -> datetime:
    return dt.replace(year=dt.year + 1, month=1) if dt.month == 12 \
        else dt.replace(month=dt.month + 1)


def single_within_range(s: str) -> Tuple[int, int]:
    """[start, end) ms of a single (possibly wildcarded) within string —
    the unit named by the coarsest wildcarded field
    (IncrementalStartTimeEndTimeFunctionExecutor.getStartTimeEndTime)."""
    body, tz = _split_offset(s)
    suffix = "" if tz is timezone.utc else s.strip()[19:]

    if _FULL.match(body):
        start = unix_ms(body + suffix)
        return start, start + 1_000
    if _MIN.match(body):
        start = unix_ms(body.replace("*", "0") + suffix)
        return start, start + 60_000
    if _HOUR.match(body):
        start = unix_ms(body.replace("*", "0") + suffix)
        return start, start + 3_600_000
    if _DAY.match(body):
        start = unix_ms(body.replace("*", "0") + suffix)
        return start, start + 86_400_000
    if _MONTH.match(body):
        head = body.replace("** **:**:**", "01 00:00:00")
        start_dt = datetime.strptime(head, "%Y-%m-%d %H:%M:%S").replace(tzinfo=tz)
        return (int(start_dt.timestamp() * 1000),
                int(_next_month(start_dt).timestamp() * 1000))
    if _YEAR.match(body):
        head = body.replace("**-** **:**:**", "01-01 00:00:00")
        start_dt = datetime.strptime(head, "%Y-%m-%d %H:%M:%S").replace(tzinfo=tz)
        return (int(start_dt.timestamp() * 1000),
                int(start_dt.replace(year=start_dt.year + 1).timestamp() * 1000))
    raise WithinFormatError(
        f"within date '{s}' doesn't match a supported pattern: wildcard "
        f"trailing fields with '**' ('yyyy-MM-dd HH:mm:**' … "
        f"'yyyy-**-** **:**:**')")


def bound_ms(v) -> int:
    """One bound of a two-bound within: unix-ms number or full date string."""
    if isinstance(v, str):
        return unix_ms(v)
    return int(v)


def resolve_within_pair(a, b) -> Tuple[int, int]:
    """[start, end) from two bounds (each unix-ms or a full date string);
    start must precede end (IncrementalStartTimeEndTimeFunctionExecutor
    two-arg validation)."""
    r = (bound_ms(a), bound_ms(b))
    if not r[0] < r[1]:
        raise WithinFormatError(
            f"within start {r[0]} must be less than end {r[1]}")
    return r
