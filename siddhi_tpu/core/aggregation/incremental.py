"""Incremental (multi-granularity time-series) aggregation.

Mirror of the reference aggregation subsystem (``define aggregation ...
aggregate by <ts> every sec ... year``): ``aggregation/AggregationRuntime.java:81``,
``IncrementalExecutor.java:103-160`` (bucketize per duration, roll on
boundary, cascade to the coarser duration), ``BaseIncrementalValueStore``
(per-bucket per-group running base aggregates) and the incremental
aggregator composition (sum, count, avg = sum+count, min, max,
distinctCount — ``query/selector/attribute/aggregator/incremental/*.java``).

Redesigned for columnar batches: each arriving chunk is bucketized for the
finest duration in one vectorized pass (numpy datetime64 truncation covers
calendar months/years), reduced per (bucket, group) with
``np.add.reduceat``-style grouped folds, and merged into per-duration
bucket stores. Coarser durations aggregate the same batch directly — the
cascade is algebraic (bases compose), so no per-event executor chain is
needed. Query-time ``within``/``per`` stitches closed buckets + the open
bucket, exactly like the reference's table + in-memory stitch
(``AggregationRuntime.compileExpression:331``).

Distributed mode note: the reference shards by writing per-``shardId``
tables into one external database (``AggregationParser.java:171-197``).
Here per-host partial bases are mergeable by construction; cross-host
merging over collectives lands with the multi-host runner.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.event import Event, HostBatch
from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
from siddhi_tpu.core.stream.junction import Receiver
from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY, CompileError, compile_expr
from siddhi_tpu.query_api.definitions import (
    AggregationDefinition,
    Attribute,
    AttrType,
    Duration,
    StreamDefinition,
)
from siddhi_tpu.query_api.expressions import AttributeFunction, Expression, Variable

AGG_TS = "AGG_TIMESTAMP"

_LOG = logging.getLogger("siddhi_tpu.aggregation")

_DUR_ORDER = [Duration.SECONDS, Duration.MINUTES, Duration.HOURS, Duration.DAYS,
              Duration.MONTHS, Duration.YEARS]
_DUR_MS = {Duration.SECONDS: 1000, Duration.MINUTES: 60_000,
           Duration.HOURS: 3_600_000, Duration.DAYS: 86_400_000}
_DUR_NAMES = {
    "sec": Duration.SECONDS, "second": Duration.SECONDS, "seconds": Duration.SECONDS,
    "min": Duration.MINUTES, "minute": Duration.MINUTES, "minutes": Duration.MINUTES,
    "hour": Duration.HOURS, "hours": Duration.HOURS,
    "day": Duration.DAYS, "days": Duration.DAYS,
    "month": Duration.MONTHS, "months": Duration.MONTHS,
    "year": Duration.YEARS, "years": Duration.YEARS,
}


def parse_duration_name(name: str) -> Duration:
    d = _DUR_NAMES.get(name.strip().lower())
    if d is None:
        raise CompileError(f"unknown aggregation duration '{name}'")
    return d


_TIME_UNITS_MS = {
    "ms": 1, "millisecond": 1, "milliseconds": 1,
    "sec": 1000, "second": 1000, "seconds": 1000,
    "min": 60_000, "minute": 60_000, "minutes": 60_000,
    "h": 3600_000, "hour": 3600_000, "hours": 3600_000,
    "day": 86_400_000, "days": 86_400_000,
    "month": 31 * 86_400_000, "months": 31 * 86_400_000,
    "year": 366 * 86_400_000, "years": 366 * 86_400_000,
}


def _parse_time_str(s: str) -> Optional[int]:
    """'120 sec' / '24 hours' / 'all' -> milliseconds (None = keep all)."""
    s = s.strip().lower()
    if s == "all":
        return None
    parts = s.split()
    if len(parts) == 2 and parts[1] in _TIME_UNITS_MS:
        return int(float(parts[0]) * _TIME_UNITS_MS[parts[1]])
    if s.isdigit():
        return int(s)
    raise CompileError(f"cannot parse retention/interval time '{s}'")


def bucket_starts(ts_ms: np.ndarray, duration: Duration) -> np.ndarray:
    """Vectorized bucket-start (ms) per duration; months/years are
    calendar-truncated (reference ``executor/incremental/*`` time
    functions)."""
    ts_ms = np.asarray(ts_ms, np.int64)
    if duration in _DUR_MS:
        w = _DUR_MS[duration]
        return ts_ms - ts_ms % w
    dt = ts_ms.astype("datetime64[ms]")
    unit = "M" if duration == Duration.MONTHS else "Y"
    return dt.astype(f"datetime64[{unit}]").astype("datetime64[ms]").astype(np.int64)


class _BaseSpec:
    """One base accumulator column (reference BaseIncrementalValueStore
    fields): kind in sum/count/min/max; `out` names the stored column.
    ``arg_fn`` supplies both the value and the null mask — null rows leave
    the base untouched (reference incremental aggregators skip nulls)."""

    def __init__(self, kind: str, arg_fn, out: str, out_type: AttrType):
        self.kind = kind
        self.arg_fn = arg_fn
        self.out = out
        self.out_type = out_type

    def fold(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if self.kind in ("sum", "count"):
            return a + b
        if self.kind == "distinct":
            return a | b          # sets of observed values
        if self.kind == "last":
            # bare selections keep the value of the LATEST event TIME in
            # the bucket — an out-of-order arrival with an older timestamp
            # must not displace it (LatestAggregationTestCase test1);
            # slots are (event_ts, value) pairs. Bare values from snapshots
            # or shard blobs written before the pair layout sort oldest.
            if not isinstance(a, tuple):
                a = (-2 ** 62, a)
            if not isinstance(b, tuple):
                b = (-2 ** 62, b)
            return b if b[0] >= a[0] else a
        return min(a, b) if self.kind == "min" else max(a, b)


class _OutSpec:
    """One selected output: computed from base columns at query time."""

    def __init__(self, name: str, kind: str, bases: List[str], out_type: AttrType):
        self.name = name
        self.kind = kind      # 'sum'|'count'|'avg'|'min'|'max'|'group'
        self.bases = bases    # base column names (avg: [sum, count])
        self.out_type = out_type


class IncrementalAggregationRuntime(Receiver):
    def __init__(self, definition: AggregationDefinition, app_context,
                 dictionary, stream_definitions: Dict[str, StreamDefinition]):
        self.definition = definition
        self.app_context = app_context
        self.dictionary = dictionary
        self._lock = threading.RLock()

        s = definition.input_stream
        sid = s.unique_stream_id if hasattr(s, "unique_stream_id") else s.stream_id
        if sid not in stream_definitions:
            raise CompileError(f"aggregation '{definition.id}': stream '{sid}' undefined")
        self.input_def = stream_definitions[sid]
        self.input_stream_id = sid
        resolver = SingleStreamResolver(self.input_def, dictionary)

        # time attribute (`aggregate by attr`, default: event timestamp);
        # STRING attributes carry 'yyyy-MM-dd HH:mm:ss [±HH:MM]' dates,
        # parsed per event — unparsable rows are dropped with a log, not
        # raised (reference IncrementalUnixTimeFunctionExecutor +
        # Aggregation1TestCase test16/test38)
        self.ts_is_string = False
        self._ts_memo: Dict[int, Optional[int]] = {}
        if definition.aggregate_attribute is not None:
            fn, t = compile_expr(definition.aggregate_attribute, resolver)
            if t == AttrType.STRING:
                self.ts_is_string = True
            elif t not in (AttrType.LONG, AttrType.INT):
                raise CompileError(
                    "aggregate by attribute must be long (ms epoch) or a "
                    "'yyyy-MM-dd HH:mm:ss' string")
            self.ts_fn = fn
        else:
            self.ts_fn = None

        # durations
        tp = definition.time_period
        if tp is None or not tp.durations:
            raise CompileError("aggregation needs `every <durations>`")
        if tp.operator == "range":
            lo = _DUR_ORDER.index(tp.durations[0])
            hi = _DUR_ORDER.index(tp.durations[-1])
            self.durations = _DUR_ORDER[lo: hi + 1]
        else:
            self.durations = sorted(set(tp.durations), key=_DUR_ORDER.index)

        # selector -> group keys + base/output specs
        sel = definition.selector
        self.group_fns = []
        self.group_attrs: List[Attribute] = []
        for v in (sel.group_by_list or []):
            fn, t = compile_expr(v, resolver)
            self.group_fns.append(fn)
            self.group_attrs.append(Attribute(v.attribute_name, t))

        self.bases: Dict[str, _BaseSpec] = {}
        self.outputs: List[_OutSpec] = []
        group_names = {a.name for a in self.group_attrs}
        for oa in sel.selection_list:
            expr = oa.expression
            name = oa.name
            if isinstance(expr, Variable) and expr.attribute_name in group_names:
                self.outputs.append(_OutSpec(
                    name, "group", [expr.attribute_name],
                    next(a.type for a in self.group_attrs
                         if a.name == expr.attribute_name)))
                continue
            kind = expr.name.lower() if isinstance(expr, AttributeFunction) \
                else None
            if kind not in ("sum", "count", "avg", "min", "max",
                            "distinctcount"):
                # bare expression (`(price * quantity) as lastTradeValue`):
                # the LATEST arrival's value per (bucket, group) — reference
                # AggregationParser keeps non-aggregate selections with
                # last-value semantics (Aggregation1TestCase test5; null
                # arguments leave the stored value untouched)
                arg_fn, arg_t = compile_expr(expr, resolver)
                base = self._base(f"last@{name}", arg_fn, arg_t, kind="last")
                self.outputs.append(_OutSpec(name, "last", [base], arg_t))
                continue
            arg_fn, arg_t = (compile_expr(expr.parameters[0], resolver)
                             if expr.parameters else (None, None))
            if kind == "count":
                base = self._base("count", None, AttrType.LONG)
                self.outputs.append(_OutSpec(name, "count", [base], AttrType.LONG))
            elif kind == "distinctcount":
                # per-bucket per-group value sets (reference
                # IncrementalAggregateBaseTimeFunctions distinct-count)
                base = self._base(f"distinct@{name}", arg_fn, AttrType.LONG,
                                  kind="distinct")
                self.outputs.append(_OutSpec(name, "distinctcount", [base],
                                             AttrType.LONG))
            elif kind == "avg":
                bs = self._base(f"sum@{name}", arg_fn, AttrType.DOUBLE)
                # avg counts only non-null argument rows, so its count base
                # carries the argument (for the null mask), unlike count()
                bc = self._base(f"cnt@{name}", arg_fn, AttrType.LONG,
                                kind="count")
                self.outputs.append(_OutSpec(name, "avg", [bs, bc], AttrType.DOUBLE))
            elif kind == "sum":
                t = AttrType.LONG if arg_t in (AttrType.INT, AttrType.LONG) else AttrType.DOUBLE
                base = self._base(f"sum@{name}", arg_fn, t)
                self.outputs.append(_OutSpec(name, "sum", [base], t))
            else:  # min / max
                base = self._base(f"{kind}@{name}", arg_fn, arg_t)
                self.outputs.append(_OutSpec(name, kind, [base], arg_t))

        # per-duration bucket stores:
        #   {duration: {bucket_start: {group_tuple: [base values]}}}
        self.store: Dict[Duration, Dict[int, Dict[tuple, list]]] = {
            d: {} for d in self.durations
        }
        # incremental-snapshot bookkeeping: buckets touched/purged since
        # the last checkpoint (reference IncrementalSnapshotable op-logs)
        self._dirty: set = set()
        self._deleted: set = set()

        # @purge retention (reference IncrementalDataPurger.java:62):
        # per-duration retention windows; coarser durations retain the
        # history the purged finer buckets summarized
        from siddhi_tpu.query_api.annotations import find_annotation

        purge_ann = find_annotation(definition.annotations or [], "purge")
        self.purge_enabled = False
        self.purge_interval_ms = 15 * 60 * 1000
        self.retention: Dict[Duration, Optional[int]] = {}
        if purge_ann is not None:
            self.purge_enabled = (purge_ann.element("enable") or "true").lower() == "true"
            interval = purge_ann.element("interval")
            if interval:
                self.purge_interval_ms = _parse_time_str(interval)
            # reference defaults (IncrementalDataPurger): fine granularities
            # age out fast, coarse ones are kept
            self.retention = {
                Duration.SECONDS: 120_000,
                Duration.MINUTES: 24 * 3600_000,
                Duration.HOURS: 30 * 24 * 3600_000,
                Duration.DAYS: 366 * 24 * 3600_000,
                Duration.MONTHS: None,
                Duration.YEARS: None,
            }
            rp = purge_ann.annotation("retentionPeriod")
            if rp is not None:
                for k, v in rp.elements:
                    if k is None:
                        continue
                    self.retention[parse_duration_name(k)] = _parse_time_str(v)

        # @PartitionById distributed (shard) mode: this runtime aggregates
        # only its shard's events; rows are tagged so a reader can stitch
        # shards (reference AggregationParser.java:171-197 shardId columns)
        pbi = find_annotation(definition.annotations or [], "PartitionById")
        cm = getattr(app_context.siddhi_context, "config_manager", None)
        ann_enabled = pbi is not None and (
            (pbi.element("enable") or "true").lower() == "true")
        sys_enabled = ((cm.get_property("partitionById") or "")
                       if cm is not None else "").lower() == "true"
        # the `partitionById` system property enables shard mode even when
        # the annotation disables it (Aggregation2TestCase test55/56)
        self.shard_mode = ann_enabled or sys_enabled
        self.shard_id = None
        if self.shard_mode:
            cfg = cm.get_property("shardId") if cm is not None else None
            if not cfg:
                # the reference requires a configured shardId
                # (AggregationParser.java:173-186; Aggregation2TestCase
                # test52/53 expect creation to fail without one)
                raise CompileError(
                    f"aggregation '{definition.id}': @PartitionById needs a "
                    f"configured 'shardId' property")
            self.shard_id = cfg

        # /metrics: per-granularity rollup bucket-count gauges and a
        # flush-latency (ingest fold) histogram, registered on the
        # always-on telemetry registry so the unsharded path and the
        # serving tier's sharded path are both scraped the same way
        self._flush_hist = None
        tel = getattr(app_context, "telemetry", None)
        if tel is not None and hasattr(tel, "histogram"):
            aid = definition.id
            for d in self.durations:
                tel.gauge(f"aggregation.{aid}.{d.value}.buckets",
                          lambda d=d: self._bucket_count(d))
            self._flush_hist = tel.histogram(f"aggregation.{aid}.flush_ms")

    def _bucket_count(self, duration: Duration) -> int:
        """Live bucket count for one granularity (telemetry gauge); the
        sharded serving runtime overrides this to sum its shards."""
        return len(self.store.get(duration, ()))

    def purge(self, now: Optional[int] = None) -> int:
        """Drop buckets older than each duration's retention; returns the
        number of purged buckets (reference IncrementalDataPurger run)."""
        if now is None:
            now = int(self.app_context.timestamp_generator.current_time())
        purged = 0
        with self._lock:
            for d, dstore in self.store.items():
                keep_ms = self.retention.get(d)
                if keep_ms is None:
                    continue
                cutoff = now - keep_ms
                drop = [b for b in dstore if b < cutoff]
                for b in drop:
                    del dstore[b]
                    self._deleted.add((d, b))
                    self._dirty.discard((d, b))
                purged += len(drop)
        return purged

    # ----------------------------------------------- incremental snapshots

    def incremental_snapshot(self) -> dict:
        """Buckets touched since the last checkpoint (+ purge tombstones).
        Pure capture — the op log is cleared only after the checkpoint is
        durably saved (``clear_oplog``), so a failed save loses nothing."""
        with self._lock:
            out = {"buckets": {}, "deleted": []}
            for d, b in self._dirty:
                groups = self.store.get(d, {}).get(b)
                if groups is None:
                    continue
                out["buckets"].setdefault(d.value, {})[b] = {
                    g: list(v) for g, v in groups.items()}
            out["deleted"] = [(d.value, b) for d, b in self._deleted]
            return out

    def clear_oplog(self):
        with self._lock:
            self._dirty.clear()
            self._deleted.clear()

    def apply_increment(self, snap: dict):
        with self._lock:
            # deletions first: a bucket purged then re-created within one
            # checkpoint interval appears in both lists and must survive
            for dv, b in snap.get("deleted", []):
                self.store.get(Duration(dv), {}).pop(b, None)
            for dv, buckets in snap.get("buckets", {}).items():
                d = Duration(dv)
                dstore = self.store.setdefault(d, {})
                for b, groups in buckets.items():
                    dstore[b] = {g: list(v) for g, v in groups.items()}

    def _base(self, key: str, arg_fn, out_type, kind: Optional[str] = None) -> str:
        if key not in self.bases:
            if kind is None:
                kind = key.split("@")[0]
            self.bases[key] = _BaseSpec(kind, arg_fn, key, out_type)
        return key

    # ------------------------------------------------------------- ingest

    def receive(self, events: List[Event]):
        import time

        prep = self._prepare_batch(events)
        if prep is None:
            return
        t0 = time.perf_counter()
        with self._lock:
            self._fold_rows(self, prep, prep["idx"])
        hist = getattr(self, "_flush_hist", None)
        if hist is not None:
            hist.record((time.perf_counter() - t0) * 1000.0)

    def _prepare_batch(self, events: List[Event]) -> Optional[dict]:
        """Run the compiled rollup PROGRAM over one batch: timestamps,
        group keys, base argument columns and per-duration bucket starts —
        everything that is independent of WHICH store the rows fold into.
        The sharded serving tier (``siddhi_tpu/serving/``) prepares once
        and folds per shard, sharing this program across shards instead of
        compiling one per shard (the semantic-overlap sharing of
        PAPERS.md applied to rollup programs)."""
        batch = HostBatch.from_events(events, self.input_def, self.dictionary)
        cols = batch.cols
        ctx = {"xp": np}
        valid = cols[VALID_KEY] & (cols[TYPE_KEY] == 0)
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return None
        if self.ts_fn is not None:
            tsv, _m = self.ts_fn(cols, ctx)
            if self.ts_is_string:
                from siddhi_tpu.core.aggregation.within_time import unix_ms

                ids = np.broadcast_to(np.asarray(tsv, np.int64), valid.shape)
                tsv = np.zeros(valid.shape, np.int64)
                ok = np.zeros(valid.shape, bool)
                for j in idx:
                    i = int(ids[j])
                    if i not in self._ts_memo:
                        s = self.dictionary.decode(i)
                        try:
                            self._ts_memo[i] = unix_ms(s) if s else None
                        except Exception:
                            self._ts_memo[i] = None
                        if self._ts_memo[i] is None:
                            _LOG.warning(
                                "aggregation '%s': '%s' doesn't match the "
                                "supported formats <yyyy>-<MM>-<dd> "
                                "<HH>:<mm>:<ss> (GMT) or with a <Z> offset; "
                                "dropping event", self.definition.id, s)
                    ms = self._ts_memo[i]
                    if ms is not None:
                        tsv[j] = ms
                        ok[j] = True
                valid = valid & ok
                idx = np.nonzero(valid)[0]
                if idx.size == 0:
                    return None
            else:
                tsv = np.broadcast_to(np.asarray(tsv, np.int64), valid.shape)
        else:
            tsv = np.asarray(cols[TS_KEY], np.int64)

        groups = []
        for fn in self.group_fns:
            v, _m = fn(cols, ctx)
            groups.append(np.broadcast_to(np.asarray(v), valid.shape))
        base_vals = {}
        base_null = {}
        for key, spec in self.bases.items():
            if spec.arg_fn is None:
                base_vals[key] = np.ones(valid.shape, np.int64)
                base_null[key] = None
            else:
                v, m = spec.arg_fn(cols, ctx)
                if spec.kind == "count":
                    base_vals[key] = np.ones(valid.shape, np.int64)
                else:
                    base_vals[key] = np.broadcast_to(np.asarray(v), valid.shape)
                base_null[key] = (np.broadcast_to(np.asarray(m), valid.shape)
                                  if m is not None else None)

        return {
            "idx": idx,
            "tsv": tsv,
            "groups": groups,
            "group_tuples": {int(i): tuple(x[i].item() for x in groups)
                             for i in idx},
            "base_vals": base_vals,
            "base_null": base_null,
            "buckets": {d: bucket_starts(tsv, d) for d in self.durations},
        }

    def _fold_rows(self, holder, prep: dict, rows) -> None:
        """Fold prepared rows into ``holder``'s bucket stores. ``holder``
        supplies ``store`` / ``_dirty`` / ``_deleted`` (this runtime, or
        one ``AggregationShard`` of the serving tier); the caller holds
        the holder's lock."""
        ctl = getattr(self.app_context, "overload", None)
        if ctl is not None and ctl.memory_budget_bytes is not None:
            # device-memory budget gate (resilience/overload.py): bucket
            # stores grow a (duration, bucket, group) slot per novel key —
            # deny the fold BEFORE creating new slots once the app's
            # budget is spent (purge/shorter retention frees it). The
            # O(slots) store scan only runs when a budget is actually
            # configured — queue-quota-only apps pay nothing here
            from siddhi_tpu.resilience.overload import (
                charge_memory,
                ensure_memory_budget,
            )

            comp = self._budget_component(holder)
            per_slot = 96 + 56 * max(len(self.bases), 1)
            est = self._approx_store_slots(holder) * per_slot
            ensure_memory_budget(
                self.app_context, comp,
                est + len(rows) * len(self.durations) * per_slot,
                what=f"aggregation '{self.definition.id}' bucket-store "
                     f"growth")
            self._fold_rows_inner(holder, prep, rows)
            charge_memory(self.app_context, comp,
                          self._approx_store_slots(holder) * per_slot)
            return
        self._fold_rows_inner(holder, prep, rows)

    def _budget_component(self, holder) -> str:
        idx = getattr(holder, "index", None)
        base = f"aggregation.{self.definition.id}"
        return base if holder is self or idx is None else f"{base}.shard{idx}"

    @staticmethod
    def _approx_store_slots(holder) -> int:
        """(duration, bucket, group) slot count — the unit the memory
        budget charges bucket stores by (approximate: slot boxes dominate
        the host-dict footprint)."""
        return sum(len(groups) for dstore in holder.store.values()
                   for groups in dstore.values())

    def _fold_rows_inner(self, holder, prep: dict, rows) -> None:
        base_keys = list(self.bases)
        tsv = prep["tsv"]
        base_vals, base_null = prep["base_vals"], prep["base_null"]
        group_tuples = prep["group_tuples"]
        for d in self.durations:
            buckets = prep["buckets"][d]
            # setdefault: a restore may have replaced the store with a
            # snapshot keeping fewer granularities — ingest re-creates
            # the declared ones rather than crashing
            dstore = holder.store.setdefault(d, {})
            for i in rows:
                b = int(buckets[i])
                g = group_tuples[int(i)]
                holder._dirty.add((d, b))
                holder._deleted.discard((d, b))   # re-created after purge
                slot = dstore.setdefault(b, {}).get(g)
                if slot is None:
                    slot = dstore[b][g] = [None] * len(base_keys)
                for j, k in enumerate(base_keys):
                    nm = base_null[k]
                    if nm is not None and nm[i]:
                        continue  # null arg leaves the base untouched
                    spec = self.bases[k]
                    v = base_vals[k][i].item()
                    if spec.kind == "distinct":
                        v = {v}
                    elif spec.kind == "last":
                        v = (int(tsv[i]), v)   # event-time-tagged
                    slot[j] = spec.fold(slot[j], v)

    # -------------------------------------------------------------- query

    def output_definition(self) -> StreamDefinition:
        attrs = [Attribute(AGG_TS, AttrType.LONG)]
        seen = {AGG_TS}
        for o in self.outputs:
            if o.name not in seen:
                attrs.append(Attribute(o.name, o.out_type))
                seen.add(o.name)
        for a in self.group_attrs:
            if a.name not in seen:
                attrs.append(a)
                seen.add(a.name)
        return StreamDefinition(id=self.definition.id, attributes=attrs)

    def _resolve_within(self, duration: Duration,
                        within: Optional[Tuple[int, int]]):
        # checked against the STORE, not self.durations: a restore may
        # have replaced the store with a snapshot keeping fewer (or more)
        # granularities, and the queryable set follows the state
        if duration not in self.store:
            raise CompileError(
                f"aggregation '{self.definition.id}' does not keep "
                f"'{duration.value}' granularity")
        if within is not None:
            # the reference truncates the within-START down to the queried
            # duration's bucket start (IncrementalTimeConverterUtil via
            # IncrementalAggregateCompileCondition): a range falling inside
            # one bucket still selects that bucket (Aggregation1TestCase
            # test44: a 1-second range read `per "hours"`)
            start = int(bucket_starts(np.asarray([within[0]]), duration)[0])
            within = (start, within[1])
        return within

    def _rows_from_items(self, items) -> List[list]:
        """Compute the final output rows from (bucket, group, base-values)
        items — ONE code path shared by the single-store read and the
        serving tier's cross-shard stitched read, so sharded and unsharded
        results are computed bit-identically."""
        base_keys = list(self.bases)
        out_rows: List[list] = []
        onames = {o.name for o in self.outputs}
        gnames = [a.name for a in self.group_attrs]
        for b, g, vals in items:
            by_key = dict(zip(base_keys, vals))
            row = [b]
            for o in self.outputs:
                if o.kind == "group":
                    row.append(g[gnames.index(o.bases[0])])
                elif o.kind == "avg":
                    s, c = by_key[o.bases[0]], by_key[o.bases[1]]
                    row.append(s / c if (c and s is not None) else None)
                elif o.kind == "count":
                    row.append(by_key[o.bases[0]] or 0)
                elif o.kind == "distinctcount":
                    s = by_key[o.bases[0]]
                    row.append(len(s) if s else 0)
                elif o.kind == "last":
                    v = by_key[o.bases[0]]  # (event_ts, value) pair
                    # bare pre-pair-layout snapshot values pass through
                    row.append(v[1] if isinstance(v, tuple) else v)
                else:
                    row.append(by_key[o.bases[0]])  # None -> null output
            for gi, a in enumerate(self.group_attrs):
                if a.name not in onames:
                    row.append(g[gi])
            out_rows.append(row)
        return out_rows

    def rows(self, duration: Duration,
             within: Optional[Tuple[int, int]] = None) -> List[list]:
        """Final (stitched) rows for one duration: [AGG_TS, outputs...,
        group attrs...] — closed and open buckets alike (the reference's
        table + running-store stitch)."""
        within = self._resolve_within(duration, within)
        items = []
        with self._lock:
            for b in sorted(self.store[duration]):
                if within is not None and not (within[0] <= b < within[1]):
                    continue
                for g, vals in self.store[duration][b].items():
                    items.append((b, g, list(vals)))
        return self._rows_from_items(items)

    def contents(self, duration: Duration,
                 within: Optional[Tuple[int, int]] = None):
        """Columnar probe surface over the stitched buckets of one
        duration: (output_definition, cols, valid) — shared by on-demand
        `within/per` queries and aggregation joins (reference
        ``AggregationRuntime.java:331-357`` compiled selection)."""
        return self._columnize(self.rows(duration, within))

    def _columnize(self, rows: List[list]):
        """Rows -> (output_definition, columnar numpy arrays, valid mask) —
        the probe surface shape shared with tables/named windows."""
        from siddhi_tpu.ops.expressions import TS_KEY
        from siddhi_tpu.ops.types import dtype_of

        definition = self.output_definition()
        n = len(rows)
        # pad to the next power of two: the on-demand selector stage jits
        # per columnar SHAPE, and under live ingest the stitched row count
        # moves with every fold — raw-n capacity meant a recompile per
        # query, pow2 padding means a handful of shapes per query text
        # (padding rows stay valid=False, exactly like table capacity)
        cap = 1
        while cap < n:
            cap *= 2
        cols = {}
        for pos, attr in enumerate(definition.attributes):
            dt = dtype_of(attr.type)
            arr = np.zeros(cap, dt)
            mask = np.zeros(cap, bool)
            for i, r in enumerate(rows):
                v = r[pos]
                if v is None:
                    mask[i] = True
                else:
                    arr[i] = v
            cols[attr.name] = arr
            cols[attr.name + "?"] = mask
        cols[TS_KEY] = cols[definition.attributes[0].name]  # AGG_TIMESTAMP
        valid = np.arange(cap) < n
        return definition, cols, valid

    # ------------------------------------------------- distributed shards

    def _shard_store(self):
        store = self.app_context.siddhi_context.persistence_store
        if store is None:
            raise RuntimeError(
                "@PartitionById aggregation needs a shared persistence "
                "store — call SiddhiManager.set_persistence_store(...)")
        return store

    @property
    def _shard_ns(self) -> str:
        return f"aggregation-shards:{self.definition.id}"

    def _group_codec(self):
        """(encode, decode) for group-key tuples: STRING components travel
        as text between shards (each node has its OWN dictionary, so raw
        ids don't align across runtimes)."""
        str_pos = [i for i, a in enumerate(self.group_attrs)
                   if a.type == AttrType.STRING]
        dic = self.app_context.string_dictionary

        def decode(g):
            return tuple(dic.decode(int(v)) if i in str_pos else v
                         for i, v in enumerate(g))

        def encode(g):
            return tuple(dic.encode(v) if i in str_pos else v
                         for i, v in enumerate(g))

        return encode, decode

    def publish_shard(self):
        """Shard mode: publish this node's partial buckets to the shared
        persistence store under its shardId — the TPU-native analog of
        the reference writing per-``shardId``-keyed rows into shared
        aggregation tables (AggregationParser.java:171-197). Idempotent:
        each publish overwrites this shard's previous rows. String group
        keys are decoded to text (dictionaries are per-node)."""
        import pickle

        if not self.shard_mode:
            raise RuntimeError(
                f"aggregation '{self.definition.id}' is not @PartitionById")
        _enc, dec = self._group_codec()
        with self._lock:
            snap = self.snapshot()
        snap["store"] = {
            dv: {b: {dec(tuple(g) if isinstance(g, (list, tuple)) else (g,)):
                     v for g, v in groups.items()}
                 for b, groups in dstore.items()}
            for dv, dstore in snap["store"].items()
        }
        blob = pickle.dumps(snap, protocol=pickle.HIGHEST_PROTOCOL)
        self._shard_store().save(self._shard_ns, f"shard-{self.shard_id}",
                                 blob)

    def stitch_shards(self) -> int:
        """Reader side: fold every published shard's partial bases into
        this runtime's store (sum/count add, min/min, max/max, distinct
        sets union — ``_BaseSpec.fold``), REPLACING local buckets. The
        role of the reference's cross-shard aggregation table reads
        (``IncrementalAggregateCompileCondition`` over shardId-keyed
        tables). Returns the number of shards merged."""
        import pickle

        store = self._shard_store()
        shard_revs = [r for r in store.revisions(self._shard_ns)
                      if r.startswith("shard-")]
        base_keys = list(self.bases)
        merged: Dict[Duration, Dict[int, Dict[tuple, list]]] = {
            d: {} for d in self.durations}
        enc, _dec = self._group_codec()
        for rev in sorted(shard_revs):
            snap = pickle.loads(store.load(self._shard_ns, rev))
            snap_keys = snap.get("base_keys", base_keys)
            for dv, dstore in snap["store"].items():
                d = parse_duration_name(dv)
                if d not in merged:
                    continue
                for b, groups in dstore.items():
                    buckets = merged[d].setdefault(int(b), {})
                    for g, vals in groups.items():
                        key = enc(tuple(g) if isinstance(g, (list, tuple))
                                  else (g,))
                        by = dict(zip(snap_keys, vals))
                        cur = buckets.get(key)
                        if cur is None:
                            cur = buckets[key] = [None] * len(base_keys)
                        for i, k in enumerate(base_keys):
                            cur[i] = self.bases[k].fold(cur[i], by.get(k))
        with self._lock:
            self.store = merged
        return len(shard_revs)

    # --------------------------------------------------------- persistence

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "base_keys": list(self.bases),
                "store": {
                    d.value: {b: {g: list(v) for g, v in groups.items()}
                              for b, groups in dstore.items()}
                    for d, dstore in self.store.items()
                }
            }

    def _merge_sharded_snapshot(self, snap: dict) -> dict:
        """Fold a serving-tier sharded snapshot ({"sharded": True,
        "shards": [...]}) into one flat store dict — the shard-stitch rule
        (``_BaseSpec.fold`` per base) applied at restore time, so
        pre-sharding and post-sharding revisions cross-restore in both
        directions (the PR-3 fusion-config precedent)."""
        snap_keys = snap.get("base_keys", list(self.bases))
        store: dict = {}
        for shard_snap in snap.get("shards", []):
            for dv, dstore in shard_snap.get("store", {}).items():
                dd = store.setdefault(dv, {})
                for b, groups in dstore.items():
                    bg = dd.setdefault(b, {})
                    for g, vals in groups.items():
                        cur = bg.get(g)
                        if cur is None:
                            bg[g] = list(vals)
                        else:  # duplicate (bucket, group): fold the bases
                            bg[g] = [
                                self.bases[k].fold(a, v)
                                if k in self.bases
                                else (v if v is not None else a)
                                for k, a, v in zip(snap_keys, cur, vals)]
        return {"base_keys": snap_keys, "store": store}

    def restore(self, snap: dict):
        if snap.get("sharded"):
            snap = self._merge_sharded_snapshot(snap)
        # realign slot lists by base-key name so snapshots survive base
        # layout changes (e.g. avg gaining a cnt@ base)
        snap_keys = snap.get("base_keys")
        cur_keys = list(self.bases)
        if snap_keys is None or snap_keys == cur_keys:
            remap = None
        else:
            remap = [snap_keys.index(k) if k in snap_keys else -1
                     for k in cur_keys]

        def realign(v):
            if remap is None:
                return list(v)
            return [v[j] if j >= 0 else None for j in remap]

        with self._lock:
            self.store = {
                parse_duration_name(dv): {
                    int(b): {tuple(g) if isinstance(g, (list, tuple)) else (g,): realign(v)
                             for g, v in groups.items()}
                    for b, groups in dstore.items()
                }
                for dv, dstore in snap["store"].items()
            }
