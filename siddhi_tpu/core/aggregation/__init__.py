from siddhi_tpu.core.aggregation.incremental import IncrementalAggregationRuntime

__all__ = ["IncrementalAggregationRuntime"]
