"""SiddhiAppRuntime: one running app — junctions, query runtimes, callbacks.

Mirror of reference ``core/SiddhiAppRuntime.java`` /
``SiddhiAppRuntimeImpl.java`` and the assembly logic of
``util/parser/SiddhiAppParser.java:91-212`` +
``util/SiddhiAppRuntimeBuilder.java``: reads @app annotations (playback,
async, statistics), materializes a StreamJunction per stream definition,
plans each query, auto-defines insert-into target streams
(``OutputParser``), and wires callbacks.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.compiler.errors import SiddhiAppValidationException
from siddhi_tpu.core.context import SiddhiAppContext, SiddhiContext
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.plan.query_planner import plan_query
from siddhi_tpu.core.query.callback import QueryCallback
from siddhi_tpu.core.query.ratelimit import (create_rate_limiter,
                                             rate_uses_group_key)
from siddhi_tpu.core.query.runtime import QueryRuntime
from siddhi_tpu.core.stream.input.input_handler import InputHandler, InputManager
from siddhi_tpu.core.stream.junction import StreamJunction
from siddhi_tpu.core.stream.output.stream_callback import StreamCallback
from siddhi_tpu.core.util.scheduler import Scheduler
from siddhi_tpu.query_api.annotations import find_annotation
from siddhi_tpu.query_api.definitions import Attribute, AttrType, StreamDefinition
from siddhi_tpu.query_api.execution import InsertIntoStream, Partition, Query
from siddhi_tpu.query_api.siddhi_app import SiddhiApp


def _compile_script_function(fdef):
    """``define function f[python] return <type> { <expression> }`` — the
    body is a Python expression over ``arg0..argN`` (aka ``data0..``) with
    ``xp`` (jax.numpy on device) in scope, vectorized over columns
    (reference ``ScriptFunctionExecutor`` evaluates per event; here one
    call per batch). String arguments arrive dictionary-encoded."""
    from siddhi_tpu.ops.expressions import CompileError

    if fdef.language.lower() not in ("python", "py"):
        raise CompileError(
            f"function '{fdef.id}': script language '{fdef.language}' is not "
            f"supported (use [python])")
    import numpy as _np

    code = compile(fdef.body.strip(), f"<function {fdef.id}>", "eval")
    rtype = fdef.return_type

    class _Script:
        return_type = rtype

        @staticmethod
        def apply(xp, *args):
            ns = {"xp": xp, "np": _np}
            for i, a in enumerate(args):
                ns[f"arg{i}"] = a
                ns[f"data{i}"] = a
            return eval(code, ns)  # noqa: S307 — user-defined app function

    return _Script


def _parse_playback_time(s: str, what: str) -> int:
    """Strict time-constant string for @app:playback elements — requires
    '<int> <unit>' pairs; bare numbers or empty strings fail creation the
    way the reference's SiddhiCompiler.parseTimeConstantDefinition does
    (PlaybackTestCase test9/test10)."""
    from siddhi_tpu.compiler.errors import SiddhiParserException
    from siddhi_tpu.compiler.tokenizer import is_time_unit, time_unit_ms

    parts = (s or "").split()
    if not parts or len(parts) % 2 != 0:
        raise SiddhiParserException(
            f"Invalid {what} constant '{s}' in playback annotation")
    total = 0
    for num, unit in zip(parts[::2], parts[1::2]):
        if not num.isdigit() or not is_time_unit(unit.lower()):
            raise SiddhiParserException(
                f"Invalid {what} constant '{s}' in playback annotation")
        total += int(num) * time_unit_ms(unit.lower())
    return total


def _default_app_name(siddhi_app: SiddhiApp) -> str:
    """Deterministic fallback name so snapshots of the same (unnamed) app
    text restore across process restarts."""
    import hashlib

    # dataclass reprs are deterministic and cover definitions, queries and
    # expressions — distinct apps hash apart, identical text hashes equal
    return "siddhi-app-" + hashlib.md5(repr(siddhi_app).encode()).hexdigest()[:12]


class SiddhiAppRuntime:  # graftlint: disable=R8 — the junction/query/
    # adapter registries are populated during single-threaded wiring
    # (parse + add_callback before start()); runtime threads only read
    # them, and lifecycle transitions serialize on the app barrier
    def __init__(self, siddhi_app: SiddhiApp, siddhi_context: SiddhiContext):
        self.siddhi_app = siddhi_app
        self.name = siddhi_app.name or _default_app_name(siddhi_app)
        self.app_context = SiddhiAppContext(siddhi_context, self.name)
        self._barrier = make_lock("barrier")
        self.app_context.timestamp_generator.set_heartbeat_barrier(self._barrier)
        self.stream_definitions: Dict[str, StreamDefinition] = dict(siddhi_app.stream_definitions)
        self.junctions: Dict[str, StreamJunction] = {}
        self.query_runtimes: Dict[str, QueryRuntime] = {}
        self._stream_callback_adapters: List = []
        self._started = False
        self._profiling_on = False  # holds one journey/costmodel enable
        self._instruments_on = False  # holds one device-instruments enable

        # @app:playback (reference SiddhiAppParser.java:171-212): optional
        # idle.time + increment enable the idle heartbeat — when no event
        # arrives for idle.time of wall time, the event clock advances by
        # increment so time windows keep draining
        pb = siddhi_app.app_annotation("playback")
        if pb is not None:
            self.app_context.playback = True
            self.app_context.timestamp_generator.playback = True
            elems = pb.elements_map()
            unknown = [k for k in elems if k not in ("idle.time", "increment")]
            if unknown:
                raise SiddhiAppValidationException(
                    "Playback annotation accepts only idle.time and "
                    f"increment but found {unknown[0]}")
            idle_s, inc_s = elems.get("idle.time"), elems.get("increment")
            if (idle_s is None) != (inc_s is None):
                raise SiddhiAppValidationException(
                    "Playback annotation requires both idle.time and "
                    "increment when either is given")
            if idle_s is not None:
                self.app_context.timestamp_generator.configure_heartbeat(
                    _parse_playback_time(idle_s, "idle.time"),
                    _parse_playback_time(inc_s, "increment"))
        if siddhi_app.app_annotation("enforceOrder") is not None:
            self.app_context.enforce_order = True
        if siddhi_app.app_annotation("async") is not None:
            # reference SiddhiAppParser.java:105-111: @Async is a STREAM
            # annotation; the app-level form fails creation
            raise SiddhiAppValidationException(
                "@Async not supported in SiddhiApp level, instead use "
                "@Async with streams")
        prec = siddhi_app.app_annotation("precision")
        if prec is not None:
            v = (prec.element() or "").lower()
            if v not in ("exact", "fast"):
                raise SiddhiAppValidationException(
                    "@app:precision must be 'exact' or 'fast'")
            self.app_context.precision = v
        self.app_context.scheduler = Scheduler(self.app_context)

        # deployment config: ConfigManager system keys override the
        # capacity knobs (reference ConfigManager consulted at parse
        # time). Every siddhi_tpu.* key resolves through the typed
        # parser registry (core/util/knobs.py): junk spellings raise
        # SiddhiAppValidationException naming the key and the accepted
        # values, and graftlint R2 keeps ad-hoc reads out of the tree.
        from siddhi_tpu.core.util.knobs import apply_app_knobs

        cm = siddhi_context.config_manager
        explicit_knobs = apply_app_knobs(cm, self.app_context)
        explicit_depth = explicit_knobs.get("pipeline_depth")
        if self.app_context.defer_meta > 1:
            # deprecation shim: the hold-N-then-flush defer queue is
            # subsumed by the dispatch pipeline (core/query/completion.py)
            # — same pull batching, no emission lag under trickle, joins/
            # scheduler windows no longer excluded. See MIGRATION.md.
            import warnings

            if explicit_depth is None:
                warnings.warn(
                    "siddhi_tpu.defer_meta is deprecated — use "
                    "siddhi_tpu.pipeline_depth (the dispatch pipeline "
                    "subsumes meta-defer batching); mapping defer_meta="
                    f"{self.app_context.defer_meta} onto pipeline_depth",
                    DeprecationWarning, stacklevel=2)
                self.app_context.pipeline_depth = max(
                    self.app_context.pipeline_depth,
                    self.app_context.defer_meta)
                self.app_context.defer_meta = 1
            else:
                # an explicit pipeline_depth wins; defer_meta is left
                # as-is — the legacy hold-N path only engages when the
                # pipeline is pinned off (depth 1), and silently zeroing
                # it would remove the batching the user asked for
                warnings.warn(
                    "siddhi_tpu.defer_meta is deprecated — use "
                    "siddhi_tpu.pipeline_depth; explicit pipeline_depth="
                    f"{explicit_depth} set, defer_meta="
                    f"{self.app_context.defer_meta} kept for the legacy "
                    "path (engages only at pipeline_depth 1)",
                    DeprecationWarning, stacklevel=2)

        # @app:statistics (reference SiddhiStatisticsManager wiring)
        stats_ann = siddhi_app.app_annotation("statistics")
        if stats_ann is not None:
            from siddhi_tpu.core.util.statistics import (
                StatisticsManager,
                parse_level,
            )
            from siddhi_tpu.core.aggregation.incremental import _parse_time_str

            level = parse_level(stats_ann.element("level")
                                or stats_ann.element())
            reporter = stats_ann.element("reporter")
            interval = stats_ann.element("interval")
            self.app_context.statistics_manager = StatisticsManager(
                level=level,
                reporter=reporter,
                interval_ms=_parse_time_str(interval) if interval else 60_000,
            )

        # activate the manager's extension registry for query compilation
        # (custom functions/windows resolve through it — the role of
        # reference SiddhiExtensionLoader.java:58-98), merged with this
        # app's `define function` scripts (ScriptFunctionExecutor role)
        from siddhi_tpu.ops import expressions as _expr_mod

        self._script_functions = {
            f"function:{fid}": _compile_script_function(fdef)
            for fid, fdef in siddhi_app.function_definitions.items()
        }
        self._extensions = {**siddhi_context.extensions, **self._script_functions}
        _expr_mod.set_active_extensions(self._extensions)

        for sid, sdef in list(self.stream_definitions.items()):
            self._create_junction(sdef)   # may register '!sid' fault streams

        # tables, named windows, triggers (reference
        # SiddhiAppRuntimeBuilder.defineTable/defineWindow/defineTrigger)
        from siddhi_tpu.core.table import InMemoryTable
        from siddhi_tpu.core.trigger import TriggerRuntime
        from siddhi_tpu.core.window import NamedWindowRuntime

        dictionary = self.app_context.string_dictionary
        from siddhi_tpu.core.table.record_table import create_table

        self.tables: Dict[str, InMemoryTable] = {
            tid: create_table(tdef, dictionary, siddhi_context.extensions)
            for tid, tdef in siddhi_app.table_definitions.items()
        }
        # cache retention clocks wire at BUILD time: a row cached before
        # start() (lazy start, on-demand reads) must stamp the same
        # event-aware clock the expirer sweeps with — mixing wall time in
        # would make @app:playback rows immortal
        for t in self.tables.values():
            # overload layer (resilience/overload.py): tables gate their
            # capacity growth on the app's device-memory budget
            t.app_context = self.app_context
            cache = getattr(t, "cache", None)
            if cache is not None:
                cache.now_fn = self.app_context.timestamp_generator.current_time
        self.named_windows: Dict[str, NamedWindowRuntime] = {}
        for wid, wdef in siddhi_app.window_definitions.items():
            w = NamedWindowRuntime(wdef, self.app_context, dictionary)
            w.scheduler = self.app_context.scheduler
            self.named_windows[wid] = w
        self.app_context.tables = self.tables
        self.app_context.named_windows = self.named_windows

        # incremental aggregations (reference AggregationParser/-Runtime)
        from siddhi_tpu.core.aggregation import IncrementalAggregationRuntime

        self.aggregations: Dict[str, IncrementalAggregationRuntime] = {}
        for aid, adef in siddhi_app.aggregation_definitions.items():
            n_shards = int(getattr(self.app_context, "agg_shards", 1) or 1)
            # @PartitionById (annotation or system property) keeps the
            # legacy DB shard-stitch runtime — the two sharding modes are
            # mutually exclusive (MIGRATION.md)
            pbi = find_annotation(adef.annotations or [], "PartitionById")
            sys_pbi = ((cm.get_property("partitionById") or "")
                       if cm is not None else "").lower() == "true"
            if n_shards > 1 and pbi is None and not sys_pbi:
                from siddhi_tpu.serving import ShardedIncrementalAggregation

                agg = ShardedIncrementalAggregation(
                    adef, self.app_context, dictionary,
                    self.stream_definitions, n_shards=n_shards,
                    wal_batches=getattr(self.app_context,
                                        "agg_shard_wal", 1024) or None)
            else:
                agg = IncrementalAggregationRuntime(
                    adef, self.app_context, dictionary,
                    self.stream_definitions)
            self.junctions[agg.input_stream_id].subscribe(agg)
            self.aggregations[aid] = agg
        self.app_context.aggregations = self.aggregations

        self.trigger_runtimes: List[TriggerRuntime] = []
        for tid, tdef in siddhi_app.trigger_definitions.items():
            if tid in self.junctions:
                # an explicitly defined `(triggered_time long)` stream may
                # share the trigger's id (TriggerTestCase testQuery4) —
                # reuse its junction so @async config and subscribers stay
                junction = self.junctions[tid]
            else:
                sdef = StreamDefinition(
                    id=tid,
                    attributes=[Attribute("triggered_time", AttrType.LONG)])
                self.stream_definitions[tid] = sdef
                junction = self._create_junction(sdef)
            self.trigger_runtimes.append(
                TriggerRuntime(tdef, junction, self.app_context,
                               barrier=self._barrier))

        self.input_manager = InputManager(self.app_context, self.junctions, self._barrier)
        # first send() starts the app lazily, AFTER callbacks are attached —
        # at-start triggers then fire with subscribers in place
        self.input_manager.ensure_started = self.start

        self.partition_contexts: List = []
        # pre-register set metadata on EXPLICITLY defined target streams so
        # a consumer query written before its producer still compiles with
        # the right multi/element-type knowledge (assembly is one pass in
        # text order; auto-defined streams cannot be forward-referenced)
        self._prescan_object_metadata(siddhi_app)
        q_index = 0
        p_index = 0
        for element in siddhi_app.execution_elements:
            if isinstance(element, Query):
                q_index += 1
                self._add_query(element, q_index)
            elif isinstance(element, Partition):
                p_index += 1
                q_index = self._add_partition(element, p_index, q_index)

        # transport boundary: @source / @sink stream annotations
        # (reference SiddhiAppRuntimeBuilder + SiddhiExtensionLoader)
        from siddhi_tpu.query_api.annotations import find_annotations
        from siddhi_tpu.core.stream.input.source import create_source_runtime
        from siddhi_tpu.core.stream.output.sink import create_sink_runtime

        extensions = siddhi_context.extensions
        self.source_runtimes: List = []
        self.sink_runtimes: List = []
        for sid, sdef in list(self.stream_definitions.items()):
            for ann in find_annotations(sdef.annotations, "source"):
                self.source_runtimes.append(create_source_runtime(
                    ann, sdef, self.get_input_handler(sid),
                    self.app_context, extensions))
            for ann in find_annotations(sdef.annotations, "sink"):
                sr = create_sink_runtime(ann, sdef, self.app_context, extensions)
                self.junctions[sid].subscribe(sr)
                self.sink_runtimes.append(sr)

        # fan-out fusion: contiguous runs of sibling single-stream queries
        # on one junction fuse into ONE jitted step + ONE __meta__ round
        # trip per batch (core/plan/fanout_plan.py); opt out with the
        # app_context.fuse_fanout knob / siddhi_tpu.fuse_fanout config key
        from siddhi_tpu.core.plan.fanout_plan import plan_fanout_groups

        self.fused_fanout_groups: List = plan_fanout_groups(self)

        # eligibility census (core/eligibility.py): classify every query
        # on every strategy surface (route / fusion / join engine / join
        # pipeline) with stable reason codes — stashed on
        # self.eligibility_census for tooling (the semantic fuzzer) and
        # counted as the siddhi_eligibility_total{surface,code,query}
        # family on /metrics
        from siddhi_tpu.core.eligibility import register_census

        register_census(self)

        # overload armor (resilience/overload.py): siddhi_tpu.quota_* /
        # siddhi_tpu.shed_policy config keys register per-app ingest
        # quotas, shed policies, a device-memory budget and a fair-share
        # weight. No keys set => app_context.overload stays None and the
        # engine is bit-identical to the pre-quota default.
        if cm is not None:
            self._overload_from_config(cm)

    def _overload_from_config(self, cm) -> None:
        from siddhi_tpu.core.util.knobs import read_knob

        queue_quota = read_knob(cm, "quota_queue_depth")
        policy = read_knob(cm, "shed_policy")
        pipeline_quota = read_knob(cm, "quota_pipeline_depth")
        memory_mb = read_knob(cm, "quota_memory_mb")
        block_timeout = read_knob(cm, "quota_block_timeout_s")
        fair_weight = read_knob(cm, "fair_weight")
        query_cap = read_knob(cm, "quota_query_cap")
        per_stream_quota = {}
        per_stream_policy = {}
        for sid in self.junctions:
            v = read_knob(cm, "quota_queue_depth", stream=sid)
            if v is not None:
                per_stream_quota[sid] = v
            v = read_knob(cm, "shed_policy", stream=sid)
            if v is not None:
                per_stream_policy[sid] = v
        # presence, not truthiness: the values are TYPED now, and an
        # explicit `quota_queue_depth: 0` / `fair_weight: 0` must still
        # register overload enforcement
        if all(v is None for v in (queue_quota, policy, pipeline_quota,
                                   memory_mb, block_timeout, fair_weight,
                                   query_cap)) \
                and not per_stream_quota and not per_stream_policy:
            return
        self.enable_overload(
            queue_quota=queue_quota,
            shed_policy=policy if policy else "block",
            queue_quota_per_stream=per_stream_quota,
            shed_policy_per_stream=per_stream_policy,
            pipeline_quota=pipeline_quota,
            memory_budget_mb=memory_mb,
            block_timeout_s=block_timeout,
            fair_weight=fair_weight if fair_weight is not None else 1.0,
            query_cap=query_cap)

    def enable_overload(self, queue_quota=None, shed_policy="block",
                        queue_quota_per_stream=None,
                        shed_policy_per_stream=None, pipeline_quota=None,
                        memory_budget_mb=None, block_timeout_s=None,
                        fair_weight=1.0, query_cap=None):
        """Register this app with the process-global overload layer
        (``resilience/overload.py``): @Async queue-depth quotas with
        per-stream ``block`` / ``shed_oldest`` / ``shed_newest``
        policies, an app-wide dispatch-pipeline quota, an approximate
        device-memory budget gating every capacity-growth site, and a
        weighted fair share against sibling apps. Idempotent (re-enable
        replaces the config); returns the ``AppOverloadControl``."""
        from siddhi_tpu.resilience.overload import (
            DEFAULT_BLOCK_TIMEOUT_S,
            OverloadConfig,
            OverloadManager,
        )

        cfg = OverloadConfig(
            queue_quota=queue_quota,
            queue_quota_per_stream=dict(queue_quota_per_stream or {}),
            shed_policy=shed_policy or "block",
            shed_policy_per_stream=dict(shed_policy_per_stream or {}),
            pipeline_quota=pipeline_quota,
            memory_budget_bytes=(int(memory_budget_mb * 1024 * 1024)
                                 if memory_budget_mb is not None else None),
            block_timeout_s=(block_timeout_s if block_timeout_s is not None
                             else DEFAULT_BLOCK_TIMEOUT_S),
            fair_weight=fair_weight,
            query_cap=query_cap)
        return OverloadManager.instance().register(self, cfg)

    # ------------------------------------------------------------ assembly

    def _create_junction(self, sdef: StreamDefinition) -> StreamJunction:
        j = StreamJunction(sdef, self.app_context)
        async_ann = find_annotation(sdef.annotations, "async")
        if async_ann is not None:
            if self.app_context.enforce_order:
                raise SiddhiAppValidationException(
                    f"@app:enforceOrder is incompatible with @Async on "
                    f"stream '{sdef.id}': async buffering can interleave "
                    f"producer batches out of timestamp order")
            from siddhi_tpu.core.aggregation.incremental import _parse_time_str

            buffer_size = int(async_ann.element("buffer.size") or 1024)
            batch_size = int(async_ann.element("batch.size") or 256)
            max_delay = async_ann.element("max.delay")
            latency_target = async_ann.element("latency.target")
            j.enable_async(
                buffer_size, batch_size,
                max_delay_ms=_parse_time_str(max_delay)
                if max_delay else None,
                latency_target_ms=_parse_time_str(latency_target)
                if latency_target else None)
        onerr = find_annotation(sdef.annotations, "OnError")
        if onerr is not None and (
                onerr.element("action") or "log").lower() == "stream":
            # @OnError(action='stream'): failing events route to the
            # '!stream' fault junction with an appended `_error` column
            # (reference StreamJunction.handleError +
            # FaultStreamEventConverter — FaultStreamTestCase test3-5)
            fdef = StreamDefinition(
                id="!" + sdef.id,
                attributes=list(sdef.attributes)
                + [Attribute("_error", AttrType.STRING)])
            fj = StreamJunction(fdef, self.app_context)
            self.junctions[fdef.id] = fj
            self.stream_definitions[fdef.id] = fdef
            j.fault_junction = fj
            j.on_error_action = "STREAM"
        self.junctions[sdef.id] = j
        return j

    def _add_partition(self, partition: Partition, p_index: int, q_index: int) -> int:
        """Assemble a ``partition with (...) begin ... end`` block — the
        role of reference ``util/parser/PartitionParser.java`` +
        ``partition/PartitionRuntimeImpl.java``, with per-key processor
        instances replaced by dense-keyed state (ops/keyed_windows.py)."""
        from siddhi_tpu.core.partition import (
            PartitionContext,
            RangePartitionKeyer,
            ValuePartitionKeyer,
        )
        from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
        from siddhi_tpu.ops.expressions import compile_condition, compile_expr
        from siddhi_tpu.query_api.execution import RangePartitionType, ValuePartitionType

        pctx = PartitionContext(p_index)
        self.partition_contexts.append(pctx)
        purge_ann = find_annotation(partition.annotations or [], "purge")
        if purge_ann is not None and (
            purge_ann.element("enable") or "true"
        ).lower() == "true":
            from siddhi_tpu.core.aggregation.incremental import _parse_time_str

            interval = purge_ann.element("interval")
            idle = purge_ann.element("idle.period")
            pctx.purge_interval_ms = _parse_time_str(interval) if interval else 60_000
            pctx.purge_idle_ms = _parse_time_str(idle) if idle else 3600_000
            pctx.keyspace.enable_purge_tracking()
        for ptype in partition.partition_types:
            sid = ptype.stream_id
            if sid not in self.stream_definitions:
                raise SiddhiAppValidationException(
                    f"partition with (... of {sid}): stream '{sid}' is not defined"
                )
            resolver = SingleStreamResolver(
                self.stream_definitions[sid], self.app_context.string_dictionary
            )
            if isinstance(ptype, ValuePartitionType):
                fn, t = compile_expr(ptype.expression, resolver)
                pctx.keyers[sid] = ValuePartitionKeyer([(fn, t)], pctx.keyspace)
            elif isinstance(ptype, RangePartitionType):
                conds = [
                    (rc.partition_key, compile_condition(rc.condition, resolver))
                    for rc in ptype.conditions
                ]
                pctx.keyers[sid] = RangePartitionKeyer(conds)
            else:
                raise SiddhiAppValidationException(f"unknown partition type {ptype!r}")

        # streams PRODUCED by queries inside this partition (non-inner
        # insert targets): a later partition query may consume them, and
        # their events stay in the producing instance's flow (reference
        # partition ThreadLocal flow — WindowPartitionTestCase q6 chains
        # `insert events into OutputStream` -> `from OutputStream`)
        produced = {
            q.output_stream.target_id
            for q in partition.queries
            if isinstance(q.output_stream, InsertIntoStream)
            and not q.output_stream.is_inner_stream
        }
        consumed = set()
        for q in partition.queries:
            ist = q.input_stream
            for s in ("stream_id", "unique_stream_id"):
                sid = getattr(ist, s, None)
                if isinstance(sid, str):
                    consumed.add(sid)
            for side in ("left_input_stream", "right_input_stream"):
                sub = getattr(ist, side, None)
                sid = getattr(sub, "stream_id", None)
                if isinstance(sid, str):
                    consumed.add(sid)
        pctx.local_streams = produced & consumed
        for query in partition.queries:
            q_index += 1
            self._add_query(query, q_index, partition_ctx=pctx)
        return q_index

    def _prescan_object_metadata(self, siddhi_app):
        """Best-effort first pass over query ASTs: record which object
        attributes of explicitly defined streams are MULTI-element sets
        (unionSet outputs) and their element types (createSet args), so
        query text order does not change set semantics."""
        from siddhi_tpu.query_api.execution import (
            InsertIntoStream,
            Partition,
            Query,
        )
        from siddhi_tpu.query_api.expressions import AttributeFunction, Variable

        def input_attr_type(query, var):
            ist = getattr(query, "input_stream", None)
            sid = getattr(ist, "stream_id", None)
            sdef = self.stream_definitions.get(sid) if sid else None
            if sdef is None:
                return None
            try:
                return sdef.attribute(var.attribute_name).type
            except Exception:
                return None

        def elem_of(query, expr):
            # element type of createSet(<arg>) when statically resolvable
            if not (isinstance(expr, AttributeFunction)
                    and expr.name.lower() == "createset" and expr.parameters):
                return None
            arg = expr.parameters[0]
            if isinstance(arg, Variable):
                return input_attr_type(query, arg)
            return None

        def scan(query):
            out = getattr(query, "output_stream", None)
            if not isinstance(out, InsertIntoStream):
                return
            tdef = self.stream_definitions.get(out.target_id)
            if tdef is None or query.selector is None:
                return
            for oa in query.selector.selection_list or []:
                expr = oa.expression
                if not isinstance(expr, AttributeFunction):
                    continue
                name = expr.name.lower()
                elem = None
                multi = False
                if name == "unionset" and expr.parameters:
                    multi = True
                    elem = elem_of(query, expr.parameters[0])
                elif name == "createset":
                    elem = elem_of(query, expr)
                else:
                    continue
                if multi:
                    ms = set(getattr(tdef, "object_multi_attrs", None) or set())
                    ms.add(oa.name)
                    tdef.object_multi_attrs = ms
                if elem is not None:
                    et = dict(getattr(tdef, "object_elem_types", None) or {})
                    et[oa.name] = elem
                    tdef.object_elem_types = et

        for element in siddhi_app.execution_elements:
            if isinstance(element, Query):
                scan(element)
            elif isinstance(element, Partition):
                for q in element.queries:
                    scan(q)

    def _add_query(self, query: Query, index: int, partition_ctx=None):
        query_name = query.name or f"query_{index}"
        definitions = dict(self.stream_definitions)
        for wid, w in self.named_windows.items():
            definitions[wid] = w.definition
        for tid, t in self.tables.items():
            definitions[tid] = t.definition
        if partition_ctx is not None:
            definitions.update(partition_ctx.inner_definitions)

        from siddhi_tpu.query_api.execution import SingleInputStream

        if (
            isinstance(query.input_stream, SingleInputStream)
            and query.input_stream.unique_stream_id in self.tables
        ):
            raise SiddhiAppValidationException(
                f"'{query.input_stream.stream_id}' is a table — consume it via a "
                f"join or an on-demand query (runtime.query(...))"
            )
        from siddhi_tpu.observability.tracing import span

        with span("plan", query=query_name):
            runtime = plan_query(query, query_name, self.app_context,
                                 definitions, partition_ctx=partition_ctx)

        from siddhi_tpu.core.query.output_callbacks import create_table_callback
        from siddhi_tpu.query_api.execution import (
            DeleteStream,
            UpdateOrInsertStream,
            UpdateStream,
        )

        out = query.output_stream
        if isinstance(out, (DeleteStream, UpdateStream, UpdateOrInsertStream)):
            if out.target_id not in self.tables:
                raise SiddhiAppValidationException(
                    f"'{out.target_id}' is not a defined table"
                )
            runtime.output_action = create_table_callback(
                out, self.tables[out.target_id], query_name, runtime.output_attrs,
                self.app_context.string_dictionary)
        elif isinstance(out, InsertIntoStream) and out.target_id in self.tables \
                and not out.is_inner_stream:
            runtime.output_action = create_table_callback(
                out, self.tables[out.target_id], query_name, runtime.output_attrs,
                self.app_context.string_dictionary)
        elif isinstance(out, InsertIntoStream) and out.target_id in self.named_windows \
                and not out.is_inner_stream:
            w = self.named_windows[out.target_id]
            if len(runtime.output_attrs) != len(w.definition.attributes):
                raise SiddhiAppValidationException(
                    f"insert into window '{out.target_id}': query outputs "
                    f"{len(runtime.output_attrs)} attributes, window has "
                    f"{len(w.definition.attributes)}"
                )
            runtime.output_junction = w
        elif isinstance(out, InsertIntoStream):
            target = out.target_id
            if partition_ctx is not None and out.is_inner_stream:
                # '#stream' scoped to this partition; events carry pk ids
                inner_id = "#" + target
                if inner_id not in partition_ctx.inner_definitions:
                    sdef = StreamDefinition(
                        id=inner_id,
                        attributes=[Attribute(n, t) for n, t in runtime.output_attrs],
                    )
                    partition_ctx.inner_definitions[inner_id] = sdef
                    partition_ctx.inner_junctions[inner_id] = StreamJunction(
                        sdef, self.app_context
                    )
                runtime.output_junction = partition_ctx.inner_junctions[inner_id]
                runtime.attach_pk = True
            else:
                if target not in self.stream_definitions:
                    # auto-define the output stream (reference OutputParser)
                    sdef = StreamDefinition(
                        id=target,
                        attributes=[Attribute(n, t) for n, t in runtime.output_attrs],
                    )
                    self.stream_definitions[target] = sdef
                    self._create_junction(sdef)
                else:
                    # inserting into an existing stream requires an
                    # equivalent schema (reference
                    # AbstractDefinition.checkEquivalency via OutputParser —
                    # SimpleQueryValidatorTestCase duplicate-definition)
                    existing = self.stream_definitions[target]
                    dattrs = [(a.name, a.type) for a in existing.attributes]
                    if list(runtime.output_attrs) != dattrs:
                        raise SiddhiAppValidationException(
                            f"query '{query_name}' inserts "
                            f"{list(runtime.output_attrs)} into stream "
                            f"'{target}' defined as {dattrs}")
                runtime.output_junction = self.junctions[target]
                if (partition_ctx is not None
                        and target in getattr(partition_ctx,
                                              "local_streams", ())):
                    # a partition-mate consumes this stream: outputs must
                    # carry the producing instance's pk
                    runtime.attach_pk = True
                # record set-element types on the target stream so later
                # queries (unionSet/sizeOfSet over this stream) and event
                # decode know how to interpret object set columns
                ometa = {n: t for n, t in getattr(
                    runtime.selector_plan, "object_meta", {}).items()
                    if t is not None}
                omulti = getattr(runtime.selector_plan, "object_multi", [])
                if ometa or omulti:
                    tdef = self.stream_definitions[target]
                    merged = dict(getattr(tdef, "object_elem_types", None) or {})
                    merged.update(ometa)
                    tdef.object_elem_types = merged
                    tdef.object_multi_attrs = (
                        set(getattr(tdef, "object_multi_attrs", None) or set())
                        | set(omulti))
        elif out is not None:
            raise SiddhiAppValidationException(
                f"unsupported output action {type(out).__name__}")

        from siddhi_tpu.query_api.execution import JoinInputStream, StateInputStream

        sp = getattr(runtime, "selector_plan", None)
        agg_positions = tuple(getattr(sp, "agg_positions", ()) or ())
        # every join counts as windowed (QueryParser.java:149); a named
        # window source is windowed too (the window junction delivers its
        # expireds); else a #window handler on the single stream
        src_id = getattr(query.input_stream, "unique_stream_id", None)
        windowed = (isinstance(query.input_stream, JoinInputStream)
                    or src_id in self.named_windows
                    or getattr(runtime, "window_stage", None) is not None
                    or getattr(runtime, "host_window", None) is not None)
        group_key_fn = None
        if query.selector.group_by_list and rate_uses_group_key(
                query.output_rate, windowed, agg_positions):
            # grouped queries get per-group limiter variants (reference
            # OutputParser picks the GroupBy limiter classes)
            gb_names = {v.attribute_name for v in query.selector.group_by_list}
            positions = tuple(i for i, (n, _t) in enumerate(runtime.output_attrs)
                              if n in gb_names)
            if positions:
                group_key_fn = lambda ev, _p=positions: tuple(  # noqa: E731
                    ev.data[i] for i in _p)
            else:
                # group key not projected (`select sum(calls) group by ip`):
                # ride the dense group-id column into Event.gk — the
                # reference keys its limiters on GroupedComplexEvent's
                # groupKey, which exists whether or not it is selected.
                # Inside partitions GK already folds the partition id in
                # (GroupKeyer keys on (pk, group)), so grouping stays
                # correct per partition instance.
                runtime.limiter_needs_gk = True
                group_key_fn = lambda ev: ev.gk  # noqa: E731
        # inside a partition each key is its OWN query instance in the
        # reference — wrap the limiter per partition key (events carry pk)
        limiter_partitioned = (partition_ctx is not None
                               and query.output_rate is not None)
        if limiter_partitioned:
            runtime.limiter_needs_pk = True
        runtime.rate_limiter = create_rate_limiter(
            query.output_rate, runtime.send_to_callbacks, group_key_fn,
            partitioned=limiter_partitioned,
            windowed=windowed,
            agg_positions=agg_positions,
            out_size=len(getattr(runtime, "output_attrs", ()) or ()),
            empty_send=getattr(runtime, "send_empty_to_query_callbacks", None))
        runtime.scheduler = self.app_context.scheduler

        if isinstance(query.input_stream, StateInputStream):
            # pattern/sequence: one proxy receiver per consumed stream
            for sid, proxy in runtime.make_proxies().items():
                self.junctions[sid].subscribe(proxy)
        elif isinstance(query.input_stream, JoinInputStream):
            # table sides have no proxy; named-window sides subscribe to the
            # window's emission junction, stream sides to their junction
            proxies = runtime.make_proxies()
            _left_sid = query.input_stream.left.unique_stream_id
            _right_sid = query.input_stream.right.unique_stream_id
            for side_key, s in (("left", query.input_stream.left),
                                ("right", query.input_stream.right)):
                if side_key not in proxies:
                    continue
                sid = s.unique_stream_id
                if sid in self.named_windows:
                    if _left_sid == _right_sid:
                        # a window joined with ITSELF processes each
                        # emission through ONE side chain only (reference
                        # MultiProcessStreamReceiver with processCount=1 —
                        # JoinInputStreamParser.java:129-135; both sides
                        # triggering would emit every match twice). Keep
                        # the TRIGGERING side (unidirectional joins pin it).
                        from siddhi_tpu.query_api.execution import EventTrigger

                        keep = ("right" if query.input_stream.trigger
                                == EventTrigger.RIGHT else "left")
                        if side_key != keep:
                            continue
                    self.named_windows[sid].out_junction.subscribe(proxies[side_key])
                elif (partition_ctx is not None and s.is_inner_stream):
                    if sid not in partition_ctx.inner_junctions:
                        raise SiddhiAppValidationException(
                            f"inner stream '{sid}' is consumed before any "
                            f"query in this partition produces it")
                    partition_ctx.inner_junctions[sid].subscribe(
                        proxies[side_key])
                else:
                    self.junctions[sid].subscribe(proxies[side_key])
        elif partition_ctx is not None and query.input_stream.is_inner_stream:
            input_stream_id = query.input_stream.unique_stream_id
            if input_stream_id not in partition_ctx.inner_junctions:
                raise SiddhiAppValidationException(
                    f"inner stream '{input_stream_id}' is consumed before any query "
                    f"in this partition produces it"
                )
            partition_ctx.inner_junctions[input_stream_id].subscribe(runtime)
        elif query.input_stream.unique_stream_id in self.named_windows:
            # `from W`: consume the named window's emissions
            self.named_windows[query.input_stream.unique_stream_id].out_junction.subscribe(runtime)
        else:
            self.junctions[query.input_stream.unique_stream_id].subscribe(runtime)
        self.query_runtimes[query_name] = runtime
        if partition_ctx is not None:
            partition_ctx.runtimes.append(runtime)

    # ------------------------------------------------------------- API

    def get_input_handler(self, stream_id: str) -> InputHandler:
        return self.input_manager.get_input_handler(stream_id)

    # Java-style alias
    getInputHandler = get_input_handler

    def add_callback(self, id_: str, callback):
        """addCallback(streamId, StreamCallback) or (queryName, QueryCallback)
        — reference SiddhiAppRuntimeImpl overloads."""
        if isinstance(callback, StreamCallback):
            if id_ not in self.junctions:
                raise SiddhiAppValidationException(f"stream '{id_}' is not defined")
            callback.stream_id = id_
            self.junctions[id_].subscribe(callback)
            self._stream_callback_adapters.append(callback)
        elif isinstance(callback, QueryCallback):
            if id_ not in self.query_runtimes:
                raise SiddhiAppValidationException(f"query '{id_}' not found")
            callback.query_name = id_
            self.query_runtimes[id_].query_callbacks.append(callback)
        else:
            raise TypeError(f"unsupported callback type {type(callback)}")

    addCallback = add_callback

    def remove_callback(self, callback):
        """Detach a previously added Stream/QueryCallback (reference
        SiddhiAppRuntimeImpl.removeCallback — CallbackTestCase: events
        sent after removal no longer reach it)."""
        if isinstance(callback, StreamCallback):
            j = self.junctions.get(getattr(callback, "stream_id", ""))
            if j is not None and callback in j.receivers:
                j.receivers.remove(callback)
            if callback in self._stream_callback_adapters:
                self._stream_callback_adapters.remove(callback)
        elif isinstance(callback, QueryCallback):
            for qr in self.query_runtimes.values():
                if callback in qr.query_callbacks:
                    qr.query_callbacks.remove(callback)

    removeCallback = remove_callback

    def start(self):
        with self._barrier:  # lazy start can race concurrent first sends
            if self._started:
                return
            self._started = True
            # critical-path profiler knobs: refcounted process-wide
            # enables, paired one-for-one with the disables in shutdown()
            if not self._profiling_on and (self.app_context.profile_journeys
                                           or self.app_context.profile_costs):
                from siddhi_tpu.observability import costmodel, journey

                if self.app_context.profile_journeys:
                    journey.enable()
                if self.app_context.profile_costs:
                    costmodel.enable()
                self._profiling_on = True
            # device telemetry plane: default-on per-app knob holds one
            # refcount on the process collector for the app's lifetime
            # (same discipline as profile_journeys)
            if (not self._instruments_on
                    and self.app_context.profile_device_instruments):
                from siddhi_tpu.observability import instruments

                instruments.enable()
                self._instruments_on = True
            # multicore ingest (core/stream/input/pack_pool.py): with
            # siddhi_tpu.ingest_pool > 0, pack/encode work shards across
            # that many supervised worker threads; every pack call site
            # reads the pool through core.event.pack_pool_of
            if (self.app_context.ingest_pool > 0
                    and self.app_context.ingest_pack_pool is None):
                from siddhi_tpu.core.stream.input.pack_pool import (
                    IngestPackPool,
                )

                self.app_context.ingest_pack_pool = IngestPackPool(
                    self.app_context,
                    workers=self.app_context.ingest_pool,
                    split_rows=self.app_context.ingest_split)
            # closed-loop controller (siddhi_tpu/autopilot/): register
            # with the per-process controller when the knob is armed —
            # 'off' (the default) keeps the engine free of any
            # controller thread, observation or actuation
            if getattr(self.app_context, "autopilot", "off") != "off":
                from siddhi_tpu.autopilot.controller import (
                    AutopilotController,
                )

                AutopilotController.instance().register(self)
            for j in self.junctions.values():
                j.start_processing()
            scheduler = self.app_context.scheduler
            for qr in self.query_runtimes.values():
                if qr.rate_limiter is not None:
                    qr.rate_limiter.start(scheduler)
                if hasattr(qr, "arm_initial"):
                    qr.arm_initial()  # head-absent patterns wait from start
            for sr in self.sink_runtimes:
                sr.connect()
            for sr in self.source_runtimes:
                # connect with retry/backoff off-thread (Source.java:155-185)
                t = threading.Thread(target=sr.connect_with_retry, daemon=True)
                t.start()
            for agg in self.aggregations.values():
                if agg.purge_enabled and scheduler is not None:
                    scheduler.schedule_periodic(
                        agg.purge_interval_ms,
                        lambda ts, a=agg: a.purge(ts))
            # cache-table retention sweeps (reference CacheExpirer: a
            # periodic task deletes cache rows older than retention.period)
            for t in self.tables.values():
                cache = getattr(t, "cache", None)
                if (cache is not None and cache.retention_ms is not None
                        and scheduler is not None):
                    scheduler.schedule_periodic(
                        cache.purge_interval_ms,
                        lambda _ts, c=cache: c.expire())
            if self.app_context.statistics_manager is not None:
                self.app_context.statistics_manager.start_reporting(scheduler)
                self._register_statistic_probes()
            for pctx in self.partition_contexts:
                if pctx.purge_interval_ms is not None and scheduler is not None:
                    scheduler.schedule_periodic(
                        pctx.purge_interval_ms,
                        lambda _ts, p=pctx: p.purge())  # wall clock, not event time
            for tr in self.trigger_runtimes:
                tr.start()

    def debug(self):
        """Attach a SiddhiDebugger (reference SiddhiAppRuntime.debug)."""
        from siddhi_tpu.core.debugger import SiddhiDebugger

        if getattr(self, "_debugger", None) is None:
            # breakpoints instrument per-runtime delivery methods, which a
            # fused group bypasses — debugging runs unfused
            for g in list(self.fused_fanout_groups):
                g.dissolve()
            self.fused_fanout_groups = []
            self._debugger = SiddhiDebugger(self)
        return self._debugger

    def _register_statistic_probes(self):
        """DETAIL memory + buffered-events probes for every stateful
        element — the analog of ``SiddhiAppRuntimeImpl.
        monitorQueryMemoryUsage:757-782`` (reflective deep size there;
        exact pytree/array nbytes here) and ``monitorBufferedEvents:
        784-821`` (@Async ring fill there; junction queue depth + deferred
        device outputs here). Idempotent — probes are keyed by name."""
        from siddhi_tpu.core.util.statistics import pytree_nbytes

        sm = self.app_context.statistics_manager
        if sm is None:
            return
        # dirty-guard: probe sets only change when runtimes are built, so
        # a statistics() polling loop must not rebuild closures per poll
        sig = (len(self.query_runtimes), len(self.tables),
               len(self.named_windows), len(self.aggregations),
               len(self.junctions))
        if getattr(self, "_probe_sig", None) == sig:
            return
        self._probe_sig = sig
        for name, qr in self.query_runtimes.items():
            sm.register_memory_probe(
                f"query.{name}", lambda q=qr: pytree_nbytes(q._state))
            sm.register_buffer_probe(
                f"query.{name}.deferred_outputs",
                lambda q=qr: len(q._deferred))
        for name, t in self.tables.items():
            sm.register_memory_probe(
                f"table.{name}", lambda tb=t: _element_state_bytes(tb))
        for name, w in self.named_windows.items():
            sm.register_memory_probe(
                f"window.{name}", lambda win=w: _element_state_bytes(win))
        for name, agg in self.aggregations.items():
            sm.register_memory_probe(
                f"aggregation.{name}", lambda a=agg: _agg_store_bytes(a))
        for sid, j in self.junctions.items():
            if getattr(j, "_queue", None) is not None:
                sm.register_buffer_probe(
                    f"junction.{sid}", lambda jn=j: jn._queue.qsize())

    def statistics(self) -> dict:
        """Metrics snapshot (reference SiddhiAppRuntime.getStatistics)."""
        sm = self.app_context.statistics_manager
        if sm is None:
            return {"level": "off"}
        self._register_statistic_probes()   # cover late-built runtimes
        return sm.report()

    def set_statistics_level(self, level: str):
        """'off' | 'basic' | 'detail' (reference setStatisticsLevel)."""
        from siddhi_tpu.core.util.statistics import StatisticsManager, parse_level

        if self.app_context.statistics_manager is None:
            self.app_context.statistics_manager = StatisticsManager()
        self.app_context.statistics_manager.set_level(parse_level(level))
        self._register_statistic_probes()

    setStatisticsLevel = set_statistics_level

    def start_trace(self, log_dir: str):
        """Start a device-level profiler trace (XLA/TPU timeline) into
        ``log_dir`` — the TPU-native answer to the reference's latency
        tracker detail level: per-op device timings come from the XLA
        profiler rather than per-processor stopwatches. View with
        TensorBoard or xprof."""
        import jax

        if getattr(self, "_tracing", False):
            raise RuntimeError("a trace is already running")
        jax.profiler.start_trace(log_dir)
        self._tracing = True
        return log_dir

    def stop_trace(self):
        import jax

        if not getattr(self, "_tracing", False):
            raise RuntimeError("no trace is running")
        jax.profiler.stop_trace()
        self._tracing = False

    def shutdown(self):
        self.app_context.stopped = True
        if getattr(self.app_context, "autopilot", "off") != "off":
            # detach FIRST: no actuation may land on a tearing-down app
            # (identity-pinned — an old runtime never strips a newer
            # same-named app's controller registration)
            from siddhi_tpu.autopilot.controller import AutopilotController

            AutopilotController.instance().unregister(
                self.app_context.name, app_runtime=self)
        if self.app_context.supervisor is not None:
            self.app_context.supervisor.stop()
        if getattr(self.app_context, "overload", None) is not None:
            # drop the process-global registration (fair-scheduler slot,
            # per-app control); identity-pinned so shutting down an OLD
            # runtime never strips a newer same-named app's quotas
            from siddhi_tpu.resilience.overload import OverloadManager

            OverloadManager.instance().unregister(
                self.app_context.name, ctl=self.app_context.overload)
            self.app_context.overload = None
        self.app_context.timestamp_generator.stop_heartbeat()
        pump = getattr(self.app_context, "completion_pump", None)
        if pump is not None and pump.has_pending:
            # batches still riding the dispatch pipeline emit before
            # teardown (async tails are additionally flushed by each
            # worker as its last act on the stop sentinel)
            try:
                pump.flush()
            except RuntimeError:
                import logging

                logging.getLogger(__name__).exception(
                    "pipeline flush failed during shutdown")
        for qr in self.query_runtimes.values():
            if getattr(qr, "_deferred", None):
                try:
                    qr.flush_deferred()
                except RuntimeError:
                    # deferred overflow error must not abort teardown —
                    # outputs were drained before the raise
                    import logging

                    logging.getLogger(__name__).exception(
                        "deferred flush failed during shutdown")
        if self.app_context.statistics_manager is not None:
            self.app_context.statistics_manager.stop_reporting(
                self.app_context.scheduler)
        for sr in self.source_runtimes:
            sr.shutdown()
        for tr in self.trigger_runtimes:
            tr.stop()
        for qr in self.query_runtimes.values():
            if qr.rate_limiter is not None:
                qr.rate_limiter.stop()
        for j in self.junctions.values():
            j.stop_processing()
        for sr in self.sink_runtimes:
            sr.shutdown()
        if self.app_context.ingest_pack_pool is not None:
            # after junction workers stopped: no pack can be in flight
            self.app_context.ingest_pack_pool.shutdown()
            self.app_context.ingest_pack_pool = None
        if self.app_context.scheduler is not None:
            self.app_context.scheduler.shutdown()
        from siddhi_tpu.core.util import program_cache

        # release this app's refs on the process-global compiled-program
        # cache; entries reaching refcount zero evict (free) here. The
        # owner token is this runtime's telemetry-registry INSTANCE
        # (identity-pinned, the blue/green convention): an OLD runtime's
        # shutdown can never strip the programs a newer same-named app
        # acquired through ITS registry.
        program_cache.cache().release_owner(self.app_context.telemetry)
        from siddhi_tpu.observability import journey

        # this app's wall-tracking must die with it (a redeployed
        # same-named app starts a fresh observation window)
        journey.forget_app(self.app_context.name)
        if self._profiling_on:
            # release this runtime's refcount on the process collectors
            from siddhi_tpu.observability import costmodel

            if self.app_context.profile_journeys:
                journey.disable()
            if self.app_context.profile_costs:
                costmodel.disable()
            self._profiling_on = False
        if self._instruments_on:
            from siddhi_tpu.observability import instruments

            instruments.disable()
            self._instruments_on = False
        self._started = False

    # ----------------------------------------------------- resilience API

    def enable_autopilot(self, mode: str = "on",
                         interval_s: Optional[float] = None,
                         cooldown_s: Optional[float] = None):
        """Arm the closed-loop controller (``siddhi_tpu/autopilot/``)
        programmatically — the API spelling of the
        ``siddhi_tpu.autopilot`` config knob. ``mode`` is ``'on'`` or
        ``'dry_run'`` (decide + log, never actuate). Idempotent;
        registration with the per-process controller happens here when
        the app already started, else at ``start()``. Returns the
        controller."""
        from siddhi_tpu.autopilot.controller import AutopilotController
        from siddhi_tpu.core.util.knobs import KNOBS

        self.app_context.autopilot = KNOBS["autopilot"].parse(mode)
        if self.app_context.autopilot == "off":
            raise ValueError("enable_autopilot with mode 'off' — use the "
                             "config knob to keep the controller out")
        if interval_s is not None:
            self.app_context.autopilot_interval_s = float(interval_s)
        if cooldown_s is not None:
            self.app_context.autopilot_cooldown_s = float(cooldown_s)
        ctl = AutopilotController.instance()
        if self._started:
            ctl.register(self)
        return ctl

    def enable_wal(self, max_batches: int = 4096,
                   max_events: Optional[int] = None):
        """Attach a bounded ingest WAL (``resilience/replay.py``): every
        accepted batch is recorded until the next checkpoint barrier trims
        it; ``restore_revision`` replays the retained suffix, turning
        checkpoint recovery from at-most-once into effectively-once.
        Idempotent; returns the WAL."""
        from siddhi_tpu.resilience.replay import IngestWAL, register_wal_gauges

        if self.app_context.ingest_wal is None:
            self.app_context.ingest_wal = IngestWAL(
                max_batches=max_batches, max_events=max_events,
                app_context=self.app_context)
        # scrapeable WAL size/loss gauges (GET /metrics): a log that
        # keeps dropping batches means checkpoints are too far apart for
        # the configured bound
        register_wal_gauges(self.app_context)
        return self.app_context.ingest_wal

    def supervise(self, interval_s: float = 0.25,
                  wedge_timeout_s: float = 5.0, peer_recovery=None,
                  peer_monitor=None):
        """Start an ``AppSupervisor`` (``resilience/supervisor.py``) that
        heartbeats this app's @Async junction workers — restarting dead or
        wedged ones with their queues intact — and, when ``peer_recovery``
        is given, runs the cluster-peer recovery protocol on a peer
        failure (a ``ClusterPeerError`` from the bounded pull, or a lost
        ``peer_monitor`` heartbeat). Idempotent; returns the supervisor."""
        from siddhi_tpu.resilience.supervisor import AppSupervisor

        if self.app_context.supervisor is None:
            AppSupervisor(self, interval_s=interval_s,
                          wedge_timeout_s=wedge_timeout_s,
                          peer_recovery=peer_recovery,
                          peer_monitor=peer_monitor).start()
        return self.app_context.supervisor

    # ---------------------------------------------------- persistence API

    @property
    def persistence(self):
        from siddhi_tpu.core.util.snapshot import PersistenceManager

        if getattr(self, "_persistence", None) is None:
            self._persistence = PersistenceManager(self)
        return self._persistence

    def persist(self) -> str:
        """Checkpoint all state to the configured persistence store;
        returns the revision id (reference SiddhiAppRuntimeImpl.persist:677).
        Sources are paused around the snapshot so no events race the
        checkpoint (reference pauses source handlers during persist)."""
        for sr in self.source_runtimes:
            sr.pause()
        try:
            return self.persistence.persist()
        finally:
            for sr in self.source_runtimes:
                sr.resume()

    def persist_incremental(self) -> str:
        """Op-log checkpoint chained to the last revision (reference
        incremental snapshots); falls back to full when none exists."""
        for sr in self.source_runtimes:
            sr.pause()
        try:
            return self.persistence.persist_incremental()
        finally:
            for sr in self.source_runtimes:
                sr.resume()

    def restore_revision(self, revision: str):
        self.persistence.restore_revision(revision)

    restoreRevision = restore_revision

    def restore_last_revision(self):
        return self.persistence.restore_last_revision()

    restoreLastRevision = restore_last_revision

    def clear_all_revisions(self):
        self.persistence.clear_all_revisions()

    def snapshot(self) -> bytes:
        """Raw state snapshot bytes (reference SiddhiAppRuntime.snapshot)."""
        from siddhi_tpu.core.util.snapshot import SnapshotService

        with self._barrier:
            return SnapshotService(self).full_snapshot()

    def restore(self, snapshot: bytes):
        from siddhi_tpu.core.util.snapshot import SnapshotService

        with self._barrier:
            SnapshotService(self).restore(snapshot)

    # ------------------------------------------------------ on-demand API

    def query(self, on_demand_query: str) -> List[Event]:
        """Run an ad-hoc (store) query against a table or named window —
        reference ``SiddhiAppRuntimeImpl.query`` +
        ``util/parser/OnDemandQueryParser.java``."""
        from siddhi_tpu.core.query.on_demand import run_on_demand_query
        from siddhi_tpu.ops import expressions as _expr_mod

        # lazy compiles resolve against THIS app's registry (manager
        # extensions + script functions)
        _expr_mod.set_active_extensions(self._extensions)

        # barrier management lives in run_on_demand_query: mutations and
        # table/window finds serialize on the app barrier as before, but
        # aggregation store-queries read epoch-pinned per-shard snapshots
        # and must NOT hold it — the serving tier's whole point is that a
        # dashboard query storm never stalls ingest (which takes the same
        # barrier on every send)
        return run_on_demand_query(on_demand_query, self)

    @property
    def query_names(self) -> List[str]:
        return list(self.query_runtimes)

    def get_queries(self) -> List:
        """Query runtimes in declaration order (reference
        ``SiddhiAppRuntime.getQueries``)."""
        return list(self.query_runtimes.values())


def _element_state_bytes(el) -> int:
    """State footprint of a table or named window, whatever its backing:
    dense arrays (``state``), a store-backed adapter (row count x columnar
    row width, incl. its cache rows), or a host-mode window's columnar
    probe surface."""
    from siddhi_tpu.core.util.statistics import pytree_nbytes

    st = getattr(el, "state", None)
    if st is not None:
        return pytree_nbytes(st)
    if hasattr(el, "count") and hasattr(el, "col_specs"):
        # RecordTableAdapter: rows live behind the SPI; size them by the
        # columnar row width this adapter would encode them at
        import numpy as np

        row = sum(np.dtype(d).itemsize + 1 for d in el.col_specs.values())
        n = int(el.count)
        cache = getattr(el, "cache", None)
        return n * row + (len(cache) * row if cache is not None else 0)
    if hasattr(el, "contents"):
        c = el.contents()   # host-mode named window
        return pytree_nbytes(c[0] if isinstance(c, tuple) else c)
    return 0


def _agg_store_bytes(agg) -> int:
    """State footprint of an incremental aggregation: the host cube's
    stored base values (8 bytes each — floats/longs in per-group lists)
    plus any array-valued running state. The reference sizes this with a
    reflective object walk (ObjectSizeCalculator.java:66); the dense cube
    makes it a direct count."""
    total = 0
    # sharded serving runtimes hold their cube in per-shard stores
    for holder in (getattr(agg, "shards", None) or [agg]):
        for dstore in getattr(holder, "store", {}).values():
            for groups in dstore.values():
                for vals in groups.values():
                    total += 8 * len(vals)
    for v in vars(agg).values():
        if hasattr(v, "nbytes"):
            total += int(v.nbytes)
    return total
