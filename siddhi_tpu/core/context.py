"""Dependency-injection contexts threaded through the runtime.

Mirror of the reference's ``SiddhiContext`` (per-manager),
``SiddhiAppContext`` (per-app: executors, snapshot service, playback clock,
root timestamp) and ``SiddhiQueryContext`` (per-query state-holder factory)
— ``core/config/*.java``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from siddhi_tpu.core.event import StringDictionary


class SiddhiContext:
    """Per-SiddhiManager shared services (reference ``SiddhiContext.java``)."""

    def __init__(self):
        self.extensions: Dict[str, type] = {}
        self.persistence_store = None
        self.incremental_persistence_store = None
        self.config_manager = None
        self.attributes: Dict[str, object] = {}


class TimestampGenerator:  # graftlint: disable=R8 — listener list is
    # mutated at single-threaded wiring time only; the heartbeat thread
    # iterates a snapshot under the app barrier, and one-shot listeners
    # remove themselves inside that same barrier'd iteration
    """Event/wall clock (reference ``util/timestamp/TimestampGeneratorImpl.java:31``):
    live mode returns wall time; playback mode returns the last event
    timestamp (+ configurable idle increment handled by the scheduler)."""

    def __init__(self):
        self.playback = False
        self._last_event_ts: int = -1
        self._increment_listeners = []
        # @app:playback(idle.time, increment) heartbeat (reference
        # TimestampGeneratorImpl idle task): when no event arrives for
        # idle_ms of WALL time, the event clock advances by increment_ms
        self._hb_idle_ms: int = 0
        self._hb_increment_ms: int = 0
        self._hb_thread = None
        self._hb_stop = None

    def current_time(self) -> int:
        if self.playback and self._last_event_ts >= 0:
            return self._last_event_ts
        return int(time.time() * 1000)

    def set_current_timestamp(self, ts: int):
        if ts > self._last_event_ts:
            self._last_event_ts = ts
            # snapshot: one-shot listeners remove themselves mid-iteration
            for listener in tuple(self._increment_listeners):
                listener(ts)
        if self._hb_idle_ms and self._hb_thread is None:
            self._start_heartbeat()

    def configure_heartbeat(self, idle_ms: int, increment_ms: int):
        self._hb_idle_ms = int(idle_ms)
        self._hb_increment_ms = int(increment_ms)

    def set_heartbeat_barrier(self, lock):
        """The app's ingestion barrier (snapshot quiesce gate): heartbeat
        ticks advance the clock under it so they serialize with
        InputHandler.send and persistence snapshots."""
        self._hb_barrier = lock

    def _start_heartbeat(self):
        import threading

        self._hb_stop = threading.Event()
        stop = self._hb_stop
        barrier = getattr(self, "_hb_barrier", None) or threading.RLock()

        def _run():
            seen = self._last_event_ts
            while not stop.wait(self._hb_idle_ms / 1000.0):
                with barrier:
                    cur = self._last_event_ts
                    if cur == seen and cur >= 0 and not stop.is_set():
                        # idle: advance the event clock (fires timers)
                        self.set_current_timestamp(cur + self._hb_increment_ms)
                    seen = self._last_event_ts

        self._hb_thread = threading.Thread(
            target=_run, name="playback-heartbeat", daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self):
        # zero idle_ms FIRST: the lazy-start guard in set_current_timestamp
        # must never resurrect a thread after shutdown (a tick in flight
        # could otherwise re-enter it with _hb_thread already None)
        self._hb_idle_ms = 0
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_thread = None
            self._hb_stop = None

    def reset_timestamp(self, ts: int):
        """Force the event clock (restore/rollback): unlike
        ``set_current_timestamp`` this may move BACKWARD, and fires no
        time-change listeners (restored timers re-arm separately)."""
        self._last_event_ts = int(ts)

    def add_time_change_listener(self, fn):
        self._increment_listeners.append(fn)

    def remove_time_change_listener(self, fn):
        """One-shot listeners (e.g. playback head-wait arming) unregister
        themselves so the per-event clock path stays listener-free."""
        try:
            self._increment_listeners.remove(fn)
        except ValueError:
            pass

    def once_first_time(self, fn):
        """Run ``fn(first_ts)`` when the event clock first advances
        (playback arming: wall time is unreachable by the event clock, so
        periodic cycles and quiet windows anchor at the FIRST event ts).
        Returns a cancel() callable — callers MUST cancel on re-arm or
        job cancellation, or a stale anchor starts a second chain."""
        def _listener(ts: int):
            self.remove_time_change_listener(_listener)
            fn(ts)

        self.add_time_change_listener(_listener)

        def cancel():
            self.remove_time_change_listener(_listener)

        return cancel


class SiddhiAppContext:
    """Per-app context (reference ``core/config/SiddhiAppContext.java``)."""

    def __init__(self, siddhi_context: SiddhiContext, name: str):
        self.siddhi_context = siddhi_context
        self.name = name
        self.timestamp_generator = TimestampGenerator()
        self.string_dictionary = StringDictionary()
        self.snapshot_service = None
        self.scheduler = None
        self.statistics_manager = None
        # always-on telemetry registry (observability/telemetry.py):
        # gauges (@Async queue depth, WAL size), backpressure counters,
        # jit-compile events — scraped via GET /metrics; kept separate
        # from statistics_manager, which only exists under
        # @app:statistics and gates by level
        from siddhi_tpu.observability.telemetry import TelemetryRegistry

        self.telemetry = TelemetryRegistry()
        # bind the registry to this context: InstrumentedJit reads the
        # program-cache knobs through it, and the registry INSTANCE is
        # the app's identity-pinned owner token in the process-global
        # compiled-program cache (core/util/program_cache.py) — unique
        # per runtime, so a blue/green replace's old-runtime shutdown
        # can never release the new runtime's refs
        self.telemetry.app_context = self
        self.telemetry.owner_name = name
        self.playback = False
        self.enforce_order = False
        self.root_metrics_level = "OFF"
        # key-capacity defaults for dense state (padded, grows by recompile)
        self.initial_key_capacity = 16
        # ring-buffer capacity for unbounded (time-based) windows
        self.window_capacity = 4096
        # per-key ring capacity for time windows inside partitions
        self.partition_window_capacity = 256
        # pending-match slot capacity per key for pattern/sequence queries
        self.nfa_slots = 32
        # device numeric precision: 'exact' = 64-bit accumulators (matches
        # the reference's double math bit-for-bit; CPU default), 'fast' =
        # 32-bit on-device (TPU default — v5e emulates 64-bit in software).
        # Overridable with @app:precision('exact'|'fast').
        self.precision = _default_precision()
        # >1: batch N step metas into ONE device->host round trip, emitting
        # outputs (and surfacing overflow errors) up to N batches late —
        # the tunnel charges ~70ms latency per pull (see PERF.md). Set via
        # ConfigManager key siddhi_tpu.defer_meta. DEPRECATED: values >1
        # are remapped onto pipeline_depth at app build (app_runtime.py).
        self.defer_meta = 1
        # dispatch pipeline depth: up to N device batches per query ride
        # in flight while the host packs the next batch; emission stays
        # in per-query dispatch order and overflow errors surface on the
        # producer's next send (core/query/completion.py). 1 = fully
        # synchronous (today's pull-per-batch). Set via ConfigManager key
        # siddhi_tpu.pipeline_depth; SIDDHI_TPU_PIPELINE_DEPTH overrides
        # the process default (typed read — junk spellings raise naming
        # the variable, core/util/knobs.py).
        from siddhi_tpu.core.util.knobs import env_knob

        self.pipeline_depth = env_knob("SIDDHI_TPU_PIPELINE_DEPTH",
                                       "int", 2)
        from siddhi_tpu.core.query.completion import CompletionPump

        self.completion_pump = CompletionPump(self)
        # multi-process clusters: bound every device pull by this many
        # seconds; a peer process dying mid-collective otherwise hangs
        # the coordinator forever (ClusterPeerError surfaces through the
        # junction's @OnError/fault-stream machinery). Set via
        # ConfigManager key siddhi_tpu.cluster_step_timeout. None = off.
        self.cluster_step_timeout = None
        # fold window evictions into invertible aggregator deltas where the
        # query shape allows (ops/fused_agg.py); off = always-generic path
        self.enable_fusion = True
        # fan-out fusion: sibling single-stream queries on one junction
        # compile into ONE jitted step with ONE combined __meta__ pull per
        # batch (core/plan/fanout_plan.py + core/query/fused_fanout.py).
        # Off = every query keeps its own dispatch. Set via ConfigManager
        # key siddhi_tpu.fuse_fanout.
        self.fuse_fanout = True
        # critical-path profiler (siddhi_tpu/observability/journey.py +
        # costmodel.py): batch-journey stage tracing and first-compile
        # program-cost capture. Both enable a PROCESS-wide collector for
        # this runtime's lifetime (refcounted across apps). Keys
        # siddhi_tpu.profile_journeys / siddhi_tpu.profile_costs;
        # SIDDHI_TPU_PROFILE_COSTS=1 and POST /profile/* flip them
        # process-wide without a config.
        self.profile_journeys = False
        self.profile_costs = False
        # process-global compiled-program cache (core/util/program_cache.py):
        # identical step programs compile ONCE and share the immutable
        # executable across tenant apps (per-app state pytrees stay
        # private). Default on; 'false' restores per-app compiles.
        # program_cache_max caps live entries. Keys
        # siddhi_tpu.program_cache / siddhi_tpu.program_cache_max;
        # SIDDHI_TPU_PROGRAM_CACHE / _MAX set the process defaults.
        self.program_cache = env_knob("SIDDHI_TPU_PROGRAM_CACHE",
                                      "bool", True)
        self.program_cache_max = env_knob("SIDDHI_TPU_PROGRAM_CACHE_MAX",
                                          "int", 256)
        # device telemetry plane (observability/instruments.py): jitted
        # steps append declared instrument slots (window ring fill, join
        # partition fill, NFA active runs, routed-row skew, distinct
        # groups) behind the standard [overflow, notify, count] meta
        # prefix — device truth per batch at ZERO extra host transfers.
        # Default ON; 'false' keeps the pre-round-9 meta layouts
        # bit-for-bit. Key siddhi_tpu.profile_device_instruments.
        self.profile_device_instruments = True
        # serving tier (siddhi_tpu/serving/): >1 key-partitions every
        # incremental aggregation's bucket state across this many
        # in-process shards (round-robin over mesh devices) and answers
        # on-demand `within ... per ...` queries by scatter-gather ordered
        # merge. Set via ConfigManager key siddhi_tpu.agg_shards.
        # @PartitionById (DB shard-stitch) aggregations keep the legacy
        # single-store runtime regardless — see MIGRATION.md.
        self.agg_shards = 1
        # per-shard bounded WAL (batches) backing the shard rebuild
        # protocol; 0 disables shard WALs. Key siddhi_tpu.agg_shard_wal.
        self.agg_shard_wal = 1024
        # device join engine (core/join/): 'device' attaches the
        # PanJoin-style partitioned probe engine to eligible stream-stream
        # window joins (pipeline/fusion-eligible fused insert+probe step);
        # 'legacy' keeps the reference synchronous broadcast-probe path
        # wholesale. Key siddhi_tpu.join_engine.
        self.join_engine = "device"
        # build-side hash partitions per join side (pow2, clamped to 64);
        # partition-local probes cut the [N, W] probe surface ~P-fold.
        # 0 = auto: 8 on accelerator backends, 1 on the CPU fallback —
        # the directory's gathers + emission-order sort lose to the
        # vectorized broadcast compare on a scalar core (bench.py
        # --section join, PERF.md), while P = 1 keeps the fused in-state
        # step (pipeline/fusion/mesh eligibility) at legacy speed. An
        # explicit value is always honored. Key siddhi_tpu.join_partitions.
        self.join_partitions = 0
        # per-partition sub-window slack factor: each [P, W*slack/P]
        # sub-window tolerates key skew up to slack/P of the ring before
        # adaptive growth (or, with growth off, a partition overflow
        # naming this knob). Key siddhi_tpu.join_partition_slack.
        self.join_partition_slack = 2
        # adaptive sub-window growth (PanJoin re-partitioning): the host
        # mirrors each side's ring occupancy and grows Wp (capped at
        # pow2(W)) before a skewed batch could overflow a partition. Off
        # = static provisioning; overflow becomes FatalQueryError naming
        # siddhi_tpu.join_partition_slack. Key
        # siddhi_tpu.join_partition_grow.
        self.join_partition_grow = True
        # multicore ingest front door (core/stream/input/pack_pool.py):
        # ingest_pool > 0 shards HostBatch pack/encode work across that
        # many worker threads as sequence-numbered sub-batches with an
        # ordered merge — outputs and dictionary id assignment stay
        # bit-identical to the inline path. 0 (default) = inline.
        # Keys siddhi_tpu.ingest_pool / siddhi_tpu.ingest_split.
        self.ingest_pool = 0
        self.ingest_split = 8192
        # the live IngestPackPool instance (created by SiddhiAppRuntime
        # at start when ingest_pool > 0; every pack call site reads it
        # through core.event.pack_pool_of)
        self.ingest_pack_pool = None
        # resilience subsystem attach points (siddhi_tpu/resilience/):
        # bounded ingest replay log + app supervisor, set by
        # SiddhiAppRuntime.enable_wal() / .supervise()
        self.ingest_wal = None
        self.supervisor = None
        # overload armor (resilience/overload.py): per-app ingest quotas,
        # shed-policy backpressure, device-memory budget, weighted fair
        # scheduling. None = no quotas (bit-identical default behavior);
        # set by OverloadManager.register via the siddhi_tpu.quota_* /
        # siddhi_tpu.shed_policy config keys or rt.enable_overload().
        self.overload = None
        # closed-loop controller (siddhi_tpu/autopilot/): 'off'
        # (default) = no controller thread, bit-identical engine;
        # 'dry_run' = observe + decide + log, never actuate; 'on' =
        # actuate live knobs within per-knob bounds. Keys
        # siddhi_tpu.autopilot / .autopilot_interval_s /
        # .autopilot_cooldown_s; rt.enable_autopilot() flips it
        # programmatically.
        self.autopilot = "off"
        self.autopilot_interval_s = 0.25
        self.autopilot_cooldown_s = 5.0
        # reshard-actuator shard-count ceiling (0 = all addressable
        # devices); also records the autopilot's current target so a
        # report can show where the controller has driven the layout
        self.route_shards = 0
        # shared stores, filled by SiddhiAppRuntime during assembly
        self.tables = {}
        self.named_windows = {}


def _default_precision() -> str:
    import jax

    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover — backend probing must never fail
        return "exact"
    return "exact" if backend == "cpu" else "fast"


@dataclass
class SiddhiQueryContext:
    """Per-query context (reference ``core/config/SiddhiQueryContext.java``)."""

    siddhi_app_context: SiddhiAppContext = None
    name: str = ""
    partitioned: bool = False
    _state_counter: int = field(default=0)

    def generate_state_id(self) -> str:
        self._state_counter += 1
        return f"{self.name}-s{self._state_counter}"
