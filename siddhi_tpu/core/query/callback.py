"""QueryCallback: user hook on a query's output.

Mirror of reference ``core/query/output/callback/QueryCallback.java``:
``receive(timestamp, inEvents, removeEvents)`` where inEvents are CURRENT
outputs and removeEvents are EXPIRED outputs.
"""

from __future__ import annotations

from typing import List, Optional

from siddhi_tpu.core.event import Event


class QueryCallback:
    query_name: str = ""

    def receive(self, timestamp: int, in_events: Optional[List[Event]], remove_events: Optional[List[Event]]):
        raise NotImplementedError
