"""Table output callbacks: insert/delete/update/update-or-insert actions.

Mirror of reference ``query/output/callback/{InsertIntoTableCallback,
DeleteTableCallback,UpdateTableCallback,UpdateOrInsertTableCallback}.java``:
the query's output chunk becomes one columnar batch applied to the table
in a single vectorized operation.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from siddhi_tpu.core.event import Event, HostBatch
from siddhi_tpu.core.table.in_memory_table import InMemoryTable, TableConditionResolver
from siddhi_tpu.ops.expressions import CompileError, compile_condition, compile_expr
from siddhi_tpu.query_api.definitions import Attribute, StreamDefinition
from siddhi_tpu.query_api.execution import (
    DeleteStream,
    InsertIntoStream,
    UpdateOrInsertStream,
    UpdateStream,
)


def _out_def(query_name: str, output_attrs) -> StreamDefinition:
    return StreamDefinition(
        id=f"{query_name}#out",
        attributes=[Attribute(n, t) for n, t in output_attrs],
    )


class InsertIntoTableCallback:
    """Output rows appended to the table (positional schema match)."""

    def __init__(self, table: InMemoryTable, output_attrs, dictionary):
        if len(output_attrs) != len(table.definition.attributes):
            raise CompileError(
                f"insert into table '{table.definition.id}': query outputs "
                f"{len(output_attrs)} attributes, table has "
                f"{len(table.definition.attributes)}"
            )
        for (name, t), attr in zip(output_attrs, table.definition.attributes):
            if t != attr.type:
                # the inferred output definition must be equivalent to the
                # table's (reference DuplicateDefinitionException when the
                # insert-into target is a defined table with other types)
                from siddhi_tpu.compiler.errors import (
                    DuplicateDefinitionException,
                )

                raise DuplicateDefinitionException(
                    f"insert into table '{table.definition.id}': output "
                    f"attribute '{name}' is {t.value} but the table column "
                    f"'{attr.name}' is {attr.type.value}")
        self.table = table
        self.dictionary = dictionary

    def __call__(self, events: List[Event]):
        if not events:
            return
        # expired events act as regular rows here: the selector's
        # output-event-type filter already chose what reaches the table
        # (reference converts EXPIRED->CURRENT before the table op)
        rows = [Event(timestamp=e.timestamp, data=e.data) for e in events]
        batch = HostBatch.from_events(rows, self.table.definition, self.dictionary)
        self.table.insert(batch)


class _ConditionedTableCallback:
    def __init__(self, table: InMemoryTable, query_name: str, output_attrs,
                 on_condition, dictionary):
        self.table = table
        self.dictionary = dictionary
        self.out_def = _out_def(query_name, output_attrs)
        resolver = TableConditionResolver(table.definition, self.out_def, dictionary)
        self.resolver = resolver
        self.cond = compile_condition(on_condition, resolver) if on_condition is not None else None

    def _batch(self, events: List[Event]) -> Optional[HostBatch]:
        if not events:
            return None
        rows = [Event(timestamp=e.timestamp, data=e.data) for e in events]
        return HostBatch.from_events(rows, self.out_def, self.dictionary)


class DeleteTableCallback(_ConditionedTableCallback):
    def __call__(self, events: List[Event]):
        batch = self._batch(events)
        if batch is not None:
            self.table.delete(self.cond, batch)


def _compile_assignments(table, out_def, update_set, resolver):
    """[(table col, fn, type)] — explicit `set` clause, or all table
    attributes updated from same-named output attributes (reference
    UpdateTableCallback default)."""
    from siddhi_tpu.query_api.expressions import Variable

    assignments: List[Tuple[str, Callable, object]] = []
    if update_set is not None:
        for sa in update_set.set_attributes:
            attr = table.definition.attribute(sa.table_variable.attribute_name)
            fn, t = compile_expr(sa.assignment, resolver)
            assignments.append((attr.name, fn, t))
    else:
        out_names = {a.name for a in out_def.attributes}
        for attr in table.definition.attributes:
            if attr.name in out_names:
                fn, t = compile_expr(Variable(attribute_name=attr.name), resolver)
                assignments.append((attr.name, fn, t))
        if not assignments:
            raise CompileError(
                f"update {table.definition.id}: no output attribute matches a "
                f"table attribute and no `set` clause given"
            )
    return assignments


class UpdateTableCallback(_ConditionedTableCallback):
    def __init__(self, table, query_name, output_attrs, on_condition, update_set,
                 dictionary):
        super().__init__(table, query_name, output_attrs, on_condition, dictionary)
        self.assignments = _compile_assignments(table, self.out_def, update_set,
                                                self.resolver)

    def __call__(self, events: List[Event]):
        batch = self._batch(events)
        if batch is not None:
            self.table.update(self.cond, self.assignments, batch)


class UpdateOrInsertTableCallback(UpdateTableCallback):
    def __init__(self, table, query_name, output_attrs, on_condition, update_set,
                 dictionary):
        super().__init__(table, query_name, output_attrs, on_condition, update_set,
                         dictionary)
        # unmatched events insert positionally, like `insert into`
        if len(output_attrs) == len(table.definition.attributes):
            self.insert_mapping = [
                (tattr.name, oname)
                for tattr, (oname, _t) in zip(table.definition.attributes,
                                              output_attrs)
            ]
        else:
            # the reference also accepts a PARTIAL output set when every
            # output attribute names a table column (UpdateOrInsert-
            # TableTestCase.java updateOrInsertTableTest5: `comp as symbol,
            # vol as volume` against a 3-attr table) — unmatched events
            # insert BY NAME with the absent columns null
            tnames = {a.name for a in table.definition.attributes}
            missing = [o for o, _t in output_attrs if o not in tnames]
            if missing:
                raise CompileError(
                    f"update or insert into '{table.definition.id}': query "
                    f"outputs {len(output_attrs)} attributes, table has "
                    f"{len(table.definition.attributes)}, and "
                    f"{missing} match no table attribute"
                )
            onames = {o for o, _t in output_attrs}
            self.insert_mapping = [
                (tattr.name, tattr.name if tattr.name in onames else None)
                for tattr in table.definition.attributes
            ]

    def __call__(self, events: List[Event]):
        batch = self._batch(events)
        if batch is not None:
            self.table.update_or_insert(self.cond, self.assignments, batch,
                                        insert_mapping=self.insert_mapping)


def create_table_callback(out, table, query_name, output_attrs, dictionary):
    """Dispatch an output action targeting a table (reference
    ``OutputParser.constructOutputCallback``)."""
    if isinstance(out, InsertIntoStream):
        return InsertIntoTableCallback(table, output_attrs, dictionary)
    if isinstance(out, DeleteStream):
        return DeleteTableCallback(table, query_name, output_attrs, out.on_delete,
                                   dictionary)
    if isinstance(out, UpdateStream):
        return UpdateTableCallback(table, query_name, output_attrs, out.on_update,
                                   out.update_set, dictionary)
    if isinstance(out, UpdateOrInsertStream):
        return UpdateOrInsertTableCallback(table, query_name, output_attrs,
                                           out.on_update, out.update_set, dictionary)
    raise CompileError(f"unsupported table output action {type(out).__name__}")
