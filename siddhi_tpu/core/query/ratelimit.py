"""Output rate limiters (host side).

Mirror of reference ``query/output/ratelimit/**`` (19 classes): pass-through,
first/last/all per N events, first/last/all per time period, and snapshot
emitters. Rate limiting operates on decoded output chunks between the
selector and the callbacks (``OutputRateLimiter.sendToCallBacks:64-108``).

Time-based limiters are driven by the app scheduler (wall clock in live
mode, event time in playback) — they register a periodic trigger.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from siddhi_tpu.core.event import Event
from siddhi_tpu.query_api.execution import (
    EventOutputRate,
    OutputRate,
    SnapshotOutputRate,
    TimeOutputRate,
)


class OutputRateLimiter:
    def __init__(self, send: Callable[[List[Event]], None]):
        self._send = send

    def process(self, events: List[Event]):
        raise NotImplementedError

    def start(self, scheduler=None):
        pass

    def stop(self):
        pass

    def reset(self):
        """Discard buffered/counted state (snapshot restore: pending
        outputs of the rolled-back timeline must not flush)."""


class PassThroughRateLimiter(OutputRateLimiter):
    """``PassThroughOutputRateLimiter`` — no limiting."""

    def process(self, events: List[Event]):
        if events:
            self._send(events)


class EventRateLimiter(OutputRateLimiter):
    """all/first/last every N events (reference
    ``ratelimit/event/{All,First,Last}PerEventOutputRateLimiter``)."""

    def __init__(self, send, value: int, kind: str):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self._counter = 0
        self._pending: List[Event] = []

    def reset(self):
        self._counter = 0
        self._pending = []

    def process(self, events: List[Event]):
        out: List[Event] = []
        for ev in events:
            self._counter += 1
            if self.kind == "first":
                if self._counter == 1:
                    out.append(ev)
            elif self.kind == "last":
                self._pending = [ev]
            else:
                self._pending.append(ev)
            if self._counter == self.value:
                self._counter = 0
                if self.kind in ("all", "last"):
                    out.extend(self._pending)
                    self._pending = []
        if out:
            self._send(out)


class TimeRateLimiter(OutputRateLimiter):
    """all/first/last every T ms, flushed by a scheduler tick (reference
    ``ratelimit/time/*PerTimeOutputRateLimiter``)."""

    def __init__(self, send, value: int, kind: str):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self._pending: List[Event] = []
        self._sent_first = False
        self._scheduler = None
        self._job = None

    def reset(self):
        self._pending = []
        self._sent_first = False

    def start(self, scheduler=None):
        self._scheduler = scheduler
        if scheduler is not None:
            self._job = scheduler.schedule_periodic(self.value, self._tick)

    def stop(self):
        if self._scheduler is not None and self._job is not None:
            self._scheduler.cancel(self._job)

    def _tick(self, _ts: int):
        if self.kind == "first":
            self._sent_first = False
            return
        if self._pending:
            out, self._pending = self._pending, []
            self._send(out)

    def process(self, events: List[Event]):
        if self.kind == "first":
            if not self._sent_first and events:
                self._sent_first = True
                self._send(events[:1])
        elif self.kind == "last":
            if events:
                self._pending = [events[-1]]
        else:
            self._pending.extend(events)


class SnapshotRateLimiter(OutputRateLimiter):
    """``output snapshot every T``: re-emit the last-known OUTPUT STATE each
    period. Variant dispatch mirrors
    ``WrappedSnapshotOutputRateLimiter.java:75-116`` via (windowed,
    group_by, aggregated-output positions):

    - not windowed:            last event / last per group
      (``PerSnapshotOutputRateLimiter``, ``GroupByPerSnapshot...``)
    - windowed, no agg:        the window's current contents — CURRENT adds,
      EXPIRED removes the first data-equal entry
      (``WindowedPerSnapshotOutputRateLimiter``)
    - windowed, ALL agg, !gb:  last aggregate row, cleared by its expiry
      (``AllAggregationPerSnapshotOutputRateLimiter``)
    - windowed, some agg:      window contents with aggregate positions
      patched to the latest aggregate values [per group]
      (``Aggregation[GroupBy]WindowedPerSnapshotOutputRateLimiter``)
    - windowed, ALL agg, gb:   per-group last row with a live count; a group
      whose count hits zero stops emitting
      (``AllAggregationGroupByWindowedPerSnapshot...`` LastEventHolder)
    """

    def __init__(self, send, value: int, *, windowed: bool, key_fn=None,
                 agg_positions=(), out_size: int = 0, empty_send=None):
        super().__init__(send)
        self.value = value
        self.windowed = windowed
        self._empty_send = empty_send
        self.key_fn = key_fn
        self.agg_positions = tuple(agg_positions)
        self.all_agg = bool(self.agg_positions) and len(self.agg_positions) == out_size
        self._scheduler = None
        self._job = None
        # per-variant state
        self._last: Optional[Event] = None            # per-snapshot / all-agg
        self._group_last: dict = {}                   # group -> Event
        self._group_count: dict = {}                  # group -> live count (all-agg gb)
        self._events: list = []                       # windowed contents
        self._agg_values: dict = {}                   # position -> latest value
        self._group_agg: dict = {}                    # group -> {position: value}

    def reset(self):
        self._last = None
        self._group_last.clear()
        self._group_count.clear()
        self._events.clear()
        self._agg_values.clear()
        self._group_agg.clear()

    def start(self, scheduler=None):
        self._scheduler = scheduler
        if scheduler is not None:
            self._job = scheduler.schedule_periodic(self.value, self._tick)

    def stop(self):
        if self._scheduler is not None and self._job is not None:
            self._scheduler.cancel(self._job)

    @staticmethod
    def _copy(ev: Event) -> Event:
        return Event(timestamp=ev.timestamp, data=list(ev.data),
                     is_expired=ev.is_expired, pk=ev.pk)

    def _tick(self, _ts: int):
        out: List[Event] = []
        if not self.windowed:
            if self.key_fn is not None:
                out = [self._copy(e) for e in self._group_last.values()]
            elif self._last is not None:
                out = [self._copy(self._last)]
        elif self.all_agg and self.key_fn is None:
            if self._last is not None:
                out = [self._copy(self._last)]
        elif self.all_agg:
            # LastEventHolder.checkAndClearLastInEvent: drop zero-count groups
            for k in [k for k, c in self._group_count.items() if c <= 0]:
                self._group_last.pop(k, None)
                self._group_count.pop(k, None)
            out = [self._copy(e) for e in self._group_last.values()]
        elif self.agg_positions:
            seen_groups = set()
            for ev in self._events:
                if self.key_fn is not None:
                    # ONE row per group, first occurrence wins
                    # (AggregationGroupByWindowed...constructOutputChunk's
                    # outputGroupingKeys dedup)
                    k = self.key_fn(ev)
                    if k in seen_groups:
                        continue
                    seen_groups.add(k)
                    vals = self._group_agg.get(k, {})
                else:
                    vals = self._agg_values
                c = self._copy(ev)
                for p in self.agg_positions:
                    c.data[p] = vals.get(p)
                out.append(c)
        else:
            out = [self._copy(e) for e in self._events]
        if out:
            self._send(out)
        elif self._empty_send is not None:
            self._empty_send()

    def _remove_matching(self, ev: Event) -> bool:
        # aggregate positions are EXCLUDED from the expiry match (their
        # values advance between insert and expiry) — the snapshot
        # comparators skip them (AggregationWindowedPerSnapshot...java:58-80)
        skip = set(self.agg_positions)
        key = [v for i, v in enumerate(ev.data) if i not in skip]
        for i, held in enumerate(self._events):
            if [v for j, v in enumerate(held.data) if j not in skip] == key:
                del self._events[i]
                return True
        return False

    def process(self, events: List[Event]):
        for ev in events:
            if not self.windowed:
                if not ev.is_expired:
                    if self.key_fn is not None:
                        self._group_last[self.key_fn(ev)] = ev
                    else:
                        self._last = ev
            elif self.all_agg and self.key_fn is None:
                # expireds CLEAR the held aggregate (AllAggregationPer
                # SnapshotOutputRateLimiter.java process else-branch)
                self._last = ev if not ev.is_expired else None
            elif self.all_agg:
                k = self.key_fn(ev)
                self._group_last[k] = ev
                self._group_count[k] = (self._group_count.get(k, 0)
                                        + (1 if not ev.is_expired else -1))
            elif self.agg_positions:
                vals = (self._group_agg.setdefault(self.key_fn(ev), {})
                        if self.key_fn is not None else self._agg_values)
                if not ev.is_expired:
                    self._events.append(ev)
                    for p in self.agg_positions:
                        vals[p] = ev.data[p]
                elif self._remove_matching(ev):
                    # agg values advance only when the expiry matched a held
                    # row (AggregationWindowedPerSnapshot...java:96-104)
                    for p in self.agg_positions:
                        vals[p] = ev.data[p]
            else:
                if not ev.is_expired:
                    self._events.append(ev)
                else:
                    self._remove_matching(ev)


class GroupEventRateLimiter(OutputRateLimiter):
    """first/last every N events PER GROUP (reference
    ``ratelimit/event/{First,Last}GroupByPerEventOutputRateLimiter`` —
    chosen automatically when the query has a group-by, like
    ``OutputParser.java`` does)."""

    def __init__(self, send, value: int, kind: str, key_fn):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self.key_fn = key_fn
        self._counter = 0
        self._group_counts: dict = {}
        self._last: dict = {}

    def reset(self):
        self._counter = 0
        self._group_counts.clear()
        self._last.clear()

    def process(self, events: List[Event]):
        out: List[Event] = []
        for ev in events:
            k = self.key_fn(ev)
            if self.kind == "first":
                # per-group counter: emit the group's 1st event, swallow its
                # next value-1, then re-arm (FirstGroupByPerEventOutput
                # RateLimiter.java:58-68 — entry removed at count value-1)
                count = self._group_counts.get(k)
                if count is None:
                    self._group_counts[k] = 1
                    out.append(ev)
                elif count == self.value - 1:
                    del self._group_counts[k]
                else:
                    self._group_counts[k] = count + 1
            else:  # last: GLOBAL counter over last-per-group insertion-order
                # map (LastGroupByPerEventOutputRateLimiter.java:63-72)
                self._counter += 1
                self._last[k] = ev
                if self._counter == self.value:
                    self._counter = 0
                    out.extend(self._last.values())
                    self._last.clear()
        if out:
            self._send(out)


class GroupTimeRateLimiter(OutputRateLimiter):
    """first/last every T ms per group (reference
    ``ratelimit/time/{First,Last}GroupByPerTimeOutputRateLimiter``)."""

    def __init__(self, send, value: int, kind: str, key_fn):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self.key_fn = key_fn
        self._first_seen: set = set()
        self._last: dict = {}
        self._scheduler = None
        self._job = None

    def reset(self):
        self._first_seen.clear()
        self._last.clear()

    def start(self, scheduler=None):
        self._scheduler = scheduler
        if scheduler is not None:
            self._job = scheduler.schedule_periodic(self.value, self._tick)

    def stop(self):
        if self._scheduler is not None and self._job is not None:
            self._scheduler.cancel(self._job)

    def _tick(self, _ts: int):
        if self.kind == "first":
            self._first_seen.clear()
            return
        if self._last:
            out = list(self._last.values())
            self._last.clear()
            self._send(out)

    def process(self, events: List[Event]):
        out: List[Event] = []
        for ev in events:
            k = self.key_fn(ev)
            if self.kind == "first":
                if k not in self._first_seen:
                    self._first_seen.add(k)
                    out.append(ev)
            else:
                self._last[k] = ev
        if out:
            self._send(out)


class PartitionedRateLimiter(OutputRateLimiter):
    """One limiter instance PER PARTITION KEY: the reference clones the
    whole query runtime — including its OutputRateLimiter — per key
    (PartitionInstanceRuntime), so counters/windows never mix across
    keys. Events route by ``Event.pk``."""

    def __init__(self, send, factory):
        super().__init__(send)
        self._factory = factory
        self._per_key: dict = {}
        self._scheduler = None

    def _limiter(self, pk):
        lim = self._per_key.get(pk)
        if lim is None:
            lim = self._per_key[pk] = self._factory()
            lim.start(self._scheduler)
        return lim

    def process(self, events: List[Event]):
        by: dict = {}
        for ev in events:
            by.setdefault(ev.pk, []).append(ev)
        for pk, evs in by.items():
            self._limiter(pk).process(evs)

    def start(self, scheduler=None):
        self._scheduler = scheduler
        for lim in self._per_key.values():
            lim.start(scheduler)

    def stop(self):
        for lim in self._per_key.values():
            lim.stop()

    def reset(self):
        for lim in self._per_key.values():
            lim.stop()
        self._per_key.clear()

    def reset_keys(self, ids):
        """Drop retired partition keys' limiter instances (@purge) so a
        recycled pk starts fresh and periodic jobs don't leak."""
        for pk in ids:
            lim = self._per_key.pop(int(pk), None)
            if lim is not None:
                lim.stop()


def rate_uses_group_key(rate: Optional[OutputRate], windowed: bool,
                        agg_positions) -> bool:
    """Does the limiter variant for ``rate`` key on the group? first/last
    event/time limiters do; snapshot does unless it is the windowed no-agg
    variant (the wrapper picks WindowedPerSnapshot there, which unwraps
    GroupedComplexEvents). The single source of truth for callers deciding
    whether to attach a group key to output events."""
    if isinstance(rate, (EventOutputRate, TimeOutputRate)):
        return rate.type in ("first", "last")
    if isinstance(rate, SnapshotOutputRate):
        return not windowed or bool(agg_positions)
    return False


def create_rate_limiter(rate: Optional[OutputRate], send,
                        group_key_fn=None,
                        partitioned: bool = False,
                        windowed: bool = False,
                        agg_positions=(),
                        out_size: int = 0,
                        empty_send=None) -> OutputRateLimiter:
    """``group_key_fn`` (group tuple from an output Event) switches
    first/last limiters to their per-group variants, exactly as the
    reference OutputParser picks GroupBy classes for grouped queries.
    ``partitioned`` wraps the limiter per partition key (events carry
    ``pk``), matching the reference's per-key query instances.
    ``windowed``/``agg_positions``/``out_size`` select the snapshot
    variant (WrappedSnapshotOutputRateLimiter.java:75-116)."""
    if rate is None:
        return PassThroughRateLimiter(send)

    def build():
        if isinstance(rate, EventOutputRate):
            if group_key_fn is not None and rate.type in ("first", "last"):
                return GroupEventRateLimiter(send, rate.value, rate.type,
                                             group_key_fn)
            return EventRateLimiter(send, rate.value, rate.type)
        if isinstance(rate, TimeOutputRate):
            if group_key_fn is not None and rate.type in ("first", "last"):
                return GroupTimeRateLimiter(send, rate.value, rate.type,
                                            group_key_fn)
            return TimeRateLimiter(send, rate.value, rate.type)
        if isinstance(rate, SnapshotOutputRate):
            key_fn = (group_key_fn
                      if rate_uses_group_key(rate, windowed, agg_positions)
                      else None)
            return SnapshotRateLimiter(send, rate.value, windowed=windowed,
                                       key_fn=key_fn,
                                       agg_positions=agg_positions,
                                       out_size=out_size,
                                       empty_send=empty_send)
        raise NotImplementedError(f"rate {rate!r}")

    if partitioned:
        return PartitionedRateLimiter(send, build)
    return build()
