"""Output rate limiters (host side).

Mirror of reference ``query/output/ratelimit/**`` (19 classes): pass-through,
first/last/all per N events, first/last/all per time period, and snapshot
emitters. Rate limiting operates on decoded output chunks between the
selector and the callbacks (``OutputRateLimiter.sendToCallBacks:64-108``).

Time-based limiters are driven by the app scheduler (wall clock in live
mode, event time in playback) — they register a periodic trigger.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from siddhi_tpu.core.event import Event
from siddhi_tpu.query_api.execution import (
    EventOutputRate,
    OutputRate,
    SnapshotOutputRate,
    TimeOutputRate,
)


class OutputRateLimiter:
    def __init__(self, send: Callable[[List[Event]], None]):
        self._send = send

    def process(self, events: List[Event]):
        raise NotImplementedError

    def start(self, scheduler=None):
        pass

    def stop(self):
        pass

    def reset(self):
        """Discard buffered/counted state (snapshot restore: pending
        outputs of the rolled-back timeline must not flush)."""


class PassThroughRateLimiter(OutputRateLimiter):
    """``PassThroughOutputRateLimiter`` — no limiting."""

    def process(self, events: List[Event]):
        if events:
            self._send(events)


class EventRateLimiter(OutputRateLimiter):
    """all/first/last every N events (reference
    ``ratelimit/event/{All,First,Last}PerEventOutputRateLimiter``)."""

    def __init__(self, send, value: int, kind: str):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self._counter = 0
        self._pending: List[Event] = []

    def reset(self):
        self._counter = 0
        self._pending = []

    def process(self, events: List[Event]):
        out: List[Event] = []
        for ev in events:
            self._counter += 1
            if self.kind == "first":
                if self._counter == 1:
                    out.append(ev)
            elif self.kind == "last":
                self._pending = [ev]
            else:
                self._pending.append(ev)
            if self._counter == self.value:
                self._counter = 0
                if self.kind in ("all", "last"):
                    out.extend(self._pending)
                    self._pending = []
        if out:
            self._send(out)


class TimeRateLimiter(OutputRateLimiter):
    """all/first/last every T ms, flushed by a scheduler tick (reference
    ``ratelimit/time/*PerTimeOutputRateLimiter``)."""

    def __init__(self, send, value: int, kind: str):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self._pending: List[Event] = []
        self._sent_first = False
        self._scheduler = None
        self._job = None

    def reset(self):
        self._pending = []
        self._sent_first = False

    def start(self, scheduler=None):
        self._scheduler = scheduler
        if scheduler is not None:
            self._job = scheduler.schedule_periodic(self.value, self._tick)

    def stop(self):
        if self._scheduler is not None and self._job is not None:
            self._scheduler.cancel(self._job)

    def _tick(self, _ts: int):
        if self.kind == "first":
            self._sent_first = False
            return
        if self._pending:
            out, self._pending = self._pending, []
            self._send(out)

    def process(self, events: List[Event]):
        if self.kind == "first":
            if not self._sent_first and events:
                self._sent_first = True
                self._send(events[:1])
        elif self.kind == "last":
            if events:
                self._pending = [events[-1]]
        else:
            self._pending.extend(events)


class GroupEventRateLimiter(OutputRateLimiter):
    """first/last every N events PER GROUP (reference
    ``ratelimit/event/{First,Last}GroupByPerEventOutputRateLimiter`` —
    chosen automatically when the query has a group-by, like
    ``OutputParser.java`` does)."""

    def __init__(self, send, value: int, kind: str, key_fn):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self.key_fn = key_fn
        self._counter = 0
        self._group_counts: dict = {}
        self._last: dict = {}

    def reset(self):
        self._counter = 0
        self._group_counts.clear()
        self._last.clear()

    def process(self, events: List[Event]):
        out: List[Event] = []
        for ev in events:
            k = self.key_fn(ev)
            if self.kind == "first":
                # per-group counter: emit the group's 1st event, swallow its
                # next value-1, then re-arm (FirstGroupByPerEventOutput
                # RateLimiter.java:58-68 — entry removed at count value-1)
                count = self._group_counts.get(k)
                if count is None:
                    self._group_counts[k] = 1
                    out.append(ev)
                elif count == self.value - 1:
                    del self._group_counts[k]
                else:
                    self._group_counts[k] = count + 1
            else:  # last: GLOBAL counter over last-per-group insertion-order
                # map (LastGroupByPerEventOutputRateLimiter.java:63-72)
                self._counter += 1
                self._last[k] = ev
                if self._counter == self.value:
                    self._counter = 0
                    out.extend(self._last.values())
                    self._last.clear()
        if out:
            self._send(out)


class GroupTimeRateLimiter(OutputRateLimiter):
    """first/last every T ms per group (reference
    ``ratelimit/time/{First,Last}GroupByPerTimeOutputRateLimiter``)."""

    def __init__(self, send, value: int, kind: str, key_fn):
        super().__init__(send)
        self.value = value
        self.kind = kind
        self.key_fn = key_fn
        self._first_seen: set = set()
        self._last: dict = {}
        self._scheduler = None
        self._job = None

    def reset(self):
        self._first_seen.clear()
        self._last.clear()

    def start(self, scheduler=None):
        self._scheduler = scheduler
        if scheduler is not None:
            self._job = scheduler.schedule_periodic(self.value, self._tick)

    def stop(self):
        if self._scheduler is not None and self._job is not None:
            self._scheduler.cancel(self._job)

    def _tick(self, _ts: int):
        if self.kind == "first":
            self._first_seen.clear()
            return
        if self._last:
            out = list(self._last.values())
            self._last.clear()
            self._send(out)

    def process(self, events: List[Event]):
        out: List[Event] = []
        for ev in events:
            k = self.key_fn(ev)
            if self.kind == "first":
                if k not in self._first_seen:
                    self._first_seen.add(k)
                    out.append(ev)
            else:
                self._last[k] = ev
        if out:
            self._send(out)


class PartitionedRateLimiter(OutputRateLimiter):
    """One limiter instance PER PARTITION KEY: the reference clones the
    whole query runtime — including its OutputRateLimiter — per key
    (PartitionInstanceRuntime), so counters/windows never mix across
    keys. Events route by ``Event.pk``."""

    def __init__(self, send, factory):
        super().__init__(send)
        self._factory = factory
        self._per_key: dict = {}
        self._scheduler = None

    def _limiter(self, pk):
        lim = self._per_key.get(pk)
        if lim is None:
            lim = self._per_key[pk] = self._factory()
            lim.start(self._scheduler)
        return lim

    def process(self, events: List[Event]):
        by: dict = {}
        for ev in events:
            by.setdefault(ev.pk, []).append(ev)
        for pk, evs in by.items():
            self._limiter(pk).process(evs)

    def start(self, scheduler=None):
        self._scheduler = scheduler
        for lim in self._per_key.values():
            lim.start(scheduler)

    def stop(self):
        for lim in self._per_key.values():
            lim.stop()

    def reset(self):
        for lim in self._per_key.values():
            lim.stop()
        self._per_key.clear()

    def reset_keys(self, ids):
        """Drop retired partition keys' limiter instances (@purge) so a
        recycled pk starts fresh and periodic jobs don't leak."""
        for pk in ids:
            lim = self._per_key.pop(int(pk), None)
            if lim is not None:
                lim.stop()


def create_rate_limiter(rate: Optional[OutputRate], send,
                        group_key_fn=None,
                        partitioned: bool = False) -> OutputRateLimiter:
    """``group_key_fn`` (group tuple from an output Event) switches
    first/last limiters to their per-group variants, exactly as the
    reference OutputParser picks GroupBy classes for grouped queries.
    ``partitioned`` wraps the limiter per partition key (events carry
    ``pk``), matching the reference's per-key query instances."""
    if rate is None:
        return PassThroughRateLimiter(send)

    def build():
        if isinstance(rate, EventOutputRate):
            if group_key_fn is not None and rate.type in ("first", "last"):
                return GroupEventRateLimiter(send, rate.value, rate.type,
                                             group_key_fn)
            return EventRateLimiter(send, rate.value, rate.type)
        if isinstance(rate, TimeOutputRate):
            if group_key_fn is not None and rate.type in ("first", "last"):
                return GroupTimeRateLimiter(send, rate.value, rate.type,
                                            group_key_fn)
            return TimeRateLimiter(send, rate.value, rate.type)
        if isinstance(rate, SnapshotOutputRate):
            # snapshot limiter re-emits the full last-known output every T
            return TimeRateLimiter(send, rate.value, "last")
        raise NotImplementedError(f"rate {rate!r}")

    if partitioned:
        return PartitionedRateLimiter(send, build)
    return build()
