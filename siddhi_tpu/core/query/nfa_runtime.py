"""Host driver for pattern/sequence (NFA) queries.

The counterpart of the reference's pattern receivers + state runtime
(``query/input/stream/state/receiver/*.java``, ``StateStreamRuntime.java``):
one runtime subscribes to every junction the pattern consumes (via
``StreamProxy`` receivers); each arriving chunk runs that stream's jitted
NFA transition (``ops/nfa.py``) fused with the query's selector stage.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.event import Event, HostBatch
from siddhi_tpu.core.plan.selector_plan import GK_KEY
from siddhi_tpu.core.query.runtime import QueryRuntime
from siddhi_tpu.core.stream.junction import Receiver
from siddhi_tpu.ops.expressions import PK_KEY, TYPE_KEY, VALID_KEY
from siddhi_tpu.ops.nfa import NFAStage
from siddhi_tpu.query_api.definitions import StreamDefinition


class StreamProxy(Receiver):
    """Per-input-stream junction subscriber for one NFA query (the role of
    PatternSingle/SequenceSingleProcessStreamReceiver)."""

    def __init__(self, runtime: "NFAQueryRuntime", stream_id: str,
                 definition: StreamDefinition):
        self.runtime = runtime
        self.stream_id = stream_id
        self.definition = definition

    def receive(self, events: List[Event]):
        batch = HostBatch.from_events(events, self.definition, self.runtime.dictionary)
        self.runtime.process_stream_batch(self.stream_id, batch)


class NFAQueryRuntime(QueryRuntime):
    def __init__(
        self,
        name: str,
        app_context,
        stage: NFAStage,
        input_defs: Dict[str, StreamDefinition],
        stream_keyers: Dict[str, object],
        selector_plan,
        dictionary,
        partition_ctx=None,
    ):
        super().__init__(
            name=name,
            app_context=app_context,
            input_definition=None,
            filters=[],
            window_stage=None,
            selector_plan=selector_plan,
            keyer=None,
            dictionary=dictionary,
            partition_ctx=partition_ctx,
        )
        self.stage = stage
        self.input_defs = input_defs
        self.stream_keyers = stream_keyers  # stream id -> partition keyer|None
        self._steps: Dict[str, object] = {}

    # -------------------------------------------------------------- wiring

    def make_proxies(self) -> Dict[str, StreamProxy]:
        return {
            sid: StreamProxy(self, sid, self.input_defs[sid])
            for sid in self.stage.plan.stream_ids
        }

    # --------------------------------------------------------------- state

    def _init_state(self) -> dict:
        return {
            "sel": self.selector_plan.init_state(),
            "nfa": self.stage.init_state(self._win_keys),
        }

    def _ensure_capacity(self):
        before = (self.selector_plan.num_keys, self._win_keys)
        super()._ensure_capacity()
        if (self.selector_plan.num_keys, self._win_keys) != before:
            self._steps.clear()

    def build_stream_step_fn(self, stream_id: str):
        """Pure (state, cols, now) -> (state', out) for one input stream —
        the NFA transition fused with the selector stage."""
        stage = self.stage
        sel = self.selector_plan

        def step(state, cols, current_time):
            ctx = {"xp": jnp, "current_time": current_time}
            new_nfa, out_cols = stage.apply_stream(stream_id, state["nfa"], cols, ctx)
            out_cols = dict(out_cols)
            overflow = out_cols.pop("__overflow__", None)
            new_sel, out = sel.apply(state["sel"], out_cols, ctx)
            if overflow is not None:
                out["__overflow__"] = overflow
            return {"nfa": new_nfa, "sel": new_sel}, out

        return step

    def build_step_fn(self):
        # single-step export (driver compile checks): first stream's step
        return self.build_stream_step_fn(self.stage.plan.stream_ids[0])

    # ----------------------------------------------------------- processing

    def process_stream_batch(self, stream_id: str, batch: HostBatch):
        with self._lock:
            cols = batch.cols
            partitioned = self.partition_ctx is not None
            if partitioned:
                keyer = self.stream_keyers.get(stream_id)
                if keyer is not None:
                    cols, pk = keyer.apply(cols)
                    cols[PK_KEY] = np.asarray(pk, np.int32)
                else:
                    cols[PK_KEY] = np.zeros(batch.capacity, np.int32)
                cols[GK_KEY] = cols[PK_KEY]
            else:
                cols[GK_KEY] = np.zeros(cols[VALID_KEY].shape[0], np.int32)
            if partitioned:
                self._ensure_capacity()
            if self._state is None:
                self._state = self._init_state()
            step = self._steps.get(stream_id)
            if step is None:
                step = jax.jit(self.build_stream_step_fn(stream_id), donate_argnums=0)
                self._steps[stream_id] = step
            self._finish_device_batch(
                step, cols,
                "pattern match-slot capacity exceeded — raise app_context.nfa_slots")

    def receive(self, events: List[Event]):  # pragma: no cover — proxies only
        raise RuntimeError("NFA queries receive through per-stream proxies")
