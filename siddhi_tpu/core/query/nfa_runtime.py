"""Host driver for pattern/sequence (NFA) queries.

The counterpart of the reference's pattern receivers + state runtime
(``query/input/stream/state/receiver/*.java``, ``StateStreamRuntime.java``):
one runtime subscribes to every junction the pattern consumes (via
``StreamProxy`` receivers); each arriving chunk runs that stream's jitted
NFA transition (``ops/nfa.py``) fused with the query's selector stage.

Absent (`not ... for t`) deadlines additionally drive a scheduler loop:
every device step reports the earliest pending deadline (``__notify__``),
the scheduler wakes the runtime at that time, and ``process_timer`` runs a
jitted all-keys deadline sweep (``NFAStage.apply_timer``) — the role of the
reference's ``Scheduler`` + ``AbsentStreamPreStateProcessor`` timer chain.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.event import Event, HostBatch, LazyColumns, pack_pool_of
from siddhi_tpu.core.plan.selector_plan import GK_KEY
from siddhi_tpu.core.query.runtime import QueryRuntime, pack_meta
from siddhi_tpu.core.stream.junction import FatalQueryError, Receiver
from siddhi_tpu.ops.expressions import PK_KEY, TS_KEY, TYPE_KEY, VALID_KEY
from siddhi_tpu.ops.nfa import NFAStage
from siddhi_tpu.query_api.definitions import StreamDefinition


def _nfa_meta(out: dict, new_nfa: dict, ins_on: bool) -> dict:
    """Append the ``nfa_runs`` instrument lane (live partial-match
    slots) behind the packed meta prefix — computed from state the step
    already holds (``observability/instruments.py``); inert when the
    ``profile_device_instruments`` knob is off."""
    if ins_on:
        out["__meta__"] = jnp.concatenate(
            [out["__meta__"],
             jnp.sum(new_nfa["active"], dtype=jnp.int64).reshape(1)])
    return out


class StreamProxy(Receiver):
    """Per-input-stream junction subscriber for one NFA query (the role of
    PatternSingle/SequenceSingleProcessStreamReceiver)."""

    def __init__(self, runtime: "NFAQueryRuntime", stream_id: str,
                 definition: StreamDefinition):
        self.runtime = runtime
        self.stream_id = stream_id
        self.definition = definition

    def receive(self, events: List[Event]):
        batch = HostBatch.from_events(
            events, self.definition, self.runtime.dictionary,
            pool=pack_pool_of(self.runtime.app_context))
        self.runtime.process_stream_batch(self.stream_id, batch)

    def receive_batch(self, batch: HostBatch, junction=None):
        self.runtime.process_stream_batch(self.stream_id, batch,
                                          junction=junction)


class NFAQueryRuntime(QueryRuntime):
    def is_stateful(self) -> bool:
        # window/NFA state is always snapshot-relevant
        return True

    def __init__(
        self,
        name: str,
        app_context,
        stage: NFAStage,
        input_defs: Dict[str, StreamDefinition],
        stream_keyers: Dict[str, object],
        selector_plan,
        dictionary,
        partition_ctx=None,
        out_keyer=None,
    ):
        super().__init__(
            name=name,
            app_context=app_context,
            input_definition=None,
            filters=[],
            window_stage=None,
            selector_plan=selector_plan,
            keyer=out_keyer,          # group-by over capture columns
            dictionary=dictionary,
            partition_ctx=partition_ctx,
        )
        self.stage = stage
        self.input_defs = input_defs
        self.stream_keyers = stream_keyers  # stream id -> partition keyer|None
        self._steps: Dict[object, object] = {}
        self._timer_step = None
        self._sel_step = None
        # host mirror of the PER-KEY event-time high-water marks (fast
        # two-step kernel dispatch — see _host_hard_batch; per-key because
        # the generic engine's `_expire` only advances the clock of each
        # row's own key); persisted with snapshots so restored state
        # cannot be resurrected by replays
        self._nfa_hwm_arr = None
        self._expire_step = None
        # one stable callback object: Scheduler dedups on (id(target), ts),
        # a fresh bound method per notify_at would defeat it
        self._timer_cb = self.process_timer

    # -------------------------------------------------------------- wiring

    def make_proxies(self) -> Dict[str, StreamProxy]:
        return {
            sid: StreamProxy(self, sid, self.input_defs[sid])
            for sid in self.stage.plan.stream_ids
        }

    # --------------------------------------------------------------- state

    def _init_state(self) -> dict:
        return {
            "sel": self.selector_plan.init_state(),
            "nfa": self.stage.init_state(self._win_keys),
        }

    def _ensure_capacity(self):
        before = (self.selector_plan.num_keys, self._win_keys)
        super()._ensure_capacity()
        if (self.selector_plan.num_keys, self._win_keys) != before:
            self._steps.clear()
            self._timer_step = None

    def _step_instrument_slots(self):
        """Every NFA step (per-stream and timer sweep) appends the live
        active-run count — see ``_nfa_meta``."""
        from siddhi_tpu.observability.instruments import Slot

        if not self._instruments_on():
            return []
        return [Slot("nfa_runs")]

    def _instrument_capacity(self, name):
        if name == "nfa_runs":
            return float(self._win_keys * self.stage.plan.slots)
        return super()._instrument_capacity(name)

    def arm_initial(self):
        """Arm key 0's head wait at app start (reference: absent pre-state
        processors schedule their first deadline when the runtime starts —
        ``AbsentStreamPreStateProcessor.java`` partitionCreated/start).

        Playback timelines have no wall origin, so the wait is anchored at
        the app clock's FIRST value instead (the playback analog of
        runtime-start wall time), via a one-shot time-change listener —
        the first event on ANY stream starts the quiet window. Anchoring
        at t=0 would let a successor at any realistic epoch timestamp sail
        past the deadline without any quiet period elapsing
        (AbsentPatternTestCase q7/q27)."""
        plan = self.stage.plan
        arm_j = plan.arm_step()
        if arm_j is None or self.partition_ctx is not None:
            return
        if self.app_context.playback:
            tsg = self.app_context.timestamp_generator
            tsg.once_first_time(lambda ts: self._arm_at(int(ts)))
            return
        self._arm_at(int(self.app_context.timestamp_generator.current_time()))

    def _arm_at(self, now: int):
        plan = self.stage.plan
        arm_j = plan.arm_step()
        with self._lock:
            if self._state is None:
                self._state = self._init_state()
            nfa = {k: np.asarray(v) for k, v in self._state["nfa"].items()}
            if nfa["armed"][0]:
                return
            nfa["armed"] = nfa["armed"].copy()
            nfa["armed"][0] = True
            nfa["active"] = nfa["active"].copy()
            nfa["active"][0, 0] = True
            nfa["stepi"] = nfa["stepi"].copy()
            nfa["stepi"][0, 0] = arm_j
            nfa["sts"] = nfa["sts"].copy()
            st = plan.steps[arm_j]
            # capture-less armed head: `within` anchors at the first
            # CAPTURE (T0 sentinel min()ed down there — ops/nfa._T0_FAR)
            from siddhi_tpu.ops.nfa import _T0_FAR

            capless = all(s.capture is None for s in st.sides)
            nfa["sts"][0, 0] = int(_T0_FAR) if capless else now
            next_dl = None
            if st.kind == "absent":
                nfa["adl"] = nfa["adl"].copy()
                nfa["adl"][0, 0] = now + st.wait_ms
                next_dl = now + st.wait_ms
            else:
                for side in st.sides:
                    if side.absent and side.wait_ms is not None:
                        key = "adl" if side.bit == 1 else "adl2"
                        nfa[key] = nfa[key].copy()
                        nfa[key][0, 0] = now + side.wait_ms
                        dl = now + side.wait_ms
                        next_dl = dl if next_dl is None else min(next_dl, dl)
            # scopes starting at the armed (capture-less) wait do NOT start
            # counting here — `within` measures across captured events
            # (see NFAStage._start_capture_scopes)
            self._state["nfa"] = {k: jnp.asarray(v) for k, v in nfa.items()}
        if next_dl is not None and self.scheduler is not None:
            self.scheduler.notify_at(int(next_dl), self._timer_cb)

    # ---------------------------------------------------------- step builds

    def build_stream_step_fn(self, stream_id: str, force_generic: bool = False):
        """Pure (state, cols, now) -> (state', out) for one input stream —
        the NFA transition fused with the selector stage (unless a host
        group-by keyer has to run between them). ``force_generic`` builds
        the serial-engine variant the host dispatches to when a batch's
        timestamps are hostile to the fast kernel (see
        ``process_stream_batch``); an in-graph ``lax.cond`` would instead
        break buffer donation (XLA copies the whole [K, S] state through
        conditionals — measured 11 big copies/step)."""
        stage = self.stage
        sel = self.selector_plan
        split = self.keyer is not None
        ins_on = self._instruments_on()

        def step(state, cols, current_time):
            from siddhi_tpu.core.plan.selector_plan import STR_RANK

            ctx = {"xp": jnp, "current_time": current_time}
            cols = dict(cols)
            strrank = cols.pop(STR_RANK, None)   # selector-only side input
            if force_generic:
                new_nfa, out_cols = stage._apply_stream_generic(
                    stream_id, state["nfa"], cols, ctx)
            else:
                new_nfa, out_cols = stage.apply_stream(
                    stream_id, state["nfa"], cols, ctx)
            out_cols = dict(out_cols)
            overflow = out_cols.pop("__overflow__", None)
            notify = out_cols.pop("__notify__", None)
            if strrank is not None:
                out_cols[STR_RANK] = strrank
            if split:
                out_cols["__overflow__"] = overflow
                out_cols["__notify__"] = notify
                return ({"nfa": new_nfa, "sel": state["sel"]},
                        _nfa_meta(pack_meta(out_cols), new_nfa, ins_on))
            new_sel, out = sel.apply(state["sel"], out_cols, ctx)
            if overflow is not None:
                out["__overflow__"] = overflow
            if notify is not None:
                out["__notify__"] = notify
            return ({"nfa": new_nfa, "sel": new_sel},
                    _nfa_meta(pack_meta(out), new_nfa, ins_on))

        return step

    def build_timer_step_fn(self):
        stage = self.stage
        sel = self.selector_plan
        split = self.keyer is not None
        ins_on = self._instruments_on()

        def step(state, now):
            ctx = {"xp": jnp, "current_time": now}
            new_nfa, out_cols = stage.apply_timer(state["nfa"], now, ctx)
            out_cols = dict(out_cols)
            overflow = out_cols.pop("__overflow__", None)
            notify = out_cols.pop("__notify__", None)
            if split:
                out_cols["__overflow__"] = overflow
                out_cols["__notify__"] = notify
                return ({"nfa": new_nfa, "sel": state["sel"]},
                        _nfa_meta(pack_meta(out_cols), new_nfa, ins_on))
            new_sel, out = sel.apply(state["sel"], out_cols, ctx)
            if overflow is not None:
                out["__overflow__"] = overflow
            if notify is not None:
                out["__notify__"] = notify
            return ({"nfa": new_nfa, "sel": new_sel},
                    _nfa_meta(pack_meta(out), new_nfa, ins_on))

        return step

    def build_step_fn(self):
        # single-step export (driver compile checks): first stream's step
        return self.build_stream_step_fn(self.stage.plan.stream_ids[0])

    # ----------------------------------------------------------- processing

    def process_stream_batch(self, stream_id: str, batch: HostBatch,
                             junction=None):
        from siddhi_tpu.observability.tracing import span

        with span("query.step", query=self.name, stream=stream_id), \
                self._lock:
            from siddhi_tpu.core.stream.junction import \
                current_delivering_junction

            j = junction or current_delivering_junction()
            self._cur_junction = j
            self._cur_fault_batch = batch if (
                j is not None and j.on_error_action == "STREAM"
                and j.fault_junction is not None) else None
            cols = batch.cols
            partitioned = self.partition_ctx is not None
            if partitioned:
                keyer = self.stream_keyers.get(stream_id)
                if keyer is not None:
                    cols, pk = keyer.apply(cols)
                    cols[PK_KEY] = np.asarray(pk, np.int32)
                else:
                    cols[PK_KEY] = np.zeros(batch.capacity, np.int32)
                cols[GK_KEY] = cols[PK_KEY]
            else:
                cols[GK_KEY] = np.zeros(cols[VALID_KEY].shape[0], np.int32)
            if partitioned:
                self._ensure_capacity()
            if self._state is None:
                self._state = self._init_state()
            force_generic = self._host_hard_batch(stream_id, cols)
            jit_key = (f"query.{self.name}.nfa.{stream_id}"
                       + (".generic" if force_generic else ""))
            step = self._steps.get((stream_id, force_generic))
            if step is None:
                fn = self.build_stream_step_fn(stream_id,
                                               force_generic=force_generic)
                if self._shard_mesh is not None:
                    from siddhi_tpu.parallel.mesh import sharded_jit_for

                    step = sharded_jit_for(self, fn, n_plain_args=2)
                else:
                    step = jax.jit(fn, donate_argnums=0)
                # cache_extra: wrapper shardings are invisible in the
                # traced program — a mesh-sharded NFA step must never
                # alias an unsharded one with an equal jaxpr
                step = self.app_context.telemetry.instrument_jit(
                    step, jit_key, family="nfa_step",
                    cache_extra=str(self._shard_mesh or ""))
                self._steps[(stream_id, force_generic)] = step
            else:
                self.app_context.telemetry.record_jit(jit_key, hit=True)
            jcols = dict(cols) if isinstance(cols, LazyColumns) else cols
            if self.selector_plan.needs_str_rank:
                from siddhi_tpu.core.plan.selector_plan import STR_RANK

                jcols[STR_RANK] = self.dictionary.rank_table()
            notify = self._run_nfa_step(lambda: step(
                self._state, jcols,
                np.int64(self.app_context.timestamp_generator.current_time())))
        if notify is not None and self.scheduler is not None:
            self.scheduler.notify_at(notify, self._timer_cb)

    def _host_hard_batch(self, stream_id: str, cols) -> bool:
        """Host-side dispatch between the fast two-step kernel and the
        serial engine, decided from timestamps alone (VERDICT r05: an
        in-graph lax.cond breaks state donation — 11 full-state copies
        per step). Hard conditions, each a conservative
        over-approximation:
        - out-of-order timestamps (below the row's key's high-water mark,
          or decreasing in-batch): the fast kernel's lazy `within` expiry
          is exact only for monotone feeds;
        - head batches where one key's rows span several timestamps: a
          `within` deadline could cross inside the batch and re-order the
          free-slot list between same-key arming rows.
        When a batch is hard, the PER-KEY physical expiry clears the
        generic engine would already have made are applied first
        (`expire_to` — per key because `_expire` only advances each row's
        own key's clock)."""
        stage = self.stage
        side_kind = (stage._fast_side(stream_id)
                     if stage.fast_enabled else None)
        if side_kind is None or stage.plan.within is None:
            return side_kind is None  # ineligible plans: generic always
        raw_ts = dict.__getitem__(cols, TS_KEY) if TS_KEY in cols else None
        if not isinstance(raw_ts, np.ndarray):
            # device-resident (chained-query) batch: reading timestamps
            # here would force a device->host pull per batch (~70 ms on
            # the tunnel), and without host timestamps the high-water
            # marks cannot be maintained soundly — retire the fast path
            # for this runtime
            stage.fast_enabled = False
            self._steps.clear()
            return True
        ts = raw_ts
        valid = np.asarray(cols[VALID_KEY]) & (
            np.asarray(cols[TYPE_KEY]) == 0)
        tsv = ts[valid]
        if tsv.size == 0:
            return False
        K = self._win_keys
        arr = self._nfa_hwm_arr
        if arr is None or arr.shape[0] < K:
            grown = np.full(K, -(2 ** 62), np.int64)
            if arr is not None:
                grown[: arr.shape[0]] = arr
            self._nfa_hwm_arr = arr = grown
        pk = (np.asarray(cols[PK_KEY], np.int64) if PK_KEY in cols
              else np.zeros(ts.shape[0], np.int64))
        pkv = np.clip(pk[valid], 0, K - 1)
        hard = bool(np.any(tsv < arr[pkv])) or bool(
            np.any(np.diff(tsv) < 0))
        if not hard and side_kind == "head" and tsv.min() != tsv.max():
            order = np.argsort(pkv, kind="stable")
            same = pkv[order][1:] == pkv[order][:-1]
            hard = bool(np.any(same & (np.diff(tsv[order]) != 0)))
        if hard:
            # apply the generic engine's per-key physical expiry clears
            # before falling back (donation-safe: state replaced wholesale)
            if self._expire_step is None:
                self._expire_step = jax.jit(self.stage.expire_to,
                                            donate_argnums=0)
            self._state = dict(self._state)
            self._state["nfa"] = self._expire_step(
                self._state["nfa"], arr)
        if tsv[0] == tsv[-1] and tsv.min() == tsv.max():
            # single-timestamp batch (the steady-state shape): duplicate
            # keys all write the same value, so plain fancy assignment
            # replaces the much slower unbuffered np.maximum.at
            arr[pkv] = np.maximum(arr[pkv], tsv[0])
        else:
            np.maximum.at(arr, pkv, tsv)
        return hard

    def process_timer(self, ts: int):
        with self._lock:
            # drain in-flight pipelined batches first: the deadline sweep
            # must observe a fully-emitted timeline (and runs sync itself)
            pump = getattr(self.app_context, "completion_pump", None)
            if pump is not None and pump.has_pending:
                pump.flush_owner(self)
            if self._state is None:
                self._state = self._init_state()
            if self._timer_step is None:
                fn = self.build_timer_step_fn()
                if self._shard_mesh is not None:
                    from siddhi_tpu.parallel.mesh import sharded_jit_for

                    self._timer_step = sharded_jit_for(self, fn, n_plain_args=1)
                else:
                    self._timer_step = jax.jit(fn, donate_argnums=0)
                self._timer_step = self.app_context.telemetry.instrument_jit(
                    self._timer_step, f"query.{self.name}.nfa.timer",
                    family="nfa_timer",
                    cache_extra=str(self._shard_mesh or ""))
            notify = self._run_nfa_step(
                lambda: self._timer_step(self._state, np.int64(ts)),
                allow_pipeline=False)
        if notify is not None and self.scheduler is not None:
            self.scheduler.notify_at(notify, self._timer_cb)

    def _run_nfa_step(self, run, allow_pipeline: bool = True) -> int | None:
        """Run a jitted NFA step; when a group-by keyer splits the pipeline,
        key the NFA emissions host-side and run the selector step after.
        Overflow/notify/size arrive packed in __meta__ — one pull."""
        from siddhi_tpu.core.util.statistics import latency_t0, record_elapsed_ms

        sm = self.app_context.statistics_manager
        t0 = latency_t0(sm)
        self._state, out = run()
        out_host = LazyColumns(out)
        size_hint = None
        # raw device ref — LazyColumns.pop would PULL it (one ~70ms round
        # trip), defeating the defer batching below
        meta = (dict.__getitem__(out_host, "__meta__")
                if "__meta__" in out_host else None)
        if meta is not None:
            pump = getattr(self.app_context, "completion_pump", None)
            if (allow_pipeline and pump is not None and pump.depth > 1
                    and self.keyer is None):
                # pipelined dispatch (completion.py). Unlike defer_meta,
                # waitish (absent-deadline) plans are ELIGIBLE: the pump
                # delivers __notify__ promptly at drain (sync sends flush
                # before returning). The split-keyer path stays sync —
                # it needs the NFA outputs host-side immediately.
                from siddhi_tpu.core.query.completion import QueryCompletion

                record_elapsed_ms(sm, self.name, t0)
                pump.submit(QueryCompletion(
                    self, out_host,
                    "pattern match-slot capacity exceeded — raise "
                    "app_context.nfa_slots",
                    junction=self._cur_junction,
                    batch=getattr(self, "_cur_fault_batch", None)))
                return None
            defer = getattr(self.app_context, "defer_meta", 1)
            if defer > 1 and self.keyer is None and not any(
                    st.waitish for st in self.stage.plan.steps):
                # batch N step metas into ONE round trip (PERF.md tunnel
                # cost model); absent deadlines need prompt notifies, so
                # only wait-free plans defer (dispatch-side latency only —
                # emission is deferred)
                record_elapsed_ms(sm, self.name, t0)
                self._deferred.append((
                    out_host,
                    "pattern match-slot capacity exceeded — raise "
                    "app_context.nfa_slots"))
                if len(self._deferred) < defer:
                    return None
                return self.flush_deferred()
            dict.pop(out_host, "__meta__")
            meta = self._pull_meta(meta)
            self.decode_meta_suffix(meta)
            overflow, notify, size_hint = int(meta[0]), int(meta[1]), int(meta[2])
        else:
            ovf = out_host.pop("__overflow__", None)
            overflow = int(ovf) if ovf is not None else 0
            nt = out_host.pop("__notify__", None)
            notify = int(nt) if nt is not None else -1
        if overflow > 0:
            raise FatalQueryError(
                f"query '{self.name}': pattern match-slot capacity exceeded — "
                f"raise app_context.nfa_slots before creating the runtime"
            )
        record_elapsed_ms(sm, self.name, t0)
        if self.keyer is not None:
            out_host.pop("__overflow__", None)
            out_host.pop("__notify__", None)
            out_host = self._host_keyed_select(out_host)
            size_hint = None
        self._emit(HostBatch(out_host, size=size_hint))
        if notify >= 0:
            return notify
        return None

    def receive(self, events: List[Event]):  # pragma: no cover — proxies only
        raise RuntimeError("NFA queries receive through per-stream proxies")
