"""QueryRuntime: host driver for one compiled query.

The counterpart of the reference's receiver->processor-chain->selector
->rate-limiter->callback assembly (``QueryParser.java:90-283``,
``ProcessStreamReceiver.java:74-184``), inverted for TPU: the junction hands
the runtime a chunk of events, the runtime packs them into a padded columnar
batch, computes group-key ids host-side (dense dictionary — the analog of
``GroupByKeyGenerator.java:37`` string keys), runs the jitted device step
(filters + windows + selector fused by XLA), and decodes valid output rows
back to Events for rate limiting and callbacks.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.core.event import CURRENT, EXPIRED, TIMER as TIMER_TYPE, Event, HostBatch, LazyColumns, StringDictionary, pack_pool_of
from siddhi_tpu.observability import instruments, journey
from siddhi_tpu.observability.instruments import Slot
from siddhi_tpu.core.plan.selector_plan import GK_KEY, SelectorPlan
from siddhi_tpu.core.query.ratelimit import OutputRateLimiter
from siddhi_tpu.core.stream.junction import FatalQueryError, Receiver, StreamJunction
from siddhi_tpu.ops.expressions import PK_KEY, TS_KEY, TYPE_KEY, VALID_KEY
from siddhi_tpu.ops.windows import conform_cols
from siddhi_tpu.query_api.definitions import AttrType, StreamDefinition

_LOG = logging.getLogger("siddhi_tpu.query.runtime")


class GroupKeyer:
    """Host-side (group-by or partition) key dictionary: maps tuples of
    key-expression values to dense ids used to index ``[K, ...]`` state."""

    def __init__(self, fns: List[Tuple[Callable, AttrType]]):
        self._fns = fns
        self._map: Dict[tuple, int] = {}
        self._next = 0   # ids are NEVER reused (purged entries leave holes)
        # fast path: single string attribute -> LUT from dict id to key id
        self._single_string = len(fns) == 1 and fns[0][1] == AttrType.STRING
        self._lut = np.full(64, -1, np.int32)

    def _alloc(self, key: tuple) -> int:
        i = self._map.get(key)
        if i is None:
            i = self._map[key] = self._next
            self._next += 1
        return i

    def __len__(self):
        # dense capacity: holes from purged entries still occupy the range
        return self._next

    def __call__(self, cols: Dict[str, np.ndarray], pk: Optional[np.ndarray] = None) -> np.ndarray:
        """Group ids for a batch; when ``pk`` is given the dictionary key is
        (partition key, group-by values) — reference state addressing is
        ``[partitionFlowId][groupByFlowId]`` (PartitionStateHolder.java:43-48)."""
        ctx = {"xp": np}
        valid = cols[VALID_KEY]
        B = valid.shape[0]
        gk = np.zeros(B, np.int32)
        if pk is None and self._single_string:
            v, m = self._fns[0][0](cols, ctx)
            # LUT slots are dict ids shifted +1: slot 0 is the NULL group
            # (the reference's "null" string key, GroupByKeyGenerator
            # String.valueOf) — a null-masked key must not share the group
            # of whatever string holds the 0 placeholder, and the shift
            # also keeps NULL_ID(-1) from wrapping to lut[-1]
            ids = np.asarray(v, np.int64) + 1
            if m is not None:
                m = np.asarray(m, bool)
                if m.any():
                    ids = np.where(m, 0, ids)
            lut = self._lut
            if ids.size and ids.max() >= lut.shape[0]:
                top = int(ids.max()) + 1
                grown = np.full(max(top, 2 * lut.shape[0]), -1, np.int32)
                grown[: lut.shape[0]] = lut
                self._lut = lut = grown
            np.take(lut, ids, out=gk)
            # steady state: every dict id already has a key id — one take +
            # one reduction, no per-batch sort (np.unique costs ~5 ms at
            # 65k rows). Misses (NEW dict ids) take the unique path once.
            missed = (gk < 0) & valid
            if missed.any():
                for sid in np.unique(ids[missed]):
                    if lut[sid] < 0:
                        lut[sid] = self._alloc((int(sid) - 1,))
                np.take(lut, ids, out=gk)
            gk[~valid] = 0
            return gk
        # general path: vectorized dictionary encoding (shared helper —
        # unique the key tuples once per batch, probe the dict per NEW
        # unique only)
        from siddhi_tpu.core.event import encode_key_tuples

        arrays = []
        if pk is not None:
            arrays.append(np.asarray(pk))
        for fn, _t in self._fns:
            v, m = fn(cols, ctx)
            arrays.append(np.broadcast_to(np.asarray(v), (B,)))
            # the null mask joins the key tuple: a null key (placeholder
            # value 0) must form its own group, distinct from a real 0 /
            # the dict-id-0 string (reference nulls key as "null")
            arrays.append(np.zeros(B, bool) if m is None
                          else np.broadcast_to(np.asarray(m, bool), (B,)))
        vidx = np.nonzero(valid)[0]
        if vidx.size == 0:
            return gk
        gk[vidx] = encode_key_tuples(arrays, vidx, self._alloc)
        return gk


class QueryRuntime(Receiver):
    def __init__(
        self,
        name: str,
        app_context,
        input_definition: StreamDefinition,
        filters: List[Callable],
        window_stage,               # ops stage or None (M2)
        selector_plan: SelectorPlan,
        keyer: Optional[GroupKeyer],
        dictionary: StringDictionary,
        partition_ctx=None,
        partition_keyer=None,
        carried_pk: bool = False,
        transforms=None,
        log_stages=None,
        post_filters=None,
        post_pipeline=None,
    ):
        self.name = name
        self.app_context = app_context
        self.input_definition = input_definition
        self.filters = filters
        self.transforms = transforms or []   # ops/stream_functions stages
        self.log_stages = log_stages or []   # host #log() taps
        self.post_filters = post_filters or []  # masks on window-emitted rows
        # ordered post-window stages ("f", cond) | ("t", transform); falls
        # back to post_filters when only filters exist
        self.post_pipeline = post_pipeline if post_pipeline is not None else [
            ("f", f) for f in (post_filters or [])]
        self.host_transforms = False         # run transforms host-side (keyer needs them)
        self.window_stage = window_stage
        self.selector_plan = selector_plan
        self.keyer = keyer
        self.dictionary = dictionary
        # partition support (reference partition/PartitionRuntimeImpl.java)
        self.partition_ctx = partition_ctx
        self.partition_keyer = partition_keyer
        self.carried_pk = carried_pk      # input is an inner '#stream': rows carry pk
        self.attach_pk = False            # output goes to an inner '#stream'
        self.limiter_needs_pk = False     # partitioned rate limiter routing
        self.limiter_needs_gk = False     # grouped limiter, key not projected
        self._win_keys = 1
        if partition_ctx is not None:
            self._win_keys = max(_pow2(partition_ctx.num_keys()), 16)
        self.host_window = None   # map/comparator windows (ops/host_windows)
        self.rate_limiter: Optional[OutputRateLimiter] = None
        self.query_callbacks: List = []
        self.output_junction: Optional[StreamJunction] = None
        self.output_action: Optional[Callable] = None  # table ops etc.
        self.scheduler = None  # set by the app runtime when timers are needed
        self._state: Optional[dict] = None
        self._step = None
        self._sel_step = None  # split pipelines (host keyer between stages)
        self._shard_mesh = None  # set by parallel.mesh.shard_query_step
        self._route_layout = None  # parallel.mesh.device_route_query_step
        self._lock = make_lock("owner")  # per-query lock (QueryParser.java:159-215)
        self._deferred: List = []   # queued outputs when defer_meta > 1
        self._cur_junction = None   # delivering junction of the batch in
        #                             process (completion-latency feedback)
        self._cur_fault_batch = None  # input batch retained for drain-time
        #                               fault-stream routing (@OnError)
        self._cur_journey = None    # batch-journey context of the batch in
        #                             process (observability/journey.py)
        # device-instrument plumbing (observability/instruments.py):
        # last drained raw lanes per slot (zero-pull scrape surface),
        # host-known capacity denominators, and the lazily-registered
        # device.<q>.<slot> gauge set
        self._instr_last: Dict[str, np.ndarray] = {}
        self._instr_caps: Dict[str, float] = {}
        self._instr_gauged: set = set()
        self._instr_spec = None     # cached instrument_slots() result
        self.on_error: Optional[Callable] = None

    # ---------------------------------------------------------------- state

    @property
    def output_attrs(self) -> List[Tuple[str, AttrType]]:
        return self.selector_plan.output_attrs

    def is_stateful(self) -> bool:
        """Does this query hold state a snapshot must capture? — a window,
        an aggregator/group-by, or a non-passthrough rate limiter
        (reference ``QueryRuntimeImpl.isStateful``, StateTestCase)."""
        from siddhi_tpu.core.query.ratelimit import PassThroughRateLimiter

        if (self.window_stage is not None
                or getattr(self, "host_window", None) is not None):
            return True
        if (self.selector_plan.contains_aggregator
                or self.selector_plan.group_by):
            return True
        rl = self.rate_limiter
        return rl is not None and not isinstance(rl, PassThroughRateLimiter)

    def _init_state(self) -> dict:
        state = {"sel": self.selector_plan.init_state()}
        if self.window_stage is not None:
            state["win"] = self.window_stage.init_state(self._win_keys)
        return state

    def _needed_sel_keys(self) -> int:
        if self.keyer is not None:
            return max(len(self.keyer), 1)
        if self.partition_ctx is not None:
            return self.partition_ctx.num_keys()
        return 1

    def _ensure_capacity(self):
        """Grow dense key capacity (pow2) when a key dictionary outgrows
        it; state rows are preserved (keyed buffers are laid out so prefix
        copy keeps per-key alignment), step re-jitted on the new shapes."""
        if self._route_layout is not None:
            # device-routed runtimes hold PER-SHARD capacities: growth
            # compares the GLOBAL key population against n * localK and
            # re-lays the state out through its canonical form
            from siddhi_tpu.parallel.mesh import ensure_routed_capacity

            ensure_routed_capacity(self)
            return
        needed = self._needed_sel_keys()
        k = self.selector_plan.num_keys
        new_k = _pow2(needed, start=k) if needed > k else k
        new_w = self._win_keys
        if self.partition_ctx is not None:
            needed_w = self.partition_ctx.num_keys()
            if needed_w > self._win_keys:
                new_w = _pow2(needed_w, start=self._win_keys)
        if new_k == k and new_w == self._win_keys:
            return
        if (getattr(self.app_context, "overload", None) is not None
                and self._state is not None):
            # device-memory budget gate (resilience/overload.py): deny
            # the growth BEFORE allocating — dense state scales with the
            # grown key capacity, so project from the current footprint
            from siddhi_tpu.core.util.statistics import pytree_nbytes
            from siddhi_tpu.resilience.overload import ensure_memory_budget

            ratio = max(new_k / max(k, 1), new_w / max(self._win_keys, 1))
            ensure_memory_budget(
                self.app_context, f"query.{self.name}",
                int(pytree_nbytes(self._state) * ratio),
                what=f"query '{self.name}' key-capacity growth "
                     f"({k}->{new_k} keys)")
        self.selector_plan.num_keys = new_k
        self._win_keys = new_w
        self._sel_step = None
        old_state = self._state
        new_state = self._init_state()
        if old_state is not None:
            self._state = jax.tree_util.tree_map(_copy_prefix, new_state, old_state)
        else:
            self._state = new_state
        self._step = None  # re-jit
        if self._shard_mesh is not None:
            # re-establish key-axis sharding on the grown state
            from siddhi_tpu.parallel.mesh import shard_query_step

            shard_query_step(self, self._shard_mesh)
        if getattr(self.app_context, "overload", None) is not None:
            from siddhi_tpu.core.util.statistics import pytree_nbytes
            from siddhi_tpu.resilience.overload import charge_memory

            charge_memory(self.app_context, f"query.{self.name}",
                          pytree_nbytes(self._state))

    def reset_partition_keys(self, ids):
        """Zero the dense state rows of purged partition keys so their ids
        can be reused by new keys (@purge — PartitionRuntimeImpl purge)."""
        if self.rate_limiter is not None and hasattr(
                self.rate_limiter, "reset_keys"):
            # per-key limiter instances of retired keys must not leak
            # their counters/pending into a recycled pk
            self.rate_limiter.reset_keys(ids)
        with self._lock:
            if self._state is None:
                return
            rl = self._route_layout
            ids_np = np.asarray(ids, np.int64)
            if rl is not None:
                # routed state is shard-major: global pk id g lives at row
                # (g % n) * local + g // n of each keyed buffer
                idx = jnp.asarray(
                    ((ids_np % rl.n) * rl.local_win
                     + ids_np // rl.n).astype(np.int32))
            else:
                idx = jnp.asarray(ids_np.astype(np.int32))
            state = dict(self._state)
            if "win" in state and hasattr(self.window_stage, "reset_keys"):
                state["win"] = self.window_stage.reset_keys(state["win"], idx)
            for wk in ("lwin", "rwin"):     # partitioned join sides
                side = getattr(self, "sides", {}).get(
                    "left" if wk == "lwin" else "right") if hasattr(self, "sides") else None
                if wk in state and side is not None and hasattr(
                        side.window_stage, "reset_keys"):
                    state[wk] = side.window_stage.reset_keys(state[wk], idx)
            if "nfa" in state:
                nfa = dict(state["nfa"])
                for k in ("active", "consumed", "armed"):
                    nfa[k] = nfa[k].at[idx].set(False)
                state["nfa"] = nfa
            if self.keyer is None:
                # gk == pk: selector rows are addressed by partition id.
                # Rows reset to the aggregator INIT values (min/max keep
                # their +/-inf sentinels), gathered from a fresh state.
                # Key axis = first axis sized num_keys (the same heuristic
                # parallel/mesh.py shards by).
                K = self.selector_plan.num_keys
                init = self.selector_plan.init_state()
                sel_idx, init_idx = idx, idx
                if rl is not None:
                    # sel space is gk == pk here; init rows are identical
                    # per key, so gather them at the LOCAL id
                    K = K * rl.n
                    sel_idx = jnp.asarray(
                        ((ids_np % rl.n) * rl.localK
                         + ids_np // rl.n).astype(np.int32))
                    init_idx = jnp.asarray((ids_np // rl.n).astype(np.int32))

                def reset_key_rows(x, x0):
                    if not hasattr(x, "shape"):
                        return x
                    for ax, s in enumerate(x.shape):
                        if s == K:
                            sl = [slice(None)] * x.ndim
                            sl[ax] = sel_idx
                            sl0 = [slice(None)] * x.ndim
                            sl0[ax] = init_idx
                            return x.at[tuple(sl)].set(
                                jnp.asarray(x0)[tuple(sl0)])
                    return x

                state["sel"] = jax.tree_util.tree_map(
                    reset_key_rows, state["sel"], init)
            else:
                # composite (pk, group) keys: drop the purged pks' entries
                # so a reused id cannot alias old groups (their gk rows
                # become unreachable, not recycled)
                dead = set(int(i) for i in np.asarray(ids))
                self.keyer._map = {k: v for k, v in self.keyer._map.items()
                                   if int(k[0]) not in dead}
                self.keyer._lut = np.full(64, -1, np.int32)
                # _next is untouched: gk ids are never reused, so a fresh
                # (pk, group) key can never alias a surviving group's row
            self._state = state

    def _make_step(self):
        # first-call compile timing rides a telemetry proxy: jit-compile
        # count/wall-ms per query (and a span("jit")) with one attribute
        # check per call afterwards — re-jits on capacity growth show up
        # as fresh compile events
        if self._route_layout is not None:
            # a cleared step on a device-routed runtime (restore, growth)
            # must come back ROUTED, not as the plain unsharded jit
            from siddhi_tpu.parallel.mesh import routed_step_for

            return routed_step_for(self)
        jitted = jax.jit(self.build_step_fn(), donate_argnums=0)
        return self.app_context.telemetry.instrument_jit(
            jitted, f"query.{self.name}.step", family="query_step")

    # ------------------------------------------------- device instruments

    def _instruments_on(self) -> bool:
        """Gate of the telemetry instrument slots — the per-app typed
        knob ``siddhi_tpu.profile_device_instruments`` (default on; off
        keeps today's meta layouts bit-for-bit). Consulted at step BUILD
        and at drain, so layout and decoder always agree."""
        return instruments.app_instruments_on(self.app_context)

    def instrument_slots(self) -> List[Slot]:
        """Ordered spec of everything this runtime's meta carries BEHIND
        the standard ``[overflow, notify, count]`` prefix — the single
        declaration the step builder, the CompletionPump drain and
        graftlint R6 all read. Route-structural slots first (their lanes
        predate the registry and are knob-independent), then the inner
        step's slots (``_step_instrument_slots``). Cached per runtime —
        the drain runs per batch; the spec only changes when the layout
        does (route install / engine attach invalidate ``_instr_spec``)."""
        if self._instr_spec is not None:
            return self._instr_spec
        spec: List[Slot] = []
        rl = self._route_layout
        if rl is not None:
            spec.append(Slot("route_overflow", kind="check"))
            spec.append(Slot("shard_rows", width=rl.n))
            if self._instruments_on():
                spec.append(Slot("route_residual"))
        spec.extend(self._step_instrument_slots())
        self._instr_spec = spec
        return spec

    def _step_instrument_slots(self) -> List[Slot]:
        """Slots the INNER (per-shard) step appends — overridden by the
        join/NFA runtimes to match their own step builders exactly."""
        if not self._instruments_on():
            return []
        slots: List[Slot] = []
        if (self.window_stage is not None
                and hasattr(self.window_stage, "live_fill")):
            slots.append(Slot("win_fill", reduce="max"))
        if self.keyer is not None or self.partition_ctx is not None:
            slots.append(Slot("groups"))
        return slots

    def _instrument_values(self, slots: List[Slot], new_state, cols) -> List:
        """Device-side slot computation (runs INSIDE the jitted step,
        from state/columns the step already holds — zero extra work
        beyond a couple of reductions)."""
        vals = []
        for slot in slots:
            if slot.name == "win_fill":
                vals.append(jnp.asarray(
                    self.window_stage.live_fill(new_state["win"]),
                    jnp.int64).reshape(1))
            elif slot.name == "groups":
                K = self.selector_plan.num_keys
                valid = cols[VALID_KEY]
                gk = jnp.clip(cols[GK_KEY].astype(jnp.int64), 0, K - 1)
                idx = jnp.where(valid, gk, jnp.int64(K))
                seen = jnp.zeros(K + 1, bool).at[idx].set(True, mode="drop")
                vals.append(jnp.sum(seen[:K], dtype=jnp.int64).reshape(1))
        return vals

    def _instrument_capacity(self, name: str) -> Optional[float]:
        """Host-known denominator of one data slot (the report quotes
        saturation against it); None = not a saturation-style signal."""
        if name == "win_fill":
            return getattr(self.window_stage, "ring_capacity", None)
        if name == "groups":
            k = self.selector_plan.num_keys
            rl = self._route_layout
            return float(k * rl.n) if rl is not None else float(k)
        if name in ("shard_rows", "route_residual"):
            rl = self._route_layout
            return float(rl.n * rl.quota) if rl is not None else None
        return None

    def decode_meta_suffix(self, meta) -> None:
        """Drain-side decoder of the meta suffix, shared by the
        synchronous tail, the CompletionPump drain, the deferred flush
        and the fused fan-out per-member path: walk the spec, record
        data slots into ``device.<query>.<slot>`` telemetry (and the
        zero-pull ``_instr_last`` cache), then run the structural check
        slots (route-overflow raise, join seq verification). Data lands
        BEFORE checks so a fatal overflow still leaves the skew gauges
        pointing at the culprit."""
        spec = self.instrument_slots()
        meta = np.asarray(meta)
        if not spec or meta.shape[0] <= 3:
            return
        ins_on = self._instruments_on()
        checks = []
        i = 3
        for slot in spec:
            if i + slot.width > meta.shape[0]:
                # a meta SHORTER than the spec means a builder/spec
                # layout drift — the bug class this registry exists to
                # prevent. It must be loud, not a silent skip of the
                # pending check slots (join seq, route overflow).
                if "decode_short" not in self._instr_gauged:
                    self._instr_gauged.add("decode_short")
                    _LOG.error(
                        "query '%s': meta suffix (%d lanes) shorter than "
                        "the declared instrument spec %s — step builder "
                        "and instrument_slots() drifted apart; remaining "
                        "slots (incl. checks) not decoded",
                        self.name, meta.shape[0] - 3,
                        [s.name for s in spec])
                tel = getattr(self.app_context, "telemetry", None)
                if tel is not None:
                    tel.count("device.decode_short")
                break
            vals = np.asarray(meta[i:i + slot.width], np.int64)
            i += slot.width
            if slot.kind == "check":
                checks.append((slot, vals))
            else:
                self._record_instrument(slot, vals, telemetry=ins_on)
        for slot, vals in checks:
            self._consume_check_slot(slot.name, vals)

    def _record_instrument(self, slot: Slot, vals, telemetry: bool) -> None:
        self._instr_last[slot.name] = vals
        if slot.name == "shard_rows" and self._route_layout is not None:
            # back-compat mirror (skew debugging reads it directly)
            self._route_layout.last_shard_rows = vals
        if telemetry:
            instruments.record(self, slot, vals,
                               capacity=self._instrument_capacity(slot.name))

    def _consume_check_slot(self, name: str, vals) -> None:
        """Structural (kind='check') slot consumers; the join runtime
        adds 'seq'. graftlint R6 pairs every check slot with a literal
        handled here or in an override."""
        if name == "route_overflow" and int(vals[0]) > 0:
            raise FatalQueryError(
                f"query '{self.name}': {self.route_overflow_msg()}")

    def build_step_fn(self):
        """The pure (state, cols, now) -> (state', out) device function for
        this query — jit-compiled by `_make_step`, also exported raw for
        sharded execution (siddhi_tpu.parallel) and the driver's
        compile-check (`__graft_entry__.entry`)."""
        # host windows already applied the filters (and transforms) before
        # their stage, host-side; host_transforms likewise pre-applies the
        # transforms so the group keyer can read synthetic columns
        host_pre = self.host_window is not None
        filters = [] if host_pre else list(self.filters)
        transforms = [] if (host_pre or self.host_transforms) else list(self.transforms)
        post_pipeline = [] if host_pre else list(self.post_pipeline)
        sel = self.selector_plan
        win = self.window_stage
        islots = self._step_instrument_slots()

        def step(state, cols, current_time):
            from siddhi_tpu.core.plan.selector_plan import STR_RANK

            ctx = {"xp": jnp, "current_time": current_time}
            cols = dict(cols)
            strrank = cols.pop(STR_RANK, None)  # window stages rebuild cols
            for t in transforms:
                cols = t.apply(cols, ctx)
            valid = cols[VALID_KEY]
            timer = cols[TYPE_KEY] == 2
            for f in filters:
                valid = valid & (f(cols, ctx) | timer)
            cols[VALID_KEY] = valid
            new_state = dict(state)
            notify = None
            overflow = None
            if win is not None:
                new_state["win"], cols = win.apply(state["win"],
                                                   conform_cols(win, cols),
                                                   ctx)
                cols = dict(cols)
                notify = cols.pop("__notify__", None)
                overflow = cols.pop("__overflow__", None)
                # post-window stages transform/mask emitted rows (window
                # retention is unaffected — they sit downstream of it)
                ptimer = cols[TYPE_KEY] == 2
                for kind, obj in post_pipeline:
                    if kind == "t":
                        cols = obj.apply(cols, ctx)
                    else:
                        cols[VALID_KEY] = cols[VALID_KEY] & (
                            obj(cols, ctx) | ptimer)
            if strrank is not None:
                cols[STR_RANK] = strrank
            new_state["sel"], out = sel.apply(state["sel"], cols, ctx)
            if notify is not None:
                out["__notify__"] = notify
            if overflow is not None:
                sel_ov = out.get("__overflow__")
                out["__overflow__"] = overflow if sel_ov is None else jnp.maximum(
                    jnp.asarray(overflow).astype(jnp.int32),
                    jnp.asarray(sel_ov).astype(jnp.int32))
            out = pack_meta(out)
            if islots:
                # device instruments ride behind the [ov, notify, count]
                # prefix — decoded by spec at drain (decode_meta_suffix)
                out["__meta__"] = jnp.concatenate(
                    [out["__meta__"]]
                    + self._instrument_values(islots, new_state, cols))
            return new_state, out

        return step

    # ----------------------------------------------------------- processing

    def receive(self, events: List[Event]):
        batch = HostBatch.from_events(events, self.input_definition,
                                      self.dictionary,
                                      pool=pack_pool_of(self.app_context))
        if self.carried_pk:
            pk = np.zeros(batch.capacity, np.int32)
            for i, e in enumerate(events):
                pk[i] = e.pk or 0
            batch.cols[PK_KEY] = pk
        self.process_batch(batch)

    def receive_batch(self, batch: HostBatch, junction=None):
        """Columnar fast path from StreamJunction.send_batch — no Event
        objects on ingest."""
        if self.carried_pk and PK_KEY not in batch.cols:
            batch.cols[PK_KEY] = np.zeros(batch.capacity, np.int32)
        backfill_null_masks(batch, self.input_definition)
        self.process_batch(batch, junction=junction)

    _now_override = None   # timer chunks sweep at their scheduled time

    def _now(self) -> int:
        """Current time for window expiry/stamping: the TIMER chunk's
        scheduled timestamp while one is being processed (the playback
        clock has already jumped ahead of queued timers — reference
        ``Scheduler.sendTimerEvents`` fires each timer AT its time), else
        the app clock."""
        if self._now_override is not None:
            return self._now_override
        return int(self.app_context.timestamp_generator.current_time())

    def process_timer(self, ts: int):
        """Inject a TIMER chunk (the role of Scheduler.sendTimerEvents +
        EntryValveProcessor in the reference)."""
        batch = HostBatch.from_events(
            [Event(timestamp=int(ts), data=[_zero_value(a.type) for a in self.input_definition.attributes])],
            self.input_definition,
            self.dictionary,
        )
        batch.cols[TYPE_KEY][...] = TIMER_TYPE
        # take the per-query lock BEFORE setting the override: a live-mode
        # event batch on another thread must never observe the timer's ts
        # as its clock (the RLock nests with process_batch's own acquire)
        with self._lock:
            # in-flight pipelined batches were dispatched BEFORE this
            # timer fired: drain them first so the timer sweep observes a
            # fully-emitted timeline (and the timer batch itself runs
            # synchronously — _now_override gates the pipeline branch)
            pump = getattr(self.app_context, "completion_pump", None)
            if pump is not None and pump.has_pending:
                pump.flush_owner(self)
            self._now_override = int(ts)
            try:
                self.process_batch(batch)
            finally:
                self._now_override = None

    def _apply_host_transforms(self, cols, ctx):
        for t in self.transforms:
            cols = t.apply(cols, ctx)
        return cols

    def _run_log_taps(self, batch: HostBatch):
        """Host side of ``#log()`` taps: replay each tap's slice of the
        pre-window pipeline with numpy and log the rows flowing at its
        position in the handler chain (LogStreamProcessor.java:219-277)."""
        base_valid = np.asarray(batch.cols[VALID_KEY]) & (
            np.asarray(batch.cols[TYPE_KEY]) == CURRENT)
        if not base_valid.any():
            return
        ctx = {
            "xp": np,
            "current_time": self._now(),
        }
        # only replay the transform prefix some tap actually reads
        depth = min(max(t.n_transforms for t in self.log_stages),
                    len(self.transforms))
        stages = [batch.cols]
        for t in self.transforms[:depth]:
            stages.append(t.apply(stages[-1], ctx))
        for tap in self.log_stages:
            cols = stages[min(tap.n_transforms, len(stages) - 1)]
            valid = np.asarray(cols[VALID_KEY]) & (
                np.asarray(cols[TYPE_KEY]) == CURRENT)
            for f in self.filters[: tap.n_filters]:
                valid = valid & np.asarray(f(cols, ctx))
            idx = np.nonzero(valid)[0]
            if idx.size == 0:
                continue
            attrs = list(self.input_definition.attributes)
            for t in self.transforms[: tap.n_transforms]:
                attrs.extend(t.out_attrs)
            rows, timestamps = [], []
            ts_col = cols[TS_KEY]
            for i in idx:
                row = []
                for a in attrs:
                    mcol = cols.get(a.name + "?")
                    if mcol is not None and bool(mcol[i]):
                        row.append(None)
                    elif a.type == AttrType.STRING:
                        row.append(self.dictionary.decode(int(cols[a.name][i])))
                    else:
                        row.append(cols[a.name][i].item())
                rows.append(tuple(row))
                timestamps.append(int(ts_col[i]))
            tap.emit(rows, timestamps)

    def process_batch(self, batch: HostBatch, junction=None):
        from siddhi_tpu.core.stream.junction import current_delivering_junction
        from siddhi_tpu.observability.tracing import span

        with span("query.step", query=self.name), self._lock:
            # Event-path deliveries (Receiver.receive) carry no junction
            # parameter — fall back to the delivery-loop thread-local so
            # pipelined completions keep their error attribution and
            # latency feedback; direct receiver feeds see None
            j = junction or current_delivering_junction()
            self._cur_junction = j
            # fault-stream routing of drain-time errors needs the input
            # events; retain the batch only under @OnError(action=stream)
            self._cur_fault_batch = batch if (
                j is not None and j.on_error_action == "STREAM"
                and j.fault_junction is not None) else None
            # batch-journey: fork the pack stamp, open the dispatch
            # stage (host prep + step dispatch); _finish_device_batch
            # consumes it (one journey per delivered batch — routed
            # splits ride the first piece)
            self._cur_journey = journey.begin(batch) \
                if journey.enabled() else None
            notify_host = None
            if self.log_stages:
                self._run_log_taps(batch)
            partitioned = self.partition_ctx is not None
            pk_done = False
            if partitioned and self.host_window is not None:
                # per-key host stages route rows by the pk column, so the
                # partition key must be attached before the window runs
                cols = batch.cols
                if self.carried_pk:
                    pk0 = cols.get(PK_KEY)
                    if pk0 is None:
                        pk0 = np.zeros(batch.capacity, np.int32)
                elif self.partition_keyer is not None:
                    cols, pk0 = self.partition_keyer.apply(cols)
                    batch = HostBatch(cols)
                else:
                    pk0 = np.zeros(batch.capacity, np.int32)
                batch.cols[PK_KEY] = np.asarray(pk0, np.int32)
                pk_done = True
            if self.host_window is not None:
                now_h = self._now()
                ctx = {"xp": np, "current_time": now_h}
                cols = batch.cols
                for t in self.transforms:
                    cols = t.apply(cols, ctx)
                valid = cols[VALID_KEY]
                timer = cols[TYPE_KEY] == TIMER_TYPE
                for f in self.filters:
                    valid = valid & (np.asarray(f(cols, ctx)) | timer)
                cols[VALID_KEY] = valid
                batch = HostBatch(cols)
                batch, notify_host = self.host_window.process(batch, now_h)
                if self.post_pipeline:
                    cols = dict(batch.cols)
                    ptimer = cols[TYPE_KEY] == TIMER_TYPE
                    for kind, obj in self.post_pipeline:
                        if kind == "t":
                            cols = obj.apply(cols, ctx)
                        else:
                            cols[VALID_KEY] = cols[VALID_KEY] & (
                                np.asarray(obj(cols, ctx)) | ptimer)
                    batch = HostBatch(cols)
            elif self.host_transforms:
                now_h = self._now()
                batch = HostBatch(self._apply_host_transforms(
                    batch.cols, {"xp": np, "current_time": now_h}))
            cols = batch.cols
            pk = None
            if partitioned:
                if pk_done:
                    # already attached (and carried through the host
                    # window's emitted rows)
                    pk = cols.get(PK_KEY)
                    if pk is None:
                        pk = np.zeros(batch.capacity, np.int32)
                elif self.carried_pk:
                    pk = cols.get(PK_KEY)
                    if pk is None:
                        pk = np.zeros(batch.capacity, np.int32)
                elif self.partition_keyer is not None:
                    cols, pk = self.partition_keyer.apply(cols)
                    batch = HostBatch(cols)
                cols[PK_KEY] = np.asarray(pk, np.int32)
            if self.keyer is not None:
                cols[GK_KEY] = self.keyer(cols, pk=pk if partitioned else None)
            elif partitioned:
                cols[GK_KEY] = cols[PK_KEY]
            else:
                cols[GK_KEY] = np.zeros(batch.capacity, np.int32)
            if partitioned or self.keyer is not None:
                self._ensure_capacity()
            if self._state is None:
                self._state = self._init_state()
            if self._step is None:
                self._step = self._make_step()
            else:
                # hit key follows the wrapper's own key: a sharded step
                # (mesh.shard_query_step) compiles under ".sharded_step",
                # and its hits must land on the SAME series or cache-hit
                # dashboards read garbage for sharded apps
                self.app_context.telemetry.record_jit(
                    getattr(self._step, "_key", f"query.{self.name}.step"),
                    hit=True)
            if self._route_layout is not None:
                # device-routed dispatch: pad/precheck host-side (splitting
                # oversized batches instead of overflowing) and run each
                # piece through the routed step in order
                from siddhi_tpu.parallel.mesh import prepare_routed_batches

                notify = None
                for piece in prepare_routed_batches(self, cols):
                    nt = self._finish_device_batch(
                        self._step, piece, self.overflow_knob_msg())
                    if nt is not None:
                        notify = nt if notify is None else min(notify, nt)
            else:
                notify = self._finish_device_batch(
                    self._step, cols, self.overflow_knob_msg())
        if notify_host is not None:
            notify = notify_host if notify is None else min(notify, notify_host)
        if notify is not None and self.scheduler is not None:
            self.scheduler.notify_at(notify, self.process_timer)

    def overflow_knob_msg(self, code: Optional[int] = None) -> str:
        """Capacity-overflow message naming THIS query's knob — shared by
        the unfused path and the fused fan-out group
        (``core/query/fused_fanout.py``) so attribution cannot drift.
        ``code`` is the step's overflow value; join runtimes decode it
        as a bitmask into the exact knob (single-stream steps carry a
        single overflow cause, so it is ignored here)."""
        knob = (
            "app_context.partition_window_capacity"
            if self.partition_ctx is not None
            else "app_context.window_capacity"
        )
        if any(s.kind == "distinctcount"
               for s in self.selector_plan.specs or []):
            knob += " (or app_context.distinct_values_capacity)"
        return f"window buffer capacity exceeded — raise {knob}"

    def route_overflow_msg(self) -> str:
        """Device-router exchange overflow naming its knob, in the same
        convention as ``overflow_knob_msg`` (the host precheck splits
        oversized batches, so this only fires on direct step callers that
        bypass ``prepare_routed_batches``)."""
        rl = self._route_layout
        rps = rl.rows_per_shard if rl is not None else 0
        return (f"shard exchange overflow — more rows bound for one shard "
                f"pair than its quota; raise rows_per_shard={rps} "
                f"(device_route_query_step) or split the batch")

    def _routed_meta_check(self, meta) -> None:
        """Back-compat alias: the route-overflow/rows suffix is now one
        case of the declarative instrument spec — see
        ``decode_meta_suffix`` / ``instrument_slots``."""
        self.decode_meta_suffix(meta)

    def _host_keyed_select(self, out_host: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Split-pipeline tail: when the group key is computed from a device
        stage's OUTPUT columns (pattern captures, joined rows), the keyer
        runs host-side between the stage and a separately-jitted selector
        step (GroupByKeyGenerator.java:37 over intermediate events)."""
        pk = out_host.get(PK_KEY) if self.partition_ctx is not None else None
        out_host[GK_KEY] = self.keyer(out_host, pk=pk)
        self._ensure_capacity()
        if self._sel_step is None:
            sel = self.selector_plan

            def fn(sel_state, cols, now):
                st2, out2 = sel.apply(sel_state, cols, {"xp": jnp, "current_time": now})
                return st2, pack_meta(out2)

            self._sel_step = self.app_context.telemetry.instrument_jit(
                jax.jit(fn, donate_argnums=0),
                f"query.{self.name}.selector", family="selector")
        else:
            self.app_context.telemetry.record_jit(
                f"query.{self.name}.selector", hit=True)
        now = np.int64(self._now())
        new_sel, sel_out = self._sel_step(self._state["sel"], dict(out_host), now)
        self._state["sel"] = new_sel
        out = LazyColumns(sel_out)
        meta = out.pop("__meta__", None)
        out.pop("__notify__", None)
        out.pop("__overflow__", None)
        if meta is not None and int(np.asarray(meta)[0]) != 0:
            # the selector step's own overflow (distinctCount value-table
            # saturation) must not be silently clamped on the split path
            raise FatalQueryError(
                "selector aggregation overflow — raise "
                "app_context.distinct_values_capacity")
        return out

    def _finish_device_batch(self, step, cols, overflow_msg: str) -> Optional[int]:
        """Run the jitted step, raise on overflow, emit outputs; returns the
        wanted timer wake time (or None). Shared tail of every query
        runtime's batch processing (single-stream, NFA, join)."""
        from siddhi_tpu.core.util.statistics import latency_t0, record_elapsed_ms

        sm = self.app_context.statistics_manager
        t0 = latency_t0(sm)
        jr = self._cur_journey
        self._cur_journey = None
        now = np.int64(self._now())
        if isinstance(cols, LazyColumns):
            cols = dict(cols)   # jit boundary: raw (possibly device) arrays
        if self.selector_plan.needs_str_rank:
            # string order-by keys sort by lexicographic rank, not id
            from siddhi_tpu.core.plan.selector_plan import STR_RANK

            cols[STR_RANK] = self.dictionary.rank_table()
        self._state, out = step(self._state, cols, now)
        # lazy pull: only columns a consumer actually reads cross the
        # device->host link; overflow/notify/size travel as ONE packed
        # array — a single ~70ms tunnel round trip per batch
        out_host = LazyColumns(out)
        size_hint = None
        meta = (dict.__getitem__(out_host, "__meta__")
                if "__meta__" in out_host else None)   # raw — no pull yet
        if meta is not None:
            pump = getattr(self.app_context, "completion_pump", None)
            if (pump is not None and pump.depth > 1 and self._pipeline_ok
                    and self._now_override is None):
                # pipelined dispatch: the batch rides in flight while the
                # producer packs the next one; the pump emits in dispatch
                # order, delivers __notify__ at drain, and surfaces
                # overflow on the producer's next send (completion.py)
                from siddhi_tpu.core.query.completion import QueryCompletion

                record_elapsed_ms(sm, self.name, t0)
                if jr is not None:
                    jr.end_dispatch()   # device/emit stages close at drain
                pump.submit(QueryCompletion(
                    self, out_host, overflow_msg,
                    junction=self._cur_junction,
                    batch=getattr(self, "_cur_fault_batch", None),
                    journey=jr))
                return None
            defer = getattr(self.app_context, "defer_meta", 1)
            if defer > 1 and self._defer_ok:
                # batch N metas into ONE round trip: queue the (device)
                # output; emission + overflow surfacing lag <= N batches
                # (dispatch-side latency only — emission is deferred)
                record_elapsed_ms(sm, self.name, t0)
                if jr is not None:
                    # legacy hold-N path: the deferred drain is not
                    # instrumented — finish with the stages observed so
                    # far (pack/queue/dispatch) rather than vanishing
                    jr.end_dispatch()
                    jr.finish(self.app_context, (self.name,))
                self._deferred.append((out_host, overflow_msg))
                if len(self._deferred) < defer:
                    return None
                return self.flush_deferred()
            dict.pop(out_host, "__meta__")
            if jr is not None:
                # synchronous device stage: the ride is ~0 (we pull
                # immediately), so device service is the blocking pull
                jr.end_dispatch()
                jr.pre_drain(journey.ready_of(meta))
                _tp = time.perf_counter()
                meta = self._pull_meta(meta)
                jr.drained((time.perf_counter() - _tp) * 1000.0)
            else:
                meta = self._pull_meta(meta)
            self.decode_meta_suffix(meta)
            overflow = int(meta[0])
            notify = int(meta[1])
            size_hint = int(meta[2])
            if overflow > 0:
                # joins pass a CALLABLE that decodes the step's overflow
                # bitmask into the exact knob (overflow_knob_msg)
                msg = (overflow_msg(overflow) if callable(overflow_msg)
                       else overflow_msg)
                raise FatalQueryError(
                    f"query '{self.name}': {msg} before creating the runtime")
            record_elapsed_ms(sm, self.name, t0)
            self._timed_emit(HostBatch(out_host, size=size_hint), jr)
            if notify >= 0:
                return notify
            return None
        overflow = out_host.pop("__overflow__", None)
        if overflow is not None and int(overflow) > 0:
            msg = (overflow_msg(int(overflow)) if callable(overflow_msg)
                   else overflow_msg)
            raise FatalQueryError(
                f"query '{self.name}': {msg} before creating the runtime"
            )
        notify = out_host.pop("__notify__", None)
        record_elapsed_ms(sm, self.name, t0)
        if jr is not None:
            jr.end_dispatch()   # host-window path: no device meta stage
        self._timed_emit(HostBatch(out_host), jr)
        if notify is not None and int(notify) >= 0:
            return int(notify)
        return None

    def _timed_emit(self, out: HostBatch, jr) -> None:
        """``_emit`` with the journey's emit stage timed and the journey
        finished (histograms + ring) — the synchronous tail; pipelined
        batches run the same accounting at drain (completion.py)."""
        if jr is None:
            self._emit(out)
            return
        t_e = time.perf_counter()
        try:
            self._emit(out)
        finally:
            jr.emit_ms = (time.perf_counter() - t_e) * 1000.0
            jr.finish(self.app_context, (self.name,))

    def _pull_meta(self, meta):
        """Pull the packed meta array; on a multi-process mesh with
        ``siddhi_tpu.cluster_step_timeout`` set, bound the wait so a dead
        peer surfaces as a labeled ClusterPeerError through the fault
        machinery instead of hanging the coordinator (SURVEY.md §5.3)."""
        timeout = getattr(self.app_context, "cluster_step_timeout", None)
        if timeout is not None and self._shard_mesh is not None:
            from siddhi_tpu.parallel.distributed import guarded_pull

            return guarded_pull(meta, timeout,
                                what=f"query '{self.name}' step")
        # explicit pull: this is THE sanctioned per-batch round trip —
        # the sanitizer's transfer guard rejects implicit d2h transfers
        return np.asarray(jax.device_get(meta))

    @property
    def _defer_ok(self) -> bool:
        # scheduler-driven windows need their per-batch __notify__ promptly
        return (self.host_window is None
                and (self.window_stage is None
                     or not getattr(self.window_stage, "needs_scheduler", False)))

    @property
    def _pipeline_ok(self) -> bool:
        """May this runtime's batches ride the CompletionPump? Unlike
        ``_defer_ok``, scheduler-driven and host windows are ELIGIBLE —
        the pump delivers their ``__notify__`` wake times promptly at
        drain (sync sends flush before returning; @Async workers flush at
        queue-idle) instead of holding them a full defer window. Joins
        override this to False (``join_runtime._pipeline_ok``)."""
        return True

    def flush_deferred(self) -> Optional[int]:
        """Drain queued outputs: pull ALL their metas in one batched round
        trip, then emit in order (called when the defer window fills, at
        checkpoints, and at shutdown)."""
        with self._lock:
            if not self._deferred:
                return None
            pending, self._deferred = self._deferred, []
            raw = [dict.__getitem__(o, "__meta__") for o, _m in pending]
            timeout = getattr(self.app_context, "cluster_step_timeout", None)
            if timeout is not None and self._shard_mesh is not None:
                # the deferred drain is a device pull too: bound it the
                # same way as _pull_meta, or a dead peer hangs it forever
                from siddhi_tpu.parallel.distributed import guarded_pull

                metas = guarded_pull(raw, timeout,
                                     what=f"query '{self.name}' drain")
            else:
                metas = jax.device_get(raw)
            notify_min: Optional[int] = None
            overflow_errs: List[str] = []
            for (out_host, overflow_msg), meta in zip(pending, metas):
                dict.pop(out_host, "__meta__")
                try:
                    # instrument/structural suffix (drain-then-raise:
                    # a route overflow joins the collected errors)
                    self.decode_meta_suffix(meta)
                except FatalQueryError as suffix_err:
                    msg = str(suffix_err)
                    if msg not in overflow_errs:
                        overflow_errs.append(msg)
                overflow, notify, size = int(meta[0]), int(meta[1]), int(meta[2])
                if overflow > 0 and overflow_msg not in overflow_errs:
                    # every DISTINCT knob text of an overflowed batch is
                    # reported (first-error-wins dropped the later
                    # members' knobs); still drain-then-raise
                    overflow_errs.append(overflow_msg)
                self._emit(HostBatch(out_host, size=size))
                if notify >= 0:
                    notify_min = notify if notify_min is None else min(notify_min, notify)
            if overflow_errs:
                raise FatalQueryError(
                    f"query '{self.name}': {'; '.join(overflow_errs)} "
                    f"before creating the runtime")
            return notify_min

    def _emit(self, out: HostBatch):
        if out.size == 0:
            return
        uuid_cols = self.selector_plan.uuid_cols
        if uuid_cols:
            # uuid(): fresh per-row UUID strings, filled host-side (the
            # jitted step emitted placeholders — see ops/expressions.py).
            # The whole batch of UUIDs — every column — is generated up
            # front and dictionary-encoded in ONE encode_array pass; the
            # fused fan-out path shares this call site via m._emit
            idx = np.nonzero(np.asarray(out.cols[VALID_KEY]))[0]
            if idx.size:
                fresh = np.array(
                    [str(uuid.uuid4())
                     for _ in range(idx.size * len(uuid_cols))],
                    dtype=object)
                ids = self.dictionary.encode_array(fresh)
                for ci, col in enumerate(uuid_cols):
                    vals = np.asarray(out.cols[col]).copy()
                    vals[idx] = ids[ci * idx.size:(ci + 1) * idx.size]
                    out.cols[col] = vals
        from siddhi_tpu.core.query.ratelimit import PassThroughRateLimiter

        if (
            (self.rate_limiter is None
             or type(self.rate_limiter) is PassThroughRateLimiter)
            and self.output_action is None
            and not self.query_callbacks
            and self.output_junction is not None
            and not self.attach_pk
            and hasattr(self.output_junction, "send_batch")
        ):
            # columnar re-publish: no Event materialization between queries
            cols = LazyColumns(out.cols)
            if self.selector_plan.expired_on:
                # EXPIRED -> CURRENT on re-publish
                # (InsertIntoStreamCallback.java:52-55); CURRENT-only
                # selectors skip the flip — touching TYPE would pull every
                # device column across the tunnel
                t = cols[TYPE_KEY]
                cols[TYPE_KEY] = np.where(t == EXPIRED, CURRENT, t).astype(np.int8)
            self.output_junction.send_batch(HostBatch(cols, size=out._size))
            return
        want_pk = self.attach_pk or self.limiter_needs_pk
        events = out.to_events(
            self.output_attrs, self.dictionary,
            pk_key=PK_KEY if want_pk else None,
            gk_key=GK_KEY if self.limiter_needs_gk else None,
            object_meta=self.selector_plan.object_meta or None,
            object_multi=set(self.selector_plan.object_multi) or None,
        )
        if self.rate_limiter is not None:
            self.rate_limiter.process(events)
        else:
            self.send_to_callbacks(events)

    def send_empty_to_query_callbacks(self):
        """Snapshot limiters deliver EMPTY flushes to QueryCallbacks as
        (null, null) — SnapshotOutputRateLimitTestCase q21 counts them —
        while stream junctions/actions see nothing."""
        ts = self.app_context.timestamp_generator.current_time()
        for cb in self.query_callbacks:
            cb.receive(ts, None, None)

    def send_to_callbacks(self, events: List[Event]):
        if not events:
            return
        if self.output_action is not None:
            self.output_action(events)
        elif self.output_junction is not None:
            # EXPIRED -> CURRENT on re-publish (InsertIntoStreamCallback.java:52-55)
            repub = [
                Event(timestamp=e.timestamp, data=e.data, pk=e.pk) if e.is_expired else e
                for e in events
            ]
            self.output_junction.send_events(repub)
        for cb in self.query_callbacks:
            in_events = [e for e in events if not e.is_expired] or None
            remove_events = [e for e in events if e.is_expired] or None
            cb.receive(events[0].timestamp, in_events, remove_events)


def backfill_null_masks(batch: HostBatch, definition) -> None:
    """A re-published batch omits '?' masks for never-null outputs;
    window buffers key off the full col-spec set, so backfill. Shared by
    the unfused and fused receive_batch paths — the capacity read skips
    ``__getitem__`` so device-held columns stay unpulled."""
    cap = dict.__getitem__(batch.cols, VALID_KEY).shape[0]
    for a in definition.attributes:
        if a.name in batch.cols and a.name + "?" not in batch.cols:
            batch.cols[a.name + "?"] = np.zeros(cap, bool)


def pack_meta(out: dict) -> dict:
    """Fold __overflow__/__notify__/valid-count into ONE device array so
    the host pays a single D2H round trip per batch (the axon tunnel
    charges ~70 ms latency per pull, independent of size)."""
    ov = out.pop("__overflow__", None)
    nt = out.pop("__notify__", None)
    ov = jnp.int64(0) if ov is None else jnp.asarray(ov).astype(jnp.int64).reshape(())
    nt = jnp.int64(-1) if nt is None else jnp.asarray(nt).astype(jnp.int64).reshape(())
    n = jnp.sum(out[VALID_KEY], dtype=jnp.int64)
    out["__meta__"] = jnp.stack([ov, nt, n])
    return out


def _zero_value(attr_type: AttrType):
    if attr_type == AttrType.STRING:
        return ""
    if attr_type == AttrType.BOOL:
        return False
    return 0


def _pow2(needed: int, start: int = 16) -> int:
    k = max(start, 1)
    while k < needed:
        k *= 2
    return k


def _copy_prefix(new, old):
    """Copy old state into the (larger) new buffer along the key axis."""
    if new.shape == old.shape:
        return old
    sl = tuple(slice(0, s) for s in old.shape)
    return new.at[sl].set(old)
