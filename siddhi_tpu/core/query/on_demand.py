"""On-demand (store) queries against tables and named windows.

Mirror of reference ``util/parser/OnDemandQueryParser.java`` (589 LoC of
find/insert/delete/update runtime assembly): the store's current contents
become one columnar batch, the `on` condition is a vectorized mask, and
the selector (aggregations, group by, having, order/limit) runs the same
device stage as streaming queries — recompiled per call shape, cached by
jit."""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from siddhi_tpu.compiler import SiddhiCompiler
from siddhi_tpu.core.event import CURRENT, Event, HostBatch
from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
from siddhi_tpu.core.plan.selector_plan import GK_KEY, plan_selector
from siddhi_tpu.core.table.in_memory_table import TBL_PREFIX, TableConditionResolver
from siddhi_tpu.ops.expressions import (
    TS_KEY,
    TYPE_KEY,
    VALID_KEY,
    CompileError,
    compile_condition,
)
from siddhi_tpu.query_api.definitions import AttrType
from siddhi_tpu.query_api.execution import OnDemandQuery, ReturnStream
from siddhi_tpu.query_api.expressions import Variable


def _aggregation_contents(agg, oq: OnDemandQuery, dictionary):
    """Synthesize the stitched per-duration rows of an incremental
    aggregation as a columnar batch (reference OnDemandQuery `within ...
    per ...` against an aggregation)."""
    from siddhi_tpu.core.aggregation.incremental import parse_duration_name
    from siddhi_tpu.ops.types import dtype_of
    from siddhi_tpu.query_api.definitions import AttrType
    from siddhi_tpu.query_api.expressions import Constant, TimeConstant

    store = oq.input_store
    if store.per is None:
        raise CompileError(
            f"aggregation '{agg.definition.id}' queries need `per '<duration>'`")
    if not isinstance(store.per, Constant) or not isinstance(store.per.value, str):
        raise CompileError("`per` must be a duration string constant")
    duration = parse_duration_name(store.per.value)

    within = None
    w = store.within
    if w is not None:
        from siddhi_tpu.core.aggregation.within_time import (
            WithinFormatError, resolve_within_pair, single_within_range)

        def _const(x):
            if isinstance(x, (Constant, TimeConstant)):
                return x.value
            raise CompileError(
                "within bounds must be constants (unix ms or "
                "'yyyy-MM-dd HH:mm:ss' date strings)")

        try:
            if isinstance(w, tuple):
                within = resolve_within_pair(_const(w[0]), _const(w[1]))
            elif isinstance(w, Constant) and isinstance(w.value, str):
                # single wildcard pattern: the whole calendar unit it names
                # (IncrementalStartTimeEndTimeFunctionExecutor.java:139-200)
                within = single_within_range(w.value)
            else:
                # single-bound within must be a date-pattern STRING
                # (startTimeEndTime single-arg validation)
                raise CompileError(
                    "a single within bound must be a date-pattern string "
                    "('yyyy-MM-dd HH:mm:ss', '**' wildcards allowed)")
        except WithinFormatError as e:
            raise CompileError(str(e)) from None

    definition, cols, valid = agg.contents(duration, within)
    return definition, {k: jnp.asarray(v) for k, v in cols.items()}, jnp.asarray(valid)


def _run_mutation(oq: OnDemandQuery, app_runtime, dictionary) -> List[Event]:
    """On-demand table mutations (reference ``OnDemandQueryParser`` +
    StoreQuery INSERT/DELETE/UPDATE/UPDATE-OR-INSERT runtimes)."""
    from siddhi_tpu.core.event import HostBatch
    from siddhi_tpu.core.query.output_callbacks import _compile_assignments
    from siddhi_tpu.ops.expressions import compile_expr
    from siddhi_tpu.ops.types import dtype_of

    out = oq.output_stream
    target = getattr(out, "target_id", None)
    table = app_runtime.tables.get(target or "")
    if table is None:
        raise CompileError(
            f"on-demand {oq.type} target '{target}' is not a defined table")
    tdef = table.definition
    const_resolver = TableConditionResolver(tdef, None, dictionary)

    # materialize the SELECT projection as a one-row pseudo event: its
    # aliases are the mutation's "triggering event" attributes, so
    # `select 100L as vol delete ... on StockTable.volume == vol` and
    # `select "X" as s update ... set StockTable.symbol = s` resolve like
    # their streaming counterparts (reference OnDemandQueryParser builds a
    # matching StateEvent the same way)
    from siddhi_tpu.query_api.definitions import (
        Attribute, StreamDefinition as _SD)

    ev_def = None
    ev_batch = None
    sel = (oq.selector.selection_list
           if oq.selector is not None else []) or []
    if sel:
        ctx = {"xp": np, "current_time": 0}
        row = {TS_KEY: np.zeros(1, np.int64),
               TYPE_KEY: np.zeros(1, np.int8),
               VALID_KEY: np.ones(1, bool)}
        ev_attrs = []
        sel_names = []
        for i, oa in enumerate(sel):
            fn, t = compile_expr(oa.expression, const_resolver)
            try:
                v, mk = fn({VALID_KEY: row[VALID_KEY]}, ctx)
            except KeyError as e:
                raise CompileError(
                    "on-demand mutation projections must be constant "
                    f"expressions (no column references): {e}") from None
            try:
                name = oa.name
            except ValueError:
                name = f"_c{i}"   # unaliased constant (positional insert)
            row[name] = np.broadcast_to(np.asarray(v, dtype_of(t)), (1,))
            row[name + "?"] = np.broadcast_to(
                np.asarray(mk, bool) if mk is not None
                else np.zeros(1, bool), (1,))
            ev_attrs.append(Attribute(name=name, type=t))
            sel_names.append(name)
        ev_def = _SD(id="__on_demand__", attributes=ev_attrs)
        ev_batch = HostBatch(row)
    resolver = TableConditionResolver(tdef, ev_def, dictionary)

    if oq.type == "insert":
        # `select <values> insert into Table` — positional mapping
        if len(sel) != len(tdef.attributes):
            raise CompileError(
                f"insert into '{target}' needs {len(tdef.attributes)} values")
        row = {TS_KEY: np.zeros(1, np.int64),
               TYPE_KEY: np.zeros(1, np.int8),
               VALID_KEY: np.ones(1, bool)}
        for attr, sname in zip(tdef.attributes, sel_names):
            row[attr.name] = np.asarray(
                ev_batch.cols[sname], dtype_of(attr.type))
            row[attr.name + "?"] = np.asarray(ev_batch.cols[sname + "?"])
        table.insert(HostBatch(row))
        return []

    if oq.type == "delete":
        cond = compile_condition(out.on_delete, resolver) \
            if out.on_delete is not None else None
        table.delete(cond, ev_batch)
        return []

    cond = compile_condition(out.on_update, resolver) \
        if out.on_update is not None else None
    if out.update_set is None:
        raise CompileError(f"on-demand {oq.type} needs a `set` clause")
    assignments = _compile_assignments(table, ev_def, out.update_set, resolver)
    if oq.type == "update":
        table.update(cond, assignments, ev_batch)
        return []
    if oq.type == "update_or_insert":
        import jax.numpy as jnp

        m = table.update(cond, assignments, ev_batch)
        if not bool(np.asarray(jnp.any(m))):
            # no row matched: insert one built from the set clause
            ctx = {"xp": np, "current_time": 0}
            ones = np.ones(1, bool)
            ev = {VALID_KEY: ones}
            if ev_batch is not None:
                from siddhi_tpu.core.table.in_memory_table import EV_PREFIX

                for k, v in ev_batch.cols.items():
                    ev[EV_PREFIX + k] = np.asarray(v)[:, None]
            row = {TS_KEY: np.zeros(1, np.int64),
                   TYPE_KEY: np.zeros(1, np.int8),
                   VALID_KEY: ones}
            # the reference inserts the PROJECTED pseudo event itself
            # (UpdateOrInsertReducer converts the matching StateEvent), so
            # name-matched projection columns seed the row; the set clause
            # then overrides (they usually agree — test15: volume 123
            # comes from the projection, not the set)
            set_cols = {}
            set_masks = {}
            for col_name, fn, _t in assignments:
                try:
                    v, mk = fn(ev, ctx)
                except KeyError as e:
                    raise CompileError(
                        "on-demand update-or-insert: the `set` clause "
                        "references a table column, which has no value on "
                        f"the insert (no-match) branch: {e}") from None
                set_cols[col_name] = np.asarray(v).reshape(-1)[:1]
                set_masks[col_name] = (np.asarray(mk, bool).reshape(-1)[:1]
                                       if mk is not None else np.zeros(1, bool))
            for attr in tdef.attributes:
                if attr.name in set_cols:
                    row[attr.name] = np.broadcast_to(
                        np.asarray(set_cols[attr.name], dtype_of(attr.type)), (1,))
                    row[attr.name + "?"] = set_masks[attr.name]
                elif ev_batch is not None and attr.name in ev_batch.cols:
                    row[attr.name] = np.asarray(
                        ev_batch.cols[attr.name], dtype_of(attr.type))[:1]
                    row[attr.name + "?"] = np.asarray(
                        ev_batch.cols[attr.name + "?"])[:1]
                else:
                    row[attr.name] = np.zeros(1, dtype_of(attr.type))
                    row[attr.name + "?"] = np.ones(1, bool)   # null
            table.insert(HostBatch(row))
        return []
    raise CompileError(f"unsupported on-demand query type '{oq.type}'")


def extract_eq_probe(cond, table_def, probe_attrs):
    """Split an `on` condition into (attr, const, residual) when it has a
    top-level equality conjunct ``T.attr == <constant>`` over an indexed
    attribute — the shape the reference compiles to an
    ``IndexedEventHolder`` probe (CompareCollectionExecutor over
    indexData). Returns None when no probe applies."""
    from siddhi_tpu.query_api.expressions import And, Compare, Constant

    def attr_const(e):
        if not isinstance(e, Compare) or e.operator != "==":
            return None
        for var, const in ((e.left, e.right), (e.right, e.left)):
            if (isinstance(var, Variable) and isinstance(const, Constant)
                    and var.stream_id in (None, table_def.id)
                    and var.attribute_name in probe_attrs):
                return var.attribute_name, const
        return None

    hit = attr_const(cond)
    if hit is not None:
        return hit[0], hit[1], None
    if isinstance(cond, And):
        for this, other in ((cond.left, cond.right), (cond.right, cond.left)):
            hit = attr_const(this)
            if hit is not None:
                return hit[0], hit[1], other
    return None


def run_on_demand_query(source: str, app_runtime) -> List[Event]:
    """Parse/compile-once, execute-per-call: compiled FIND runtimes are
    cached per query text, capped at 50 with oldest-inserted eviction
    (reference ``SiddhiAppRuntimeImpl.java:344-351``). Mutations recompile
    per call (their compile is a fraction of the store write they do).

    Barrier scope: mutations and table/named-window finds hold the app
    barrier (their stores are mutated by streaming output under the same
    barrier). Aggregation store-queries run WITHOUT it — the single-store
    runtime snapshots under its own lock, and the serving tier's sharded
    runtime reads epoch-pinned per-shard snapshots — so a storm of
    dashboard `within ... per ...` reads never blocks ingest."""
    cache = getattr(app_runtime, "_on_demand_cache", None)
    if cache is None:
        from collections import OrderedDict

        cache = app_runtime._on_demand_cache = OrderedDict()
    rt = cache.get(source)
    if rt is None:
        oq: OnDemandQuery = SiddhiCompiler.parse_on_demand_query(source)
        dictionary = app_runtime.app_context.string_dictionary
        if oq.type != "find" or oq.input_store is None:
            with app_runtime._barrier:
                return _run_mutation(oq, app_runtime, dictionary)
        rt = OnDemandFindRuntime(oq, app_runtime, dictionary)
        cache[source] = rt
        if len(cache) > 50:
            cache.popitem(last=False)
    if rt.agg is not None:
        return rt.execute()
    with app_runtime._barrier:
        return rt.execute()


class OnDemandFindRuntime:
    """Compiled FIND runtime (reference *OnDemandQueryRuntime classes):
    everything derivable from the query TEXT and the store's definition —
    resolvers, probe extraction, compiled conditions, the selector plan,
    group-key executors — happens once here; ``execute`` only touches
    store contents."""

    def __init__(self, oq: OnDemandQuery, app_runtime, dictionary):
        import threading

        self.oq = oq
        self.app_runtime = app_runtime
        self.dictionary = dictionary
        # callers are already serialized by the app barrier
        # (SiddhiAppRuntime.query), but the cached runtime must not rely
        # on that: its keyer/plan state is per-execute anyway and this
        # lock keeps direct executes safe too
        self._lock = threading.Lock()
        store_id = oq.input_store.store_id
        self.table = app_runtime.tables.get(store_id)
        self.window = app_runtime.named_windows.get(store_id)
        self.agg = app_runtime.aggregations.get(store_id)
        if self.table is not None:
            self.definition = self.table.definition
        elif self.window is not None:
            self.definition = self.window.definition
        elif self.agg is not None:
            self.definition = self.agg.output_definition()
        else:
            raise CompileError(
                f"'{store_id}' is not a defined table/window/aggregation")
        definition = self.definition

        self.cond = None
        self.probe = None
        self.residual_cond = None
        if oq.input_store.on_condition is not None:
            resolver = TableConditionResolver(definition, None, dictionary)
            probe = None
            if self.table is not None and hasattr(self.table, "probe_attrs"):
                probe = extract_eq_probe(oq.input_store.on_condition,
                                         definition, self.table.probe_attrs())
                if probe is not None:
                    # a narrowing cast into the column dtype would change
                    # equality semantics (2.5 -> 2): scan instead
                    from siddhi_tpu.core.plan.query_planner import _probe_type_safe

                    attr_t = definition.attribute(probe[0]).type
                    if not _probe_type_safe(attr_t, probe[1].type):
                        probe = None
            self.probe = probe
            if probe is not None:
                if probe[2] is not None:
                    self.residual_cond = compile_condition(probe[2], resolver)
            else:
                self.cond = compile_condition(
                    oq.input_store.on_condition, resolver)

        sel_resolver = SingleStreamResolver(
            definition, dictionary, ref_id=oq.input_store.store_reference_id,
            synthetic={})
        self.plan = plan_selector(
            selector=oq.selector,
            input_attrs=[(a.name, a.type) for a in definition.attributes],
            resolver=sel_resolver,
            output_event_type="current",
            # the store's contents are ONE batch chunk: grouped/aggregated
            # finds return one row per group (the running aggregate's last
            # row), matching reference OnDemandQueryTableTestCase test3
            # (2 groups -> 2 rows, sum aggregated across each group)
            batch_mode=True,
            dictionary=dictionary,
        )
        self.group_fns = None
        if self.plan.group_by:
            from siddhi_tpu.ops.expressions import compile_expr

            # compiled key executors are cached; the keyer itself is
            # rebuilt per execute — a persistent keyer's dense ids never
            # recycle, so state would grow with every key EVER seen
            self.group_fns = [compile_expr(v, sel_resolver)
                              for v in oq.selector.group_by_list]

    def execute(self) -> List[Event]:
        with self._lock:
            return self._execute()

    def _execute(self) -> List[Event]:
        oq, table, dictionary = self.oq, self.table, self.dictionary
        definition = self.definition
        if table is not None:
            cols, valid = table.contents()
        elif self.window is not None:
            cols, valid = self.window.contents()
        else:
            definition, cols, valid = _aggregation_contents(
                self.agg, oq, dictionary)

        C = valid.shape[0]
        match = valid
        probe = self.probe
        if probe is not None:
            # indexed equality: hash-probe the candidate slots and evaluate
            # only the residual condition over them — sub-linear in the
            # table size (IndexEventHolder probe path)
            attr, const, _residual = probe
            value = const.value
            if const.type == AttrType.STRING:
                value = dictionary.encode(value)
            with table._lock:
                # probe + snapshot under ONE lock: slots must index the
                # same state the output columns come from (a concurrent
                # insert could otherwise grow capacity past this snapshot)
                slots = table.index_candidates(attr, value)
                cols, valid = table.contents()
                C = valid.shape[0]
            # the pre-lock snapshot is dead: a concurrent insert may have
            # grown capacity, so match must rebind to the in-lock valid
            match = valid
            sel = np.zeros(C, bool)
            if slots.size:
                host_valid = np.asarray(valid)
                keep = slots[host_valid[slots]]
                if self.residual_cond is not None and keep.size:
                    sub = {TBL_PREFIX + k: np.asarray(v)[keep][None, :]
                           for k, v in cols.items()}
                    sub[TS_KEY] = np.asarray(cols[TS_KEY])[keep][None, :]
                    rm = np.broadcast_to(
                        np.asarray(self.residual_cond(sub, {"xp": np})),
                        (1, keep.size))[0]
                    keep = keep[rm]
                sel[keep] = True
            match = match & jnp.asarray(sel)
        elif self.cond is not None:
            ev = {TBL_PREFIX + k: jnp.asarray(v)[None, :]
                  for k, v in cols.items()}
            ev[TS_KEY] = jnp.asarray(cols[TS_KEY])[None, :]
            m = jnp.broadcast_to(self.cond(ev, {"xp": jnp}), (1, C))[0]
            match = match & m

        sel_cols = {k: v for k, v in cols.items()}
        sel_cols[VALID_KEY] = match
        sel_cols[TYPE_KEY] = jnp.zeros(C, jnp.int8)
        sel_cols[GK_KEY] = jnp.zeros(C, jnp.int32)

        plan = self.plan
        if self.group_fns is not None:
            from siddhi_tpu.core.query.runtime import GroupKeyer

            # fresh keyer per call: group ids sized to CURRENT contents
            keyer = GroupKeyer(self.group_fns)
            host_cols = {k: np.asarray(v) for k, v in sel_cols.items()}
            sel_cols[GK_KEY] = jnp.asarray(keyer(host_cols))
            plan.num_keys = max(16, len(keyer))

        if plan.needs_str_rank:
            # string order-by keys sort lexicographically, not by id
            from siddhi_tpu.core.plan.selector_plan import STR_RANK

            sel_cols[STR_RANK] = jnp.asarray(dictionary.rank_table())
        state = plan.init_state()
        _state, out = plan.apply(
            state, sel_cols, {"xp": jnp, "current_time": jnp.int64(0)})
        out_host = {k: np.asarray(v) for k, v in out.items()}
        return HostBatch(out_host).to_events(plan.output_attrs, dictionary)
