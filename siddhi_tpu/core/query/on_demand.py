"""On-demand (store) queries against tables and named windows.

Mirror of reference ``util/parser/OnDemandQueryParser.java`` (589 LoC of
find/insert/delete/update runtime assembly): the store's current contents
become one columnar batch, the `on` condition is a vectorized mask, and
the selector (aggregations, group by, having, order/limit) runs the same
device stage as streaming queries — recompiled per call shape, cached by
jit."""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from siddhi_tpu.compiler import SiddhiCompiler
from siddhi_tpu.core.event import CURRENT, Event, HostBatch
from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
from siddhi_tpu.core.plan.selector_plan import GK_KEY, plan_selector
from siddhi_tpu.core.table.in_memory_table import TBL_PREFIX, TableConditionResolver
from siddhi_tpu.ops.expressions import (
    TS_KEY,
    TYPE_KEY,
    VALID_KEY,
    CompileError,
    compile_condition,
)
from siddhi_tpu.query_api.execution import OnDemandQuery, ReturnStream


def _aggregation_contents(agg, oq: OnDemandQuery, dictionary):
    """Synthesize the stitched per-duration rows of an incremental
    aggregation as a columnar batch (reference OnDemandQuery `within ...
    per ...` against an aggregation)."""
    from siddhi_tpu.core.aggregation.incremental import parse_duration_name
    from siddhi_tpu.ops.types import dtype_of
    from siddhi_tpu.query_api.definitions import AttrType
    from siddhi_tpu.query_api.expressions import Constant, TimeConstant

    store = oq.input_store
    if store.per is None:
        raise CompileError(
            f"aggregation '{agg.definition.id}' queries need `per '<duration>'`")
    if not isinstance(store.per, Constant) or not isinstance(store.per.value, str):
        raise CompileError("`per` must be a duration string constant")
    duration = parse_duration_name(store.per.value)

    within = None
    w = store.within
    if w is not None:
        def _ms(x):
            if isinstance(x, (Constant, TimeConstant)) and not isinstance(
                getattr(x, "value", None), str
            ):
                return int(x.value)
            raise CompileError(
                "within bounds must be millisecond epoch constants "
                "(string date patterns are not supported yet)")

        if isinstance(w, tuple):
            within = (_ms(w[0]), _ms(w[1]))
        else:
            raise CompileError("within needs `start, end` bounds for aggregations")

    definition, cols, valid = agg.contents(duration, within)
    return definition, {k: jnp.asarray(v) for k, v in cols.items()}, jnp.asarray(valid)


def run_on_demand_query(source: str, app_runtime) -> List[Event]:
    oq: OnDemandQuery = SiddhiCompiler.parse_on_demand_query(source)
    store_id = oq.input_store.store_id
    dictionary = app_runtime.app_context.string_dictionary

    table = app_runtime.tables.get(store_id)
    window = app_runtime.named_windows.get(store_id)
    agg = app_runtime.aggregations.get(store_id)
    if table is not None:
        definition = table.definition
        cols, valid = table.contents()
    elif window is not None:
        definition = window.definition
        cols, valid = window.contents()
    elif agg is not None:
        definition, cols, valid = _aggregation_contents(agg, oq, dictionary)
    else:
        raise CompileError(f"'{store_id}' is not a defined table/window/aggregation")

    if oq.type != "find" or not isinstance(oq.output_stream, (ReturnStream, type(None))):
        raise CompileError(
            "only `select ... return`-style (find) on-demand queries are "
            "supported yet — stream-driven insert/delete/update cover mutation"
        )

    C = valid.shape[0]
    match = valid
    if oq.input_store.on_condition is not None:
        resolver = TableConditionResolver(definition, None, dictionary)
        cond = compile_condition(oq.input_store.on_condition, resolver)
        ev = {TBL_PREFIX + k: v[None, :] for k, v in cols.items()}
        ev[TS_KEY] = cols[TS_KEY][None, :]
        m = jnp.broadcast_to(cond(ev, {"xp": jnp}), (1, C))[0]
        match = match & m

    sel_cols = {k: v for k, v in cols.items()}
    sel_cols[VALID_KEY] = match
    sel_cols[TYPE_KEY] = jnp.zeros(C, jnp.int8)
    sel_cols[GK_KEY] = jnp.zeros(C, jnp.int32)

    sel_resolver = SingleStreamResolver(
        definition, dictionary, ref_id=oq.input_store.store_reference_id,
        synthetic={})
    plan = plan_selector(
        selector=oq.selector,
        input_attrs=[(a.name, a.type) for a in definition.attributes],
        resolver=sel_resolver,
        output_event_type="current",
        batch_mode=False,
        dictionary=dictionary,
    )
    if plan.group_by:
        # group ids from the key expressions over store contents (host side)
        from siddhi_tpu.core.query.runtime import GroupKeyer
        from siddhi_tpu.ops.expressions import compile_expr

        fns = [compile_expr(v, sel_resolver) for v in oq.selector.group_by_list]
        keyer = GroupKeyer(fns)
        host_cols = {k: np.asarray(v) for k, v in sel_cols.items()}
        sel_cols[GK_KEY] = jnp.asarray(keyer(host_cols))
        plan.num_keys = max(16, len(keyer))

    state = plan.init_state()
    _state, out = plan.apply(state, sel_cols, {"xp": jnp, "current_time": jnp.int64(0)})
    out_host = {k: np.asarray(v) for k, v in out.items()}
    return HostBatch(out_host).to_events(plan.output_attrs, dictionary)
